"""The metric catalog: every pwasm metric, registered in ONE place.

This module is the namespace of record for the fleet-facing metric
surface (documented operator-side in ``docs/OBSERVABILITY.md``).  The
static lint (``qa/check_supervision.py``, tier-1) enforces two rules
that keep it authoritative:

- every registration call (``registry.counter/gauge/histogram``) in
  ``pwasm_tpu/`` lives HERE — call sites elsewhere receive the built
  metric objects, never invent names inline;
- every name literal here matches the grammar (snake_case, ``pwasm_``
  prefix) and appears exactly once — a duplicate is a lint failure
  before it is a runtime ``ValueError``.

Two builders: :func:`build_run_metrics` (the per-run families — the
one-shot CLI registers them for ``--metrics-textfile``, and the serve
daemon registers the same families once and FOLDS every finished job's
``--stats`` JSON into them via :func:`fold_run_stats`, so the cumulative
fleet counters and the per-run stats schema cannot drift) and
:func:`build_service_metrics` (the daemon-only families: queue/admission
gauges, job outcome counters, wall/queue-wait histograms, the result
eviction counter).
"""

from __future__ import annotations

from pwasm_tpu.obs.metrics import MetricsRegistry

# histogram buckets for per-job queue wait (admission latency: instant
# under a drained queue, up to many job-walls when saturated)
_WAIT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0,
                 300.0)

# breaker-state gauge encoding (both surfaces use it; see
# docs/OBSERVABILITY.md)
BREAKER_CLOSED = 0
BREAKER_HALF_OPEN = 1
BREAKER_OPEN = 2


def breaker_state_value(breaker_open: bool,
                        monitor_state: str | None = None) -> int:
    """The gauge encoding of the breaker triple: 0 closed (device
    path live), 1 half-open (open but probing healthy — recovery in
    progress), 2 open (degraded to host)."""
    if not breaker_open:
        return BREAKER_CLOSED
    if monitor_state == "half-open":
        return BREAKER_HALF_OPEN
    return BREAKER_OPEN


def build_run_metrics(reg: MetricsRegistry,
                      include_live: bool = True) -> dict:
    """Register the per-run metric families; returns them keyed by the
    short names :func:`fold_run_stats` and the supervisor's observe
    hook use.  ``include_live=False`` (the serve daemon) registers
    only the foldable counters: the live instruments — the per-attempt
    histogram and the run breaker gauge — are fed by the RUN's own obs
    bundle, which a served job only has when it passed obs flags
    itself, and an advertised family that can never carry a sample
    would just feed no-data alerts."""
    m = {}
    if include_live:
        m["batch_attempt_seconds"] = reg.histogram(
            "pwasm_run_batch_attempt_seconds",
            "Wall seconds per supervised device-batch attempt, by "
            "site (one-shot CLI runs)", labels=("site",))
        m["breaker_state"] = reg.gauge(
            "pwasm_run_breaker_state",
            "Global circuit breaker: 0 closed, 1 half-open, 2 open "
            "(one-shot CLI runs)")
    m["runs"] = reg.counter(
        "pwasm_run_finished_total",
        "Finished runs folded into this registry, by outcome",
        labels=("outcome",))
    m["wall_seconds"] = reg.counter(
        "pwasm_run_wall_seconds_total",
        "Cumulative run wall seconds")
    m["alignments"] = reg.counter(
        "pwasm_run_alignments_total",
        "Alignments accepted for analysis")
    m["events"] = reg.counter(
        "pwasm_run_events_total", "Diff events reported")
    m["aligned_bases"] = reg.counter(
        "pwasm_run_aligned_bases_total",
        "Sum of per-alignment target span bases")
    m["host_stage_seconds"] = reg.counter(
        "pwasm_host_stage_seconds_total",
        "Cumulative host report-path stage wall seconds, by stage "
        "(parse/extract/analyze/format)", labels=("stage",))
    m["device_dispatches"] = reg.counter(
        "pwasm_device_dispatches_total", "Device program launches")
    m["device_flushes"] = reg.counter(
        "pwasm_device_flushes_total",
        "Host-blocking device round-trips")
    m["fallback_batches"] = reg.counter(
        "pwasm_device_fallback_batches_total",
        "Device batches replayed on the host scalar path")
    m["engine_fallbacks"] = reg.counter(
        "pwasm_engine_fallbacks_total",
        "Engine/device stage demotions in the MSA consensus path")
    m["backend_probes"] = reg.counter(
        "pwasm_backend_probes_total",
        "Bounded subprocess backend probes paid")
    m["backend_warm_hits"] = reg.counter(
        "pwasm_backend_warm_hits_total",
        "Backend reachability checks answered from warm state")
    m["retries"] = reg.counter(
        "pwasm_resilience_retries_total",
        "Re-executed supervised device attempts")
    m["fallbacks"] = reg.counter(
        "pwasm_resilience_fallbacks_total",
        "Supervised batches degraded to the host path")
    m["guardrail_rejects"] = reg.counter(
        "pwasm_resilience_guardrail_rejects_total",
        "Device outputs rejected as corrupt by guardrails")
    m["deadline_timeouts"] = reg.counter(
        "pwasm_resilience_deadline_timeouts_total",
        "Attempts abandoned past --device-deadline")
    m["breaker_trips"] = reg.counter(
        "pwasm_breaker_trips_total",
        "Global breaker opens (probe-confirmed dead backend)")
    m["site_breaker_trips"] = reg.counter(
        "pwasm_site_breaker_trips_total",
        "Per-site breaker opens on a healthy backend")
    m["breaker_recloses"] = reg.counter(
        "pwasm_breaker_recloses_total",
        "Global breaker recloses (mid-run device re-promotion)")
    m["reprobe_attempts"] = reg.counter(
        "pwasm_reprobe_attempts_total",
        "Bounded backend re-probes while the breaker was open")
    m["degraded_batches"] = reg.counter(
        "pwasm_degraded_batches_total",
        "Batches skipped straight to the host (breaker open)")
    m["recovered_batches"] = reg.counter(
        "pwasm_recovered_batches_total",
        "Device batches executed after a reclose")
    m["degraded_wall_seconds"] = reg.counter(
        "pwasm_degraded_wall_seconds_total",
        "Wall seconds spent with the global breaker open")
    m["injected_faults"] = reg.counter(
        "pwasm_injected_faults_total",
        "Faults injected by --inject-faults (debug)")
    m["checkpoints"] = reg.counter(
        "pwasm_checkpoints_total",
        "Durable batch checkpoints written")
    m["oom_events"] = reg.counter(
        "pwasm_oom_events_total",
        "Device allocation failures (real or injected)")
    m["batch_splits"] = reg.counter(
        "pwasm_batch_splits_total", "Batches bisected after an OOM")
    m["bucket_demotions"] = reg.counter(
        "pwasm_bucket_demotions_total",
        "Pow2 batch-ceiling demotions after an OOM")
    m["bucket_repromotions"] = reg.counter(
        "pwasm_bucket_repromotions_total",
        "Probation-raises of a demoted batch ceiling")
    # trace health (ISSUE 11 satellite): drops surfaced live, not only
    # in otherData at write time (fed by TraceRecorder.on_drop)
    m["trace_dropped"] = reg.counter(
        "pwasm_trace_events_dropped_total",
        "Trace events dropped past --trace-max-events (or a "
        "contended recorder lock)")
    # utilization accounting (ISSUE 11): pow2 padding waste and the
    # compile-vs-steady device wall split, folded from the --stats
    # device block; the ratio gauges are derived from the cumulative
    # counters at fold time
    m["pad_items"] = reg.counter(
        "pwasm_device_pad_items_total",
        "Live event rows launched in pow2-padded device batches")
    m["pad_slots"] = reg.counter(
        "pwasm_device_pad_slots_total",
        "Total slots (live + pad) launched in pow2-padded device "
        "batches")
    m["pad_waste"] = reg.gauge(
        "pwasm_device_pad_waste_ratio",
        "Fraction of launched device-batch slots that were pow2 "
        "bucket padding (cumulative; 0 = perfectly full buckets)")
    m["compile_seconds"] = reg.counter(
        "pwasm_device_compile_seconds_total",
        "Wall seconds of each supervised site's FIRST attempt "
        "(compile-inclusive)")
    m["steady_seconds"] = reg.counter(
        "pwasm_device_steady_seconds_total",
        "Wall seconds of supervised attempts after a site's first "
        "(steady-state, compile-cache warm)")
    m["compile_fraction"] = reg.gauge(
        "pwasm_device_compile_fraction",
        "Compile-inclusive fraction of supervised device wall "
        "(cumulative compile / (compile + steady))")
    return m


def build_service_metrics(reg: MetricsRegistry) -> dict:
    """Register the serve-daemon families (queue, admission, job
    outcomes, result eviction) keyed by short names the daemon uses."""
    m = {}
    m["queue_depth"] = reg.gauge(
        "pwasm_service_queue_depth", "Jobs waiting in the admission queue")
    m["inflight"] = reg.gauge(
        "pwasm_service_jobs_inflight", "Jobs currently executing")
    m["draining"] = reg.gauge(
        "pwasm_service_draining",
        "1 while the service drain is latched, else 0")
    m["breaker_state"] = reg.gauge(
        "pwasm_service_breaker_state",
        "Warm-pool breaker: 0 closed, 1 half-open, 2 open")
    m["max_queue"] = reg.gauge(
        "pwasm_service_max_queue", "Admission-control queue ceiling")
    m["max_concurrent"] = reg.gauge(
        "pwasm_service_max_concurrent", "Worker-pool width")
    m["results_held"] = reg.gauge(
        "pwasm_service_results_held",
        "Terminal job results currently retained for pickup")
    # device-lease scheduler (ISSUE 8): lane inventory + wait surface
    m["lanes"] = reg.gauge(
        "pwasm_service_lanes",
        "Device-lease lanes the daemon schedules jobs onto")
    m["lanes_busy"] = reg.gauge(
        "pwasm_service_lanes_busy", "Lanes currently leased to a job")
    m["lease_waiting"] = reg.gauge(
        "pwasm_service_lease_waiting_jobs",
        "Dequeued jobs waiting for a free device lease")
    m["lane_breaker_state"] = reg.gauge(
        "pwasm_service_lane_breaker_state",
        "Per-lane breaker: 0 closed, 1 half-open, 2 open",
        labels=("lane",))
    m["lane_jobs"] = reg.counter(
        "pwasm_service_lane_jobs_total",
        "Jobs completed per device-lease lane", labels=("lane",))
    m["lane_busy_fraction"] = reg.gauge(
        "pwasm_service_lane_busy_fraction",
        "Fraction of the daemon's uptime each lane spent leased to a "
        "job (per-lane device busy-fraction)", labels=("lane",))
    m["lease_wait_seconds"] = reg.histogram(
        "pwasm_service_lease_wait_seconds",
        "Per-job device-lease wait seconds (dequeue to grant)",
        buckets=_WAIT_BUCKETS)
    # crash-safe serving (ISSUE 9): journal, spool, fair-share
    m["journal_records"] = reg.counter(
        "pwasm_service_journal_records_total",
        "Durable job-journal records appended, by record type "
        "(admit/start/finish/cancel/evict)", labels=("rec",))
    m["journal_replays"] = reg.counter(
        "pwasm_service_journal_replays_total",
        "Journal replays performed at daemon start (each one is a "
        "recovery from a hard crash)")
    m["spool_bytes"] = reg.gauge(
        "pwasm_service_spool_bytes",
        "Bytes of finished-job results spooled to disk "
        "(RAM holds only index entries for these)")
    m["client_queue_depth"] = reg.gauge(
        "pwasm_service_client_queue_depth",
        "Queued jobs per fair-share client identity",
        labels=("client",))
    m["jobs"] = reg.counter(
        "pwasm_service_jobs_total",
        "Job admissions and outcomes, by outcome "
        "(accepted/rejected/rejected_draining/done/failed/"
        "preempted/cancelled)", labels=("outcome",))
    m["results_evicted"] = reg.counter(
        "pwasm_service_results_evicted_total",
        "Terminal job results evicted by --result-ttl-s/--max-results")
    m["job_wall_seconds"] = reg.histogram(
        "pwasm_service_job_wall_seconds",
        "Per-job wall seconds (start to finish)")
    m["queue_wait_seconds"] = reg.histogram(
        "pwasm_service_job_queue_wait_seconds",
        "Per-job queue wait seconds (submit to start)",
        buckets=_WAIT_BUCKETS)
    # epoch-lease fencing (ISSUE 16, fleet/fencing.py): a router-
    # governed member's split-brain guards
    m["fenced"] = reg.gauge(
        "pwasm_service_fenced",
        "1 while this member is fenced (lost/expired epoch lease: "
        "new work refused, in-flight drained to checkpoints), else 0")
    m["member_epoch"] = reg.gauge(
        "pwasm_service_member_epoch",
        "Highest fleet epoch this member has accepted a lease under "
        "(monotonic; compare with pwasm_fleet_epoch to spot a member "
        "heartbeating a stale router)")
    m["fences"] = reg.counter(
        "pwasm_service_fences_total",
        "Times this member self-fenced (lease TTL expiry or an "
        "explicit fence command) — each one is a suspected "
        "router-side failover where this member was the zombie")
    return m


def build_stream_metrics(reg: MetricsRegistry) -> dict:
    """Register the streaming-ingestion families (ISSUE 10), labeled
    by the stream's fair-share client identity.  ``records``/``batches``
    count what actually flowed; ``lag`` is the live fed-but-unconsumed
    buffer depth per client — the "is a producer outrunning its
    consumer" pressure signal the per-stream quota acts on."""
    m = {}
    m["records"] = reg.counter(
        "pwasm_stream_records_total",
        "PAF records accepted over stream-data frames, by client",
        labels=("client",))
    m["batches"] = reg.counter(
        "pwasm_stream_batches_total",
        "Arrival batches drained from stream buffers by executing "
        "jobs, by client", labels=("client",))
    m["lag"] = reg.gauge(
        "pwasm_stream_lag_records",
        "Records fed to a stream but not yet consumed by its job, "
        "by client", labels=("client",))
    m["lag_age"] = reg.gauge(
        "pwasm_stream_lag_age_seconds",
        "Age of the oldest fed-but-unconsumed stream record, by "
        "client (how STALE the lag is, where lag_records says how "
        "deep)", labels=("client",))
    return m


def build_m2m_metrics(reg: MetricsRegistry) -> dict:
    """Register the continuous-surveillance families (ISSUE 20,
    ``--m2m-stream``): counters fold each FINISHED session's flow
    (the daemon reads them from the session's ``--stats`` m2m block),
    gauges describe the live ones — the svc-stats ``m2m`` block and
    the ``top`` M2M pane read the same numbers."""
    m = {}
    m["sessions"] = reg.counter(
        "pwasm_m2m_sessions_total",
        "Finished --m2m-stream surveillance sessions")
    m["targets_in"] = reg.counter(
        "pwasm_m2m_targets_total",
        "Target records admitted by finished m2m-stream sessions")
    m["targets_scored"] = reg.counter(
        "pwasm_m2m_targets_scored_total",
        "Targets that needed at least one device dispatch (some "
        "resident pair was not in the section cache)")
    m["targets_reused"] = reg.counter(
        "pwasm_m2m_targets_reused_total",
        "Targets served ENTIRELY from the section cache's family "
        "pool — zero device work")
    m["pairs_dispatched"] = reg.counter(
        "pwasm_m2m_pairs_dispatched_total",
        "(query, target) pairs scored on the device by m2m-stream "
        "sessions")
    m["pairs_reused"] = reg.counter(
        "pwasm_m2m_pairs_reused_total",
        "(query, target) pairs spliced verbatim from cached section "
        "scores instead of dispatched")
    m["batches"] = reg.counter(
        "pwasm_m2m_batches_total",
        "Arrival batches dispatched by m2m-stream sessions")
    m["sections"] = reg.counter(
        "pwasm_m2m_sections_emitted_total",
        "Per-CDS report sections emitted by finished m2m-stream "
        "sessions")
    m["active"] = reg.gauge(
        "pwasm_m2m_active_sessions",
        "Live m2m-stream sessions currently feeding or scoring")
    m["live_targets"] = reg.gauge(
        "pwasm_m2m_live_targets",
        "Targets admitted so far by the LIVE sessions (in-flight "
        "progress, not yet folded into the counters)")
    m["reuse_ratio"] = reg.gauge(
        "pwasm_m2m_reuse_ratio",
        "Cumulative fraction of (query, target) pairs served from "
        "the section cache across finished AND live sessions — the "
        "incremental-surveillance win in one number")
    return m


def build_cache_metrics(reg: MetricsRegistry) -> dict:
    """Register the content-addressed result-cache families (ISSUE
    15, ``service/cache.py``): flow counters (hits/misses/insertions/
    evictions), the live on-disk byte gauge (fed from the unified
    :class:`~pwasm_tpu.service.cache.ByteLedger`, so it cannot drift
    from the spool gauge's accounting), and the cumulative hit-ratio
    gauge the capacity-planning dashboards read.  Registered by the
    one-shot CLI (``--result-cache`` + ``--metrics-textfile``), the
    serve daemon, and the fleet router — each over its own registry."""
    m = {}
    m["hits"] = reg.counter(
        "pwasm_cache_hits_total",
        "Result-cache hits (jobs served from stored bytes with zero "
        "device/lease/queue involvement)")
    m["misses"] = reg.counter(
        "pwasm_cache_misses_total",
        "Result-cache lookups that found no whole, unexpired, "
        "CRC-clean entry")
    m["insertions"] = reg.counter(
        "pwasm_cache_insertions_total",
        "Completed jobs whose outputs were stored in the result cache")
    m["insert_errors"] = reg.counter(
        "pwasm_cache_insert_errors_total",
        "Result-cache inserts that failed and degraded to "
        "pass-through (ENOSPC and kin): the job was served from its "
        "real run, only the cache write was skipped")
    m["evictions"] = reg.counter(
        "pwasm_cache_evictions_total",
        "Result-cache entries dropped (LRU past "
        "--result-cache-max-bytes, TTL expiry, or CRC rot)")
    m["bytes"] = reg.gauge(
        "pwasm_cache_bytes",
        "Bytes of result-cache entries currently on disk")
    m["hit_ratio"] = reg.gauge(
        "pwasm_cache_hit_ratio",
        "Cumulative result-cache hit ratio ((hits + fractional delta "
        "serves) / lookups) — a delta serve counts records-served / "
        "records-total of a hit, so incremental traffic moves the "
        "ratio truthfully instead of reading as pure misses")
    m["delta_hits"] = reg.counter(
        "pwasm_cache_delta_hits_total",
        "Near-miss DELTA serves (ISSUE 17): jobs whose exact lookup "
        "missed but whose cached same-family prefix (or m2m target "
        "subset) was spliced in, recomputing only the tail")
    return m


def build_fleet_metrics(reg: MetricsRegistry) -> dict:
    """Register the fleet-router families (the ``pwasm-tpu route``
    daemon, docs/FLEET.md): member liveness and load as the router
    sees it, placement and failover counters, and the global
    fair-share ledger's per-client live-job gauge.  Labeled by the
    sanitized member name (``fleet/transport.py::target_name``) —
    the same identity the shared-journal placement policy uses."""
    m = {}
    m["members"] = reg.gauge(
        "pwasm_fleet_members",
        "Member serve daemons this router fronts")
    m["member_up"] = reg.gauge(
        "pwasm_fleet_member_up",
        "Member liveness as the router's health loop sees it "
        "(1 up, 0 down)", labels=("member",))
    m["member_queue_depth"] = reg.gauge(
        "pwasm_fleet_member_queue_depth",
        "Queued + running jobs per member at the last stats poll",
        labels=("member",))
    m["live_jobs"] = reg.gauge(
        "pwasm_fleet_jobs_live",
        "Routed jobs not yet terminal anywhere in the fleet")
    m["client_jobs"] = reg.gauge(
        "pwasm_fleet_client_jobs",
        "Live fleet-wide jobs per fair-share client identity (the "
        "global ledger the fleet quota is enforced against)",
        labels=("client",))
    m["routed"] = reg.counter(
        "pwasm_fleet_jobs_routed_total",
        "Jobs placed per member (least-loaded placement)",
        labels=("member",))
    m["jobs"] = reg.counter(
        "pwasm_fleet_jobs_total",
        "Router admissions by outcome (accepted/rejected)",
        labels=("outcome",))
    m["failovers"] = reg.counter(
        "pwasm_fleet_failovers_total",
        "Member-death events the router handled (each one is a "
        "journal-aware failover pass)")
    m["recovered"] = reg.counter(
        "pwasm_fleet_jobs_recovered_total",
        "Jobs recovered from dead members, by verdict (resumed/"
        "requeued/restored/cancelled/stream_preempted/failed)",
        labels=("how",))
    m["max_jobs"] = reg.gauge(
        "pwasm_fleet_max_jobs",
        "Fleet-wide live-job backstop (--max-queue-total) — the "
        "ledger_saturation SLO rule's denominator")
    # router HA (ISSUE 16): WAL, standby takeover, fencing, scaler
    m["epoch"] = reg.gauge(
        "pwasm_fleet_epoch",
        "Current fleet epoch (monotonic fencing token: bumped on "
        "every router restart/takeover and every member-death "
        "failover; members accept work only under a lease at it)")
    m["fenced_members"] = reg.gauge(
        "pwasm_fleet_members_fenced",
        "Reachable members currently reporting themselves fenced "
        "(self-fenced zombies waiting for a fresh lease)")
    m["takeovers"] = reg.counter(
        "pwasm_fleet_takeovers_total",
        "Warm-standby takeovers this router performed (route "
        "--standby-of promoted itself onto the primary's socket)")
    m["journal_records"] = reg.counter(
        "pwasm_fleet_journal_records_total",
        "Router write-ahead journal records appended, by record type "
        "(route_admit/route_place/route_retire/epoch/members/scale)",
        labels=("rec",))
    m["journal_replayed"] = reg.counter(
        "pwasm_fleet_journal_replayed_total",
        "Routed jobs rebuilt from the router WAL at start (each "
        "replay is a router crash or a standby takeover recovered)")
    m["scaler_members"] = reg.gauge(
        "pwasm_fleet_scaler_members",
        "Members currently alive that the SLO-driven scaler spawned "
        "(route --scale-policy)")
    m["scaler_actions"] = reg.counter(
        "pwasm_fleet_scaler_actions_total",
        "Auto-scaler actions taken, by action (spawn/retire)",
        labels=("action",))
    m["stale_rejected"] = reg.counter(
        "pwasm_fleet_stale_completions_total",
        "Terminal replies rejected at the router edge because the "
        "job had moved to a newer generation (a fenced zombie's "
        "completion arriving after failover re-placed the job)")
    # gray-failure defense (ISSUE 18): latency-outlier quarantine +
    # brownout shedding
    m["member_latency_ewma"] = reg.gauge(
        "pwasm_fleet_member_latency_ewma_ms",
        "EWMA of the member's health-poll round-trip latency in "
        "milliseconds — the slow-member outlier detector's input "
        "(a member sustaining >K x the fleet median is quarantined)",
        labels=("member",))
    m["member_quarantined"] = reg.gauge(
        "pwasm_fleet_member_quarantined",
        "1 while the member is quarantined as a latency outlier "
        "(alive but degraded: no new placements, existing jobs "
        "finish, probation-exits after clean polls), else 0",
        labels=("member",))
    m["quarantines"] = reg.counter(
        "pwasm_fleet_quarantines_total",
        "Quarantine entries (a live member crossed the latency "
        "outlier threshold) — each one is a gray failure the router "
        "routed around without a human",)
    m["shed"] = reg.counter(
        "pwasm_fleet_jobs_shed_total",
        "Admissions shed at the router edge under brownout (fleet "
        "queue pressure past the SLO threshold): answered a truthful "
        "overloaded + retry_after_s, lowest priority lane first, "
        "before any member saw the frame", labels=("lane",))
    m["shedding"] = reg.gauge(
        "pwasm_fleet_shedding",
        "1 while brownout shedding is active (hysteresis-damped), "
        "else 0")
    return m


def build_slo_metrics(reg: MetricsRegistry) -> dict:
    """Register the SLO-engine families (ISSUE 14): one firing gauge
    per rule (0/1 — every configured rule keeps a series from start,
    so an absent series is a scrape gap, never 'healthy') and the
    firing/resolved transition counter the incident timeline keys on.
    Registered by BOTH the serve daemon and the fleet router (each
    over its own registry and rule set)."""
    m = {}
    m["firing"] = reg.gauge(
        "pwasm_alerts_firing",
        "1 while the named SLO rule is firing, else 0 (obs/slo.py; "
        "rule catalog in docs/OBSERVABILITY.md)", labels=("rule",))
    m["transitions"] = reg.counter(
        "pwasm_alert_transitions_total",
        "SLO rule state transitions, by rule and state "
        "(firing/resolved)", labels=("rule", "state"))
    return m


def build_canary_metrics(reg: MetricsRegistry) -> dict:
    """Register the synthetic-canary families (service/canary.py,
    ``serve --canary-interval``): the last probe's verdict, the probe
    wall histogram (exemplar-linked to each probe's trace_id), and
    the run counter by outcome."""
    m = {}
    m["ok"] = reg.gauge(
        "pwasm_canary_ok",
        "1 if the last synthetic canary probe passed (rc 0 + golden "
        "report digest), 0 if it failed — unset until the first probe")
    m["wall_seconds"] = reg.histogram(
        "pwasm_canary_wall_seconds",
        "Wall seconds per synthetic canary probe (the full "
        "probe->lease->device->report path)")
    m["runs"] = reg.counter(
        "pwasm_canary_runs_total",
        "Synthetic canary probes, by outcome (ok/fail/skipped — "
        "skipped means no free lane within the grab timeout)",
        labels=("outcome",))
    return m


def build_transport_metrics(reg: MetricsRegistry) -> dict:
    """Register the zero-trust edge families (ISSUE 19): TLS
    handshake failures on the listener (downgrade probes, bad certs,
    mid-handshake disconnects — counted, never fatal to the accept
    loop) and per-client authorization refusals (the signal the
    ``auth_failure_burst`` SLO rule and the connection-level penalty
    box key on).  Registered by both the serve daemon and the fleet
    router over their own registries."""
    m = {}
    m["tls_handshake_failures"] = reg.counter(
        "pwasm_transport_tls_handshake_failures_total",
        "TLS handshakes that failed on the listener (plaintext "
        "probes, protocol downgrades below the TLS1.2 floor, "
        "untrusted or missing client certs under mTLS, mid-handshake "
        "disconnects) — each answered with a loud close, never a "
        "hang or an accept-loop crash")
    m["auth_failures"] = reg.counter(
        "pwasm_transport_auth_failures_total",
        "Frames refused with the `unauthorized` error, by resolved "
        "client identity (distinct label values are capped; overflow "
        "folds into `other`) — refusals change no queue/journal "
        "state and repeated failures earn a capped-exponential "
        "connection delay", labels=("client",))
    return m


# metric-name-lint: end-of-registrations (everything below REFERENCES
# registered families — SLO rule expressions — and is excluded from
# the registration-uniqueness scan in qa/check_supervision.py)
# ---------------------------------------------------------------------------
# Default SLO rule sets (ISSUE 14): the alert sketches that lived as
# prose in docs/OBSERVABILITY.md, codified as declarative rules the
# engine (obs/slo.py) evaluates continuously.  Every rule name below
# must appear in docs/OBSERVABILITY.md — enforced by the doc-drift
# lint (qa/check_supervision.py::find_doc_drift), same contract as
# the metric families.  User rules (serve/route --slo-rules=FILE)
# merge over these by name.
# ---------------------------------------------------------------------------

# the serve daemon's default rules, evaluated over its own registry
DEFAULT_SLO_RULES = (
    {"name": "breaker_open", "severity": "page", "kind": "threshold",
     "metric": "pwasm_service_breaker_state", "op": ">=", "value": 2,
     "for_s": 0.0,
     "runbook": "a lane's device backend is probe-confirmed dead and "
                "jobs are degrading to the host path; check the lane "
                "table in `pwasm-tpu top` and the chip"},
    {"name": "queue_pressure", "severity": "warn",
     "kind": "threshold", "metric": "pwasm_service_queue_depth",
     "divide_by": "pwasm_service_max_queue", "op": ">", "value": 0.8,
     "for_s": 5.0,
     "runbook": "admission queue is over 80% of one client quota; "
                "add members or raise --max-queue"},
    {"name": "journal_replay", "severity": "warn", "kind": "rate",
     "metric": "pwasm_service_journal_replays_total", "op": ">",
     "value": 0, "window_s": 300.0, "baseline": "zero",
     "runbook": "this daemon replayed its job journal within the "
                "window — it recovered from a hard crash; find out "
                "what killed it"},
    {"name": "trace_drops", "severity": "warn", "kind": "rate",
     "metric": "pwasm_trace_events_dropped_total", "op": ">",
     "value": 0, "window_s": 300.0,
     "runbook": "trace events are being dropped past "
                "--trace-max-events; raise the cap or trace less"},
    {"name": "canary_failing", "severity": "page",
     "kind": "threshold", "metric": "pwasm_canary_ok", "op": "==",
     "value": 0, "for_s": 0.0,
     "runbook": "the synthetic canary probe failed (bad rc or report "
                "digest drift): the submit->lease->device->report "
                "path is broken end to end — check canary_fail "
                "events via `pwasm-tpu logs`"},
    {"name": "job_wall_p99_burn", "severity": "warn",
     "kind": "burn_rate", "metric": "pwasm_service_job_wall_seconds",
     "objective_s": 120.0, "budget": 0.01, "short_s": 60.0,
     "long_s": 300.0, "burn": 1.0,
     "runbook": "more than 1% of jobs exceeded the 120s wall "
                "objective in both burn windows; inspect a slow "
                "job's flight record (`pwasm-tpu inspect JOB_ID`)"},
    {"name": "queue_wait_burn", "severity": "warn",
     "kind": "burn_rate",
     "metric": "pwasm_service_job_queue_wait_seconds",
     "objective_s": 60.0, "budget": 0.05, "short_s": 60.0,
     "long_s": 300.0, "burn": 1.0,
     "runbook": "over 5% of jobs waited more than 60s for admission "
                "in both burn windows — sustained overload; scale "
                "members out"},
    {"name": "cache_thrash", "severity": "warn", "kind": "threshold",
     "metric": "pwasm_cache_evictions_total",
     "divide_by": "pwasm_cache_insertions_total", "op": ">",
     "value": 0.9, "for_s": 10.0,
     "runbook": "the result cache is evicting nearly as fast as it "
                "inserts (sustained evictions/insertions > 0.9): a "
                "mis-sized --result-cache-max-bytes silently costs "
                "every repeat job its 100x hit — raise the budget or "
                "shrink the retained output set"},
    # zero-trust edge (ISSUE 19): a burst of unauthorized refusals
    # is either a misdeployed credential (a rotated token the client
    # fleet never picked up) or someone probing the control plane —
    # both want a human within the window.
    {"name": "auth_failure_burst", "severity": "warn", "kind": "rate",
     "metric": "pwasm_transport_auth_failures_total", "op": ">",
     "value": 10, "window_s": 60.0,
     "runbook": "more than 10 frames answered `unauthorized` within "
                "the window; a legitimate client is holding a stale "
                "token (rotate via --auth-tokens hot reload) or a "
                "peer is probing scopes — the penalty box is already "
                "damping it, check the per-client labels on "
                "pwasm_transport_auth_failures_total"},
)

# the fleet router's default rules, over the pwasm_fleet_* families
DEFAULT_FLEET_SLO_RULES = (
    {"name": "member_down", "severity": "page", "kind": "threshold",
     "metric": "pwasm_fleet_member_up", "op": "==", "value": 0,
     "for_s": 0.0,
     "runbook": "a member serve daemon is unreachable (failover ran "
                "or is running); check the member host and restart "
                "it WITHOUT its set-aside .recovered journal"},
    {"name": "failover_burst", "severity": "warn", "kind": "rate",
     "metric": "pwasm_fleet_failovers_total", "op": ">", "value": 0,
     "window_s": 300.0,
     "runbook": "the router handled member-death failover(s) within "
                "the window; if members are flapping, fix the hosts "
                "before the fleet runs out of siblings"},
    {"name": "ledger_saturation", "severity": "warn",
     "kind": "threshold", "metric": "pwasm_fleet_jobs_live",
     "divide_by": "pwasm_fleet_max_jobs", "op": ">", "value": 0.8,
     "for_s": 5.0,
     "runbook": "fleet-wide live jobs are over 80% of the admission "
                "backstop; clients will start seeing queue_full — "
                "add members or raise route --max-queue-total"},
    {"name": "member_fenced", "severity": "warn", "kind": "threshold",
     "metric": "pwasm_fleet_members_fenced", "op": ">", "value": 0,
     "for_s": 0.0,
     "runbook": "a reachable member is self-fenced (it lost its epoch "
                "lease and is refusing work); the next healthy stats "
                "poll re-grants the lease — if it stays fenced, the "
                "member is heartbeating a stale router: check for a "
                "zombie primary still holding the journal"},
    # gray-failure defense (ISSUE 18): the brownout trigger — member
    # queues saturated fleet-wide.  The router's shedding keys off
    # this rule (or ledger_saturation) firing; hysteresis lives in
    # the shed controller, the rule just states the pressure truth.
    {"name": "fleet_queue_pressure", "severity": "warn",
     "kind": "threshold",
     "metric": "pwasm_fleet_member_queue_depth", "op": ">",
     "value": 8, "for_s": 1.0,
     "runbook": "a member's queued+running depth is sustained past "
                "the brownout threshold; under --priority-lanes the "
                "router sheds the lowest lane with a truthful "
                "overloaded + retry_after_s until pressure clears — "
                "add members (or let --scale-policy spawn them) if "
                "it keeps firing"},
    {"name": "member_quarantined", "severity": "warn",
     "kind": "threshold",
     "metric": "pwasm_fleet_member_quarantined", "op": ">",
     "value": 0, "for_s": 0.0,
     "runbook": "a live member is a sustained latency outlier (>K x "
                "the fleet median poll round-trip) and is quarantined "
                "from new placements; it probation-exits by itself "
                "after clean polls — investigate the host (slow disk, "
                "GC stalls, half-partition) if it cycles in and out"},
)


def default_slo_rules() -> list[dict]:
    """The serve daemon's default rule set (fresh copies — the engine
    normalizes in place)."""
    return [dict(r) for r in DEFAULT_SLO_RULES]


def default_fleet_slo_rules() -> list[dict]:
    """The fleet router's default rule set."""
    return [dict(r) for r in DEFAULT_FLEET_SLO_RULES]


def fold_run_stats(m: dict, st: dict | None) -> None:
    """Fold one run's ``--stats`` JSON (the versioned ``stats_version``
    schema) into the run-metric families.  The one-shot CLI calls it
    once at end of run; the daemon calls it per finished job — so the
    Prometheus surface is a pure function of the same schema the
    ``--stats``/``svc-stats`` surfaces report, and the two cannot
    drift.  Unknown/missing keys fold as zero (additive-schema rule)."""
    if not isinstance(st, dict):
        return

    def n(d: dict, key: str) -> float:
        v = d.get(key, 0)
        return v if isinstance(v, (int, float)) \
            and not isinstance(v, bool) and v > 0 else 0

    res = st.get("resilience")
    res = res if isinstance(res, dict) else {}
    backend = st.get("backend")
    backend = backend if isinstance(backend, dict) else {}
    device = st.get("device")
    device = device if isinstance(device, dict) else {}
    m["runs"].inc(1, outcome="preempted" if st.get("preempted")
                  else "completed")
    m["wall_seconds"].inc(n(st, "wall_s"))
    m["alignments"].inc(n(st, "alignments"))
    m["events"].inc(n(st, "events"))
    m["aligned_bases"].inc(n(st, "aligned_bases"))
    m["device_dispatches"].inc(n(device, "dispatches"))
    m["device_flushes"].inc(n(device, "flushes"))
    # utilization accounting (ISSUE 11): fold the pad/compile counters
    # and derive the ratio gauges from the CUMULATIVE totals, so the
    # gauges describe the registry's whole history (a daemon's life),
    # not just the last folded run
    m["pad_items"].inc(n(device, "pad_items"))
    m["pad_slots"].inc(n(device, "pad_slots"))
    slots = m["pad_slots"].value()
    if slots > 0:
        m["pad_waste"].set(
            round(1.0 - m["pad_items"].value() / slots, 6))
    m["compile_seconds"].inc(n(device, "compile_s"))
    m["steady_seconds"].inc(n(device, "steady_s"))
    dev_wall = m["compile_seconds"].value() \
        + m["steady_seconds"].value()
    if dev_wall > 0:
        m["compile_fraction"].set(
            round(m["compile_seconds"].value() / dev_wall, 6))
    host = st.get("host")
    host = host if isinstance(host, dict) else {}
    for stage in ("parse", "extract", "analyze", "format"):
        m["host_stage_seconds"].inc(n(host, stage + "_s"),
                                    stage=stage)
    m["fallback_batches"].inc(n(st, "fallback_batches"))
    m["engine_fallbacks"].inc(n(st, "engine_fallbacks"))
    m["backend_probes"].inc(n(backend, "probes"))
    m["backend_warm_hits"].inc(n(backend, "warm_hits"))
    m["retries"].inc(n(res, "retries"))
    m["fallbacks"].inc(n(res, "fallbacks"))
    m["guardrail_rejects"].inc(n(res, "guardrail_rejects"))
    m["deadline_timeouts"].inc(n(res, "deadline_timeouts"))
    m["breaker_trips"].inc(n(res, "breaker_trips"))
    m["site_breaker_trips"].inc(n(res, "site_breaker_trips"))
    m["breaker_recloses"].inc(n(res, "breaker_recloses"))
    m["reprobe_attempts"].inc(n(res, "reprobe_attempts"))
    m["degraded_batches"].inc(n(res, "degraded_batches"))
    m["recovered_batches"].inc(n(res, "recovered_batches"))
    m["degraded_wall_seconds"].inc(n(res, "degraded_wall_s"))
    m["injected_faults"].inc(n(res, "injected_faults"))
    m["checkpoints"].inc(n(res, "checkpoints"))
    m["oom_events"].inc(n(res, "oom_events"))
    m["batch_splits"].inc(n(res, "batch_splits"))
    m["bucket_demotions"].inc(n(res, "bucket_demotions"))
    m["bucket_repromotions"].inc(n(res, "bucket_repromotions"))
