"""Thread-safe metrics registry with Prometheus text exposition.

The fleet-facing half of the observability surface (ISSUE 6): counters,
gauges and fixed-bucket histograms registered once in a
:class:`MetricsRegistry` and rendered in the Prometheus text exposition
format (version 0.0.4 — the `# HELP` / `# TYPE` / sample-line grammar
every scraper and the node-exporter textfile collector speak).  Two
transports surface it: the serve daemon's ``metrics`` protocol command
(scraped over the unix socket) and the ``--metrics-textfile=PATH``
option (written atomically through ``utils.fsio`` so a collector never
reads a torn file).

Deliberately jax-free (gated by ``qa/check_supervision.py``, same rule
as ``pwasm_tpu/service/``) and stdlib-only: observability must be
importable — and cheap — on the plain-CPU path that never initializes
a backend.

Naming is linted statically (``qa/check_supervision.py``): every
metric name is snake_case with the ``pwasm_`` prefix, and every
registration lives in ``obs/catalog.py`` so the catalog IS the
namespace — duplicate registration raises here at runtime and fails
the lint at review time.
"""

from __future__ import annotations

import re
import threading
import time

# the linted grammar: pwasm_ prefix, lower-snake-case throughout
NAME_RE = re.compile(r"^pwasm_[a-z0-9]+(_[a-z0-9]+)*$")
LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# default histogram buckets for wall-clock seconds (jobs/batches span
# milliseconds on the host path to minutes on a cold device compile)
DEFAULT_TIME_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                        1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


def _fmt_num(v) -> str:
    """One canonical number rendering: ints bare, integral floats as
    ints (Prometheus treats 3 and 3.0 identically; bare ints diff
    cleaner in tests), everything else via repr (shortest round-trip)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(names: tuple, values: tuple) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(str(v))}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


class _Metric:
    """Base: one metric family (name + help + label names), holding one
    value cell per observed label-value tuple.  All mutation goes
    through the family lock — the daemon's worker threads and the
    accept loop update concurrently."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labels: tuple[str, ...] = ()):
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} violates the lint grammar "
                "(snake_case, pwasm_ prefix)")
        for lb in labels:
            if not LABEL_RE.match(lb):
                raise ValueError(f"bad label name {lb!r} on {name}")
        self.name = name
        self.help_text = help_text
        self.labels = tuple(labels)
        self._lock = threading.Lock()
        self._cells: dict[tuple, object] = {}

    def _values(self, labels: dict) -> tuple:
        if set(labels) != set(self.labels):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labels)}")
        return tuple(str(labels[n]) for n in self.labels)

    def expose(self, exemplars: bool = False) -> list[str]:
        out = [f"# HELP {self.name} {_escape_help(self.help_text)}",
               f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            # snapshot INSIDE the lock (including mutable histogram
            # cells) so a concurrent observe() cannot tear one
            # rendered sample apart from another (_sum vs _count)
            cells = [(values, self._snapshot(cell))
                     for values, cell in sorted(self._cells.items())]
        for values, cell in cells:
            out.extend(self._expose_cell(values, cell, exemplars))
        return out

    def _snapshot(self, cell):
        return cell   # numbers are immutable; Histogram overrides

    def snapshot_cells(self) -> list[tuple[dict, object]]:
        """Every live cell as ``({label: value}, snapshot)`` rows —
        the read API the SLO engine (obs/slo.py) evaluates rules over.
        Counter/gauge snapshots are plain numbers; Histogram rows are
        the raw ``(bucket_counts, sum, exemplars)`` triple (counts
        cumulated by the consumer, exactly like exposition).  Taken
        under the family lock, so one evaluation never sees a torn
        cell."""
        with self._lock:
            return [(dict(zip(self.labels, values)),
                     self._snapshot(cell))
                    for values, cell in sorted(self._cells.items())]

    def _expose_cell(self, values: tuple, cell,
                     exemplars: bool = False) -> list[str]:
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing count.  ``inc`` only — a counter that
    can go down is a gauge wearing the wrong TYPE line."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counter decrement ({n})")
        key = self._values(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._cells.get(self._values(labels), 0)

    def _expose_cell(self, values, cell,
                     exemplars: bool = False) -> list[str]:
        return [f"{self.name}{_label_str(self.labels, values)} "
                f"{_fmt_num(cell)}"]


class Gauge(_Metric):
    """A point-in-time level (queue depth, breaker state): settable in
    both directions, resettable to zero."""

    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        key = self._values(labels)
        with self._lock:
            self._cells[key] = v

    def inc(self, n: float = 1, **labels) -> None:
        key = self._values(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def reset(self, **labels) -> None:
        self.set(0, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._cells.get(self._values(labels), 0)

    def _expose_cell(self, values, cell,
                     exemplars: bool = False) -> list[str]:
        return [f"{self.name}{_label_str(self.labels, values)} "
                f"{_fmt_num(cell)}"]


class Histogram(_Metric):
    """Fixed-bucket histogram.  Buckets are declared at registration
    (sorted, finite upper bounds); exposition renders the Prometheus
    cumulative form — each ``_bucket{le="x"}`` counts observations
    ``<= x``, the mandatory ``+Inf`` bucket equals ``_count``, and
    ``_sum`` carries the total.

    Exemplars (ISSUE 14 satellite): ``observe(v, trace_id=...)``
    attaches the observation's cross-process trace identity to the
    bucket it landed in (latest wins per bucket), rendered in the
    OpenMetrics exemplar syntax — ``..._bucket{le="1"} 7
    # {trace_id="8f3ab129cd01"} 0.93 <ts>`` — so a p99 bucket links
    straight to ``pwasm-tpu inspect``'s flight record for a job that
    actually landed there.  Rendering is OPT-IN per exposition
    (``expose(exemplars=True)``): the default output stays pure
    Prometheus 0.0.4, which classic scrapers require."""

    kind = "histogram"

    def __init__(self, name: str, help_text: str,
                 buckets: tuple[float, ...] = DEFAULT_TIME_BUCKETS,
                 labels: tuple[str, ...] = ()):
        super().__init__(name, help_text, labels)
        bs = tuple(float(b) for b in buckets)
        if not bs or list(bs) != sorted(set(bs)):
            raise ValueError(
                f"{name}: buckets must be a sorted unique tuple")
        self.buckets = bs

    def observe(self, v: float, trace_id: str | None = None,
                **labels) -> None:
        key = self._values(labels)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                # per-bucket RAW counts (cumulated at exposition),
                # sum, and the per-bucket latest exemplar
                cell = [[0] * (len(self.buckets) + 1), 0.0, {}]
                self._cells[key] = cell
            counts = cell[0]
            idx = len(self.buckets)      # the +Inf overflow bucket
            for i, b in enumerate(self.buckets):
                if v <= b:
                    idx = i
                    break
            counts[idx] += 1
            cell[1] += v
            if trace_id:
                cell[2][idx] = (str(trace_id), float(v),
                                round(time.time(), 3))

    def count(self, **labels) -> int:
        with self._lock:
            cell = self._cells.get(self._values(labels))
            return sum(cell[0]) if cell else 0

    def _snapshot(self, cell):
        counts, total, ex = cell
        return (list(counts), total, dict(ex))

    def _expose_cell(self, values, cell,
                     exemplars: bool = False) -> list[str]:
        counts, total, ex = cell

        def exemplar(idx: int) -> str:
            e = ex.get(idx) if exemplars else None
            if e is None:
                return ""
            tid, v, ts = e
            return (f' # {{trace_id="{_escape_label(tid)}"}} '
                    f"{_fmt_num(v)} {_fmt_num(ts)}")

        out = []
        cum = 0
        for i, (b, c) in enumerate(zip(self.buckets, counts)):
            cum += c
            lbl = _label_str(self.labels + ("le",),
                             values + (_fmt_num(b),))
            out.append(f"{self.name}_bucket{lbl} {cum}"
                       + exemplar(i))
        cum += counts[-1]
        lbl = _label_str(self.labels + ("le",), values + ("+Inf",))
        out.append(f"{self.name}_bucket{lbl} {cum}"
                   + exemplar(len(self.buckets)))
        base = _label_str(self.labels, values)
        out.append(f"{self.name}_sum{base} {_fmt_num(total)}")
        out.append(f"{self.name}_count{base} {cum}")
        return out


class MetricsRegistry:
    """One namespace of metric families.  Registration is
    first-wins-and-second-raises: a duplicate name is a programming
    error the static lint also catches, never a silent aliasing of two
    meanings onto one time series."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _register(self, m: _Metric) -> _Metric:
        with self._lock:
            if m.name in self._metrics:
                raise ValueError(
                    f"metric {m.name!r} already registered")
            self._metrics[m.name] = m
        return m

    def counter(self, name: str, help_text: str,
                labels: tuple[str, ...] = ()) -> Counter:
        return self._register(Counter(name, help_text, labels))

    def gauge(self, name: str, help_text: str,
              labels: tuple[str, ...] = ()) -> Gauge:
        return self._register(Gauge(name, help_text, labels))

    def histogram(self, name: str, help_text: str,
                  buckets: tuple[float, ...] = DEFAULT_TIME_BUCKETS,
                  labels: tuple[str, ...] = ()) -> Histogram:
        return self._register(Histogram(name, help_text, buckets,
                                        labels))

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def expose(self, exemplars: bool = False) -> str:
        """The full registry in Prometheus text exposition format
        (families in registration order — stable output diffs are part
        of the test contract).  ``exemplars=True`` additionally
        renders the OpenMetrics exemplar suffix on histogram bucket
        lines — OPT-IN, because classic Prometheus 0.0.4 parsers (and
        the node-exporter textfile collector) reject the trailing
        ``#``: the default exposition and the textfile stay pure
        0.0.4, and exemplar-aware consumers (``pwasm-tpu metrics
        --exemplars``, OpenMetrics scrapers) ask explicitly."""
        with self._lock:
            fams = list(self._metrics.values())
        lines: list[str] = []
        for m in fams:
            lines.extend(m.expose(exemplars))
        return "\n".join(lines) + "\n" if lines else ""

    def write_textfile(self, path: str) -> None:
        """Publish the exposition atomically+durably for a
        node-exporter textfile collector: the audited fsync-then-replace
        (``utils.fsio``) — a scraper reads the old snapshot or the new
        one, never a torn prefix."""
        from pwasm_tpu.utils.fsio import write_durable_text
        write_durable_text(path, self.expose())
