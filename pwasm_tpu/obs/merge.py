"""Cross-process trace correlation: ``pwasm-tpu trace-merge``.

Each :class:`~pwasm_tpu.obs.tracing.TraceRecorder` stamps spans on its
OWN monotonic clock (``ts`` microseconds relative to recorder start)
plus one wall-clock anchor (``otherData.anchor_wall_s``, the wall time
of the monotonic origin).  Two processes' traces of one job — the
client's submit/wait spans and the daemon's queue/lease/exec spans —
therefore live on incomparable time axes until the anchors line them
up: :func:`merge_traces` shifts every document onto the EARLIEST
anchor's axis and emits one Chrome-trace JSON, loadable in
chrome://tracing / Perfetto, where a job's full
client→queue→lease→device→spool life reads as one timeline (grep the
``trace_id`` span args to isolate one job).

Anchor caveat: the shift is exact only as far as the two hosts' wall
clocks agree — on one machine (the unix-socket serving case) that is
microseconds; across NTP-disciplined hosts, milliseconds.  Span
NESTING within each process is untouched either way (one constant
shift per document), so the monotonic-nesting schema property
survives the merge.

jax-free (``qa/check_supervision.py`` gates ``pwasm_tpu/obs/``).
"""

from __future__ import annotations

import json

from pwasm_tpu.core.errors import EXIT_USAGE

_MERGE_USAGE = """Usage:
 pwasm-tpu trace-merge FILE.json [FILE.json ...] [-o OUT.json]

   Merge two or more --trace-json documents (e.g. a submit client's
   trace and the serve daemon's serve --trace-json) onto one wall-
   anchored timeline.  Writes Chrome trace-event JSON to OUT.json
   (default: stdout) — load it in chrome://tracing or Perfetto and
   filter on a trace_id to follow one job across both processes.
"""


def merge_traces(docs: list[tuple[str, dict]]) -> dict:
    """Merge ``(label, trace_doc)`` pairs onto one timeline.

    Every document's events are shifted by its wall-anchor delta to
    the earliest anchor (one constant per document — intra-process
    nesting is preserved exactly); pids colliding across documents are
    remapped so two processes that happened to share a pid (or two
    captures of one process) stay separate tracks; a ``process_name``
    metadata event labels each track with its source file."""
    anchors = []
    for _label, doc in docs:
        od = doc.get("otherData") or {}
        a = od.get("anchor_wall_s")
        anchors.append(float(a) if isinstance(a, (int, float)) else 0.0)
    base = min(anchors) if anchors else 0.0
    events: list[dict] = []
    used_pids: set = set()
    dropped_total = 0
    for i, ((label, doc), anchor) in enumerate(zip(docs, anchors)):
        shift_us = int(round((anchor - base) * 1e6))
        doc_events = doc.get("traceEvents") or []
        doc_pids = {e.get("pid") for e in doc_events
                    if isinstance(e, dict)}
        remap = {}
        for pid in doc_pids:
            new = pid
            while new in used_pids:
                new = (new if isinstance(new, int) else 0) + 1_000_000
            remap[pid] = new
            used_pids.add(new)
        for pid in sorted((p for p in doc_pids
                           if isinstance(p, int)), key=int):
            events.append({"name": "process_name", "ph": "M",
                           "pid": remap[pid], "tid": 0,
                           "args": {"name": label}})
        for e in doc_events:
            if not isinstance(e, dict):
                continue
            e2 = dict(e)
            if isinstance(e2.get("ts"), (int, float)):
                e2["ts"] = int(e2["ts"]) + shift_us
            if e2.get("pid") in remap:
                e2["pid"] = remap[e2["pid"]]
            events.append(e2)
        od = doc.get("otherData") or {}
        if isinstance(od.get("dropped_events"), int):
            dropped_total += od["dropped_events"]
    out = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"anchor_wall_s": round(base, 6),
                         "merged": len(docs)}}
    if dropped_total:
        out["otherData"]["dropped_events"] = dropped_total
    return out


def trace_merge_main(argv: list[str], stdout=None, stderr=None) -> int:
    """The ``pwasm-tpu trace-merge`` entry point."""
    import os
    import sys
    stdout = stdout if stdout is not None else sys.stdout
    stderr = stderr if stderr is not None else sys.stderr
    paths: list[str] = []
    out_path: str | None = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in ("-h", "--help"):
            stderr.write(_MERGE_USAGE)
            return EXIT_USAGE
        if a == "-o":
            i += 1
            if i >= len(argv):
                stderr.write(f"{_MERGE_USAGE}\n-o needs a file\n")
                return EXIT_USAGE
            out_path = argv[i]
        elif a.startswith("--out="):
            out_path = a.split("=", 1)[1]
        elif a.startswith("-") and a != "-":
            stderr.write(f"{_MERGE_USAGE}\nInvalid argument: {a}\n")
            return EXIT_USAGE
        else:
            paths.append(a)
        i += 1
    if not paths:
        stderr.write(f"{_MERGE_USAGE}\nError: at least one trace "
                     "file is required\n")
        return EXIT_USAGE
    docs: list[tuple[str, dict]] = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            stderr.write(f"Error: cannot read trace {p}: {e}\n")
            return 1
        if not isinstance(doc, dict) \
                or not isinstance(doc.get("traceEvents"), list):
            stderr.write(f"Error: {p} is not a Chrome trace-event "
                         "document\n")
            return 1
        if not isinstance((doc.get("otherData") or {})
                          .get("anchor_wall_s"), (int, float)):
            stderr.write(f"pwasm: warning: {p} carries no wall-clock "
                         "anchor (pre-ISSUE-11 trace?); merging on a "
                         "zero anchor — cross-process alignment will "
                         "be wrong\n")
        docs.append((os.path.basename(p), doc))
    merged = merge_traces(docs)
    text = json.dumps(merged)
    if out_path is None:
        stdout.write(text + "\n")
        return 0
    from pwasm_tpu.utils.fsio import write_durable_text
    try:
        write_durable_text(out_path, text)
    except OSError as e:
        stderr.write(f"Error: cannot write {out_path}: {e}\n")
        return 1
    stderr.write(f"pwasm: merged trace written to {out_path}\n")
    return 0
