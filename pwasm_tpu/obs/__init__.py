"""Fleet-grade observability (ISSUE 6): metrics, spans, event log.

One jax-free subsystem behind three CLI flags and one service command:

- ``--metrics-textfile=PATH``  a :class:`~pwasm_tpu.obs.metrics.
  MetricsRegistry` rendered in Prometheus text exposition, published
  atomically for a node-exporter textfile collector (the serve daemon
  additionally answers the same exposition over its socket via the
  ``metrics`` command / ``pwasm-tpu metrics`` verb);
- ``--trace-json=FILE``  monotonic-clock phase/batch spans as Chrome
  trace-event JSON (:mod:`pwasm_tpu.obs.tracing`), complementing the
  jax-side ``--profile=DIR`` device trace;
- ``--log-json=FILE|-``  the structured NDJSON run-lifecycle event log
  (:mod:`pwasm_tpu.obs.events`).

The :class:`Observability` facade is what gets threaded through the
run (cli -> supervisor/monitor/drain): a null instance (every hook a
cheap no-op) when no flag asked for anything, so the hot path carries
one attribute check per hook and the byte-parity contract — report
bytes identical with observability on and off — holds by construction
(observability writes only to its own sinks, never the report stream).
Metric NAMES live in :mod:`pwasm_tpu.obs.catalog`, the single
registration namespace the static lint (``qa/check_supervision.py``)
enforces.
"""

from __future__ import annotations

from contextlib import nullcontext

from pwasm_tpu.obs.events import EventLog, new_run_id  # noqa: F401
from pwasm_tpu.obs.metrics import MetricsRegistry  # noqa: F401
from pwasm_tpu.obs.tracing import TraceRecorder  # noqa: F401


class Observability:
    """The per-run observability bundle.  Any of the three sinks may be
    absent; every hook degrades to a no-op so call sites never branch.

    ``registry``/``run_metrics`` — the metrics registry and the built
    run-metric families (``obs/catalog.py``); ``tracer`` — the span
    recorder; ``events`` — the NDJSON event log.  ``trace_path`` /
    ``metrics_path`` are written by :meth:`close`.
    """

    def __init__(self, registry=None, run_metrics=None, tracer=None,
                 events=None, trace_path=None, metrics_path=None,
                 run_id=None):
        self.registry = registry
        self.run_metrics = run_metrics
        self.tracer = tracer
        self.events = events
        self.trace_path = trace_path
        self.metrics_path = metrics_path
        self.run_id = run_id or (events.run_id if events is not None
                                 else new_run_id())

    @property
    def enabled(self) -> bool:
        return (self.registry is not None or self.tracer is not None
                or self.events is not None)

    # ---- hooks (all no-ops when the sink is absent) --------------------
    def span(self, name: str, **args):
        if self.tracer is None:
            return nullcontext()
        return self.tracer.span(name, **args)

    def event(self, event: str, **fields) -> None:
        """One lifecycle event: an NDJSON line and (when tracing) an
        instant mark on the trace timeline, so the two views line up."""
        if self.events is not None:
            self.events.emit(event, **fields)
        if self.tracer is not None:
            self.tracer.instant(event, **fields)

    def clock(self) -> float:
        """The tracer's monotonic clock (0.0 when not tracing) — pair
        with :meth:`span_complete` for manually-extents phases."""
        return self.tracer.now() if self.tracer is not None else 0.0

    def span_complete(self, name: str, t0: float, **args) -> None:
        if self.tracer is not None:
            self.tracer.complete(name, t0, **args)

    def observe(self, key: str, value: float, **labels) -> None:
        if self.run_metrics is not None and key in self.run_metrics:
            self.run_metrics[key].observe(value, **labels)

    def set_gauge(self, key: str, value: float, **labels) -> None:
        if self.run_metrics is not None and key in self.run_metrics:
            self.run_metrics[key].set(value, **labels)

    # ---- end of run ----------------------------------------------------
    def close(self, stderr=None) -> None:
        """Flush the file-backed sinks (atomic writes) and close the
        event log.  Best-effort by contract: a failed trace write costs
        a warning, never the run's exit code."""
        import sys
        stderr = stderr if stderr is not None else sys.stderr
        if self.tracer is not None and self.trace_path:
            try:
                self.tracer.write(self.trace_path)
                print(f"pwasm: trace written to {self.trace_path}",
                      file=stderr)
            except OSError as e:
                print(f"Warning: cannot write --trace-json "
                      f"{self.trace_path}: {e}", file=stderr)
        if self.registry is not None and self.metrics_path:
            try:
                self.registry.write_textfile(self.metrics_path)
            except OSError as e:
                print(f"Warning: cannot write --metrics-textfile "
                      f"{self.metrics_path}: {e}", file=stderr)
        if self.events is not None:
            self.events.close()


class _NullObservability(Observability):
    """The shared do-nothing instance (default for every ``obs=``
    parameter): hooks resolve to the base no-ops, and it is never
    closed."""

    def __init__(self):
        super().__init__(run_id="null")


NULL_OBS = _NullObservability()


def make_observability(trace_json: str | None = None,
                       log_json: str | None = None,
                       metrics_textfile: str | None = None,
                       stdout=None) -> Observability:
    """Build the run's bundle from the three CLI flags (any subset).
    ``--log-json=-`` streams events to ``stdout`` (the conventional
    stdin/stdout marker; report writers targeting stdout should use
    ``-o`` with a file).  Raises ``OSError`` when a log file cannot be
    opened — the caller maps it to the usual cannot-open diagnostic."""
    registry = run_metrics = tracer = events = None
    if metrics_textfile:
        from pwasm_tpu.obs.catalog import build_run_metrics
        registry = MetricsRegistry()
        run_metrics = build_run_metrics(registry)
    if trace_json:
        tracer = TraceRecorder()
    if log_json:
        if log_json == "-":
            import sys
            events = EventLog(stdout if stdout is not None
                              else sys.stdout, owns_stream=False)
        else:
            # append, as documented: a restarted daemon (or a fleet
            # of runs sharing one log) must extend the incident
            # timeline, never wipe it
            events = EventLog(open(log_json, "a"), owns_stream=True)
    return Observability(registry=registry, run_metrics=run_metrics,
                         tracer=tracer, events=events,
                         trace_path=trace_json,
                         metrics_path=metrics_textfile)
