"""Fleet-grade observability (ISSUE 6): metrics, spans, event log.

One jax-free subsystem behind three CLI flags and one service command:

- ``--metrics-textfile=PATH``  a :class:`~pwasm_tpu.obs.metrics.
  MetricsRegistry` rendered in Prometheus text exposition, published
  atomically for a node-exporter textfile collector (the serve daemon
  additionally answers the same exposition over its socket via the
  ``metrics`` command / ``pwasm-tpu metrics`` verb);
- ``--trace-json=FILE``  monotonic-clock phase/batch spans as Chrome
  trace-event JSON (:mod:`pwasm_tpu.obs.tracing`), complementing the
  jax-side ``--profile=DIR`` device trace;
- ``--log-json=FILE|-``  the structured NDJSON run-lifecycle event log
  (:mod:`pwasm_tpu.obs.events`).

The :class:`Observability` facade is what gets threaded through the
run (cli -> supervisor/monitor/drain): a null instance (every hook a
cheap no-op) when no flag asked for anything, so the hot path carries
one attribute check per hook and the byte-parity contract — report
bytes identical with observability on and off — holds by construction
(observability writes only to its own sinks, never the report stream).
Metric NAMES live in :mod:`pwasm_tpu.obs.catalog`, the single
registration namespace the static lint (``qa/check_supervision.py``)
enforces.
"""

from __future__ import annotations

import time as _time
from contextlib import nullcontext

from pwasm_tpu.obs.events import EventLog, new_run_id  # noqa: F401
from pwasm_tpu.obs.flight import FlightRecorder  # noqa: F401
from pwasm_tpu.obs.metrics import MetricsRegistry  # noqa: F401
from pwasm_tpu.obs.tracing import TraceRecorder  # noqa: F401


class _ObsSpan:
    """One span feeding BOTH sinks that want it: the trace recorder
    (when tracing) and the per-job flight recorder (when the run is a
    served job).  Timing for the flight side is perf_counter around
    the block; the tracer keeps its own clock."""

    __slots__ = ("_obs", "_name", "_tcm", "_t0")

    def __init__(self, obs: "Observability", name: str, args: dict):
        self._obs = obs
        self._name = name
        self._tcm = obs.tracer.span(name, **args) \
            if obs.tracer is not None else None

    def __enter__(self) -> "_ObsSpan":
        self._t0 = _time.perf_counter()
        if self._tcm is not None:
            self._tcm.__enter__()
        return self

    def __exit__(self, etype, exc, tb) -> None:
        if self._tcm is not None:
            self._tcm.__exit__(etype, exc, tb)
        flight = self._obs.flight
        if flight is not None:
            flight.note(self._name,
                        _time.perf_counter() - self._t0)


class Observability:
    """The per-run observability bundle.  Any of the sinks may be
    absent; every hook degrades to a no-op so call sites never branch.

    ``registry``/``run_metrics`` — the metrics registry and the built
    run-metric families (``obs/catalog.py``); ``tracer`` — the span
    recorder; ``events`` — the NDJSON event log; ``flight`` — the
    per-job :class:`~pwasm_tpu.obs.flight.FlightRecorder` a serve
    daemon hands a served job (spans accumulate phase walls there,
    events land in its ring).  ``trace_path`` / ``metrics_path`` are
    written by :meth:`close`.
    """

    def __init__(self, registry=None, run_metrics=None, tracer=None,
                 events=None, trace_path=None, metrics_path=None,
                 run_id=None, flight=None):
        self.registry = registry
        self.run_metrics = run_metrics
        self.tracer = tracer
        self.events = events
        self.flight = flight
        self.trace_path = trace_path
        self.metrics_path = metrics_path
        self.run_id = run_id or (events.run_id if events is not None
                                 else new_run_id())

    @property
    def enabled(self) -> bool:
        return (self.registry is not None or self.tracer is not None
                or self.events is not None or self.flight is not None)

    # ---- hooks (all no-ops when the sink is absent) --------------------
    def span(self, name: str, **args):
        if self.tracer is None and self.flight is None:
            return nullcontext()
        return _ObsSpan(self, name, args)

    def event(self, event: str, **fields) -> None:
        """One lifecycle event: an NDJSON line, (when tracing) an
        instant mark on the trace timeline, and (for a served job) a
        ring entry on the flight record — the three views line up."""
        if self.events is not None:
            self.events.emit(event, **fields)
        if self.tracer is not None:
            self.tracer.instant(event, **fields)
        if self.flight is not None:
            self.flight.mark(event, **fields)

    def clock(self) -> float:
        """The span clock (0.0 when neither tracing nor flight-
        recording) — pair with :meth:`span_complete` for
        manually-extents phases."""
        if self.tracer is not None:
            return self.tracer.now()
        if self.flight is not None:
            return _time.perf_counter()
        return 0.0

    def span_complete(self, name: str, t0: float, **args) -> None:
        if self.tracer is not None:
            now = self.tracer.now()
            self.tracer.complete(name, t0, **args)
        elif self.flight is not None:
            now = _time.perf_counter()
        else:
            return
        if self.flight is not None:
            self.flight.note(name, max(0.0, now - t0))

    def observe(self, key: str, value: float, **labels) -> None:
        if self.run_metrics is not None and key in self.run_metrics:
            self.run_metrics[key].observe(value, **labels)

    def set_gauge(self, key: str, value: float, **labels) -> None:
        if self.run_metrics is not None and key in self.run_metrics:
            self.run_metrics[key].set(value, **labels)

    def count(self, key: str, n: float, **labels) -> None:
        """Increment a run-metric counter (the per-flush host-stage
        fold uses this so the live Prometheus surface attributes
        time WHILE the run is alive, not only at end of run)."""
        if n > 0 and self.run_metrics is not None \
                and key in self.run_metrics:
            self.run_metrics[key].inc(n, **labels)

    # ---- end of run ----------------------------------------------------
    def close(self, stderr=None) -> None:
        """Flush the file-backed sinks (atomic writes) and close the
        event log.  Best-effort by contract: a failed trace write costs
        a warning, never the run's exit code."""
        import sys
        stderr = stderr if stderr is not None else sys.stderr
        if self.tracer is not None and self.trace_path:
            try:
                self.tracer.write(self.trace_path)
                print(f"pwasm: trace written to {self.trace_path}",
                      file=stderr)
            except OSError as e:
                print(f"Warning: cannot write --trace-json "
                      f"{self.trace_path}: {e}", file=stderr)
        if self.registry is not None and self.metrics_path:
            try:
                self.registry.write_textfile(self.metrics_path)
            except OSError as e:
                print(f"Warning: cannot write --metrics-textfile "
                      f"{self.metrics_path}: {e}", file=stderr)
        if self.events is not None:
            self.events.close()


class _NullObservability(Observability):
    """The shared do-nothing instance (default for every ``obs=``
    parameter): hooks resolve to the base no-ops, and it is never
    closed."""

    def __init__(self):
        super().__init__(run_id="null")


NULL_OBS = _NullObservability()


def make_observability(trace_json: str | None = None,
                       log_json: str | None = None,
                       metrics_textfile: str | None = None,
                       stdout=None,
                       trace_max_events: int | None = None,
                       log_json_max_bytes: int | None = None,
                       run_id: str | None = None,
                       flight=None) -> Observability:
    """Build the run's bundle from the CLI flags (any subset).
    ``--log-json=-`` streams events to ``stdout`` (the conventional
    stdin/stdout marker; report writers targeting stdout should use
    ``-o`` with a file).  ``trace_max_events`` overrides the
    recorder's 200k event cap (``--trace-max-events``);
    ``log_json_max_bytes`` turns on size-capped event-log rotation
    (``--log-json-max-bytes``); ``run_id`` stamps an externally-minted
    identity (a served job's trace_id) on every event line; ``flight``
    is the daemon-owned per-job flight recorder.  Raises ``OSError``
    when a log file cannot be opened — the caller maps it to the usual
    cannot-open diagnostic."""
    registry = run_metrics = tracer = events = None
    if metrics_textfile:
        from pwasm_tpu.obs.catalog import build_run_metrics
        registry = MetricsRegistry()
        run_metrics = build_run_metrics(registry)
    if trace_json:
        tracer = TraceRecorder(max_events=trace_max_events
                               or 200_000)
        if run_metrics is not None:
            # surface drops WHILE the run is alive (they used to
            # appear only in otherData at write time): each dropped
            # event lands on the live counter the exposition serves
            dropped = run_metrics.get("trace_dropped")
            if dropped is not None:
                tracer.on_drop = lambda c=dropped: c.inc()
    if log_json:
        if log_json == "-":
            import sys
            events = EventLog(stdout if stdout is not None
                              else sys.stdout, owns_stream=False,
                              run_id=run_id)
        else:
            # append, as documented: a restarted daemon (or a fleet
            # of runs sharing one log) must extend the incident
            # timeline, never wipe it — rotation (when capped) keeps
            # at most one previous generation beside it
            events = EventLog(path=log_json, run_id=run_id,
                              max_bytes=log_json_max_bytes)
    return Observability(registry=registry, run_metrics=run_metrics,
                         tracer=tracer, events=events,
                         trace_path=trace_json,
                         metrics_path=metrics_textfile,
                         run_id=run_id, flight=flight)
