"""Per-job flight record: bounded phase accounting + event ring.

The post-hoc "explain this job's wall" record (ISSUE 11): every served
job carries ONE :class:`FlightRecorder` from admission to its terminal
state, accumulating

- **phase walls** — queue wait, device-lease wait, the execution wall,
  and the run's own internal phases (input loop, per-flush
  submit/format walls, per-flush host-stage deltas, the MSA tail) as
  the :class:`~pwasm_tpu.obs.Observability` spans feed them in;
- **two bounded rings** — span summaries (per-flush walls; the last
  ``max_entries``) and diagnostic marks (retries, breaker transitions,
  OOM bisections, checkpoint writes, drains; the last ``max_marks``,
  kept SEPARATE so routine per-flush noise can never evict the rare
  events an incident review needs) — oldest dropped first either way
  (a flight recorder must stay bounded no matter how turbulent the
  flight).

The :meth:`summary` is a plain JSON-able dict: it rides the job
record in daemon RAM, moves to the CRC'd result spool past the
threshold, and is served by the ``inspect`` protocol verb /
``pwasm-tpu inspect JOB_ID`` — so "why was job X slow?" is one
request, not a grep across four files.  ``coverage`` is the accounted
fraction of the job's wall (queue + lease + exec over
submit→finish); the acceptance gate holds it at >= 0.9.

jax-free and never-raises by the same contract as the event log: a
recorder must not become the failure it was meant to explain.
"""

from __future__ import annotations

import threading
import time
from collections import deque

FLIGHT_VERSION = 1

# the phases whose sum is gated against the job wall (submit->finish):
# everything else in ``phases`` is breakdown INSIDE these
ACCOUNTED_PHASES = ("queue_wait", "lease_wait", "exec")

# marks that recur once per BATCH for a job's whole life: they route
# to the span-summary ring, because 64 slots of diagnostic ring must
# never be flooded by minute-2's checkpoint cadence (an OOM bisection
# from hour 1 has to still be visible at hour 9)
ROUTINE_MARKS = frozenset({"ckpt_write"})


class FlightRecorder:
    """Thread-safe bounded per-job phase/event record.

    ``note(phase, dur_s)`` accumulates a phase wall (and appends one
    ring entry); ``mark(event)`` appends a point event.  Both use a
    BOUNDED lock acquire and drop on timeout — the recorder is fed
    from span exits and the signal-drain path, exactly like the event
    log, and must never deadlock or raise into the run it observes.
    """

    def __init__(self, trace_id: str | None = None,
                 max_entries: int = 192, max_marks: int = 64):
        self.trace_id = trace_id
        self._lock = threading.Lock()
        self._phases: dict[str, list] = {}    # name -> [total_s, n]
        # TWO rings, so routine per-flush span summaries (one per
        # flush, hundreds on a long job) can never evict the rare
        # diagnostic marks (a retry, a breaker trip, an OOM bisection)
        # the recorder exists to keep
        self._entries: deque[dict] = deque(maxlen=max(1, max_entries))
        self._marks: deque[dict] = deque(maxlen=max(1, max_marks))
        self._appended = 0
        self._marked = 0

    # ---- recording -----------------------------------------------------
    def note(self, phase: str, dur_s: float, **extra) -> None:
        """Accumulate ``dur_s`` wall seconds under ``phase``."""
        entry = {"ph": str(phase), "s": round(float(dur_s), 6),
                 "t": round(time.time(), 3)}
        for k, v in extra.items():
            if v is not None:
                entry[k] = v
        if not self._lock.acquire(timeout=0.2):
            return
        try:
            cell = self._phases.get(phase)
            if cell is None:
                cell = self._phases[phase] = [0.0, 0]
            cell[0] += float(dur_s)
            cell[1] += 1
            self._entries.append(entry)
            self._appended += 1
        except Exception:
            pass
        finally:
            self._lock.release()

    def mark(self, event: str, **fields) -> None:
        """Append one point event (no duration) to the mark ring —
        except :data:`ROUTINE_MARKS` (per-batch cadence), which land
        in the span ring so they cannot evict rare incident marks."""
        entry = {"ev": str(event), "t": round(time.time(), 3)}
        for k, v in fields.items():
            if v is not None:
                entry[k] = v
        if not self._lock.acquire(timeout=0.2):
            return
        try:
            if event in ROUTINE_MARKS:
                self._entries.append(entry)
                self._appended += 1
            else:
                self._marks.append(entry)
                self._marked += 1
        except Exception:
            pass
        finally:
            self._lock.release()

    # ---- introspection -------------------------------------------------
    def phase_s(self, phase: str) -> float:
        with self._lock:
            cell = self._phases.get(phase)
            return cell[0] if cell else 0.0

    def summary(self, wall_s: float | None = None) -> dict:
        """The JSON-able flight record.  ``wall_s`` (the job's
        submit→finish wall) turns on the coverage figure — the
        accounted fraction the acceptance gate holds at >= 0.9."""
        with self._lock:
            phases = {name: {"s": round(cell[0], 6), "n": cell[1]}
                      for name, cell in sorted(self._phases.items())}
            entries = [dict(e) for e in self._entries]
            marks = [dict(e) for e in self._marks]
            dropped = max(0, self._appended - len(self._entries))
            marks_dropped = max(0, self._marked - len(self._marks))
        accounted = sum(phases[p]["s"] for p in ACCOUNTED_PHASES
                        if p in phases)
        out = {"version": FLIGHT_VERSION,
               "trace_id": self.trace_id,
               "phases": phases,
               "accounted_s": round(accounted, 6),
               "entries": entries,
               "entries_dropped": dropped,
               "events": marks,
               "events_dropped": marks_dropped}
        if wall_s is not None and wall_s > 0:
            out["wall_s"] = round(float(wall_s), 6)
            out["coverage"] = round(min(1.0, accounted / wall_s), 4)
        return out
