"""Span tracing: monotonic-clock phase spans as Chrome trace events.

``--trace-json=FILE`` records lightweight spans around the run phases
(the input parse/extract loop, each device flush and its host
formatting, every supervised batch attempt, the MSA tail) and writes
one Chrome trace-event JSON file — loadable in ``chrome://tracing`` /
Perfetto, the same viewers the jax ``device_trace`` dump targets, so
the host-side phase timeline and the device profile line up in one
toolchain.  This COMPLEMENTS ``--profile=DIR`` (the jax profiler sees
inside device programs; these spans see the run around them) and stays
jax-free so the plain-CPU path can trace too.

Spans use the monotonic ``time.perf_counter`` clock (µs, relative to
recorder start — wall time belongs to the event log, which stamps
both).  Each span lands as one complete ``"ph": "X"`` event at exit;
instant marks (breaker trips, drains) land as ``"ph": "i"``.  Nesting
is by construction: a span entered inside another on the same thread
exits first, so its ``[ts, ts+dur]`` interval sits inside the parent's
— the property the schema test asserts.
"""

from __future__ import annotations

import json
import os
import threading
import time


class _SpanCm:
    def __init__(self, rec: "TraceRecorder", name: str, args: dict):
        self._rec = rec
        self._name = name
        self._args = args
        self._t0 = 0.0

    def __enter__(self) -> "_SpanCm":
        self._t0 = self._rec._clock()
        return self

    def __exit__(self, etype, exc, tb) -> None:
        if etype is not None:
            # a span that unwound is still a span — mark it so a trace
            # of a failed run shows WHERE it died
            self._args = dict(self._args, error=etype.__name__)
        self._rec._complete(self._name, self._t0, self._rec._clock(),
                            self._args)


class TraceRecorder:
    """Collects trace events in memory (bounded) and serializes them as
    Chrome trace-event JSON.  ``clock`` is injectable for deterministic
    tests; events past ``max_events`` are dropped and counted, never
    grown without bound — a tracer must not become the OOM it was
    meant to observe."""

    def __init__(self, clock=None, max_events: int = 200_000):
        self._clock = clock or time.perf_counter
        self._t0 = self._clock()
        # the wall-clock anchor of the monotonic origin: two processes'
        # traces (client submit vs serve daemon) each stamp their own,
        # and trace_merge shifts every timeline onto one wall axis —
        # the cross-process correlation the per-process monotonic
        # clocks cannot provide alone
        self.anchor_wall_s = time.time()
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._max = max_events
        self.dropped = 0
        self.on_drop = None   # hook: called (unlocked) per dropped
        #   event — the live pwasm_trace_events_dropped_total feed
        self._pid = os.getpid()

    # ---- recording -----------------------------------------------------
    def span(self, name: str, **args) -> _SpanCm:
        """Context manager recording one complete ("X") span."""
        return _SpanCm(self, name, args)

    def complete(self, name: str, t0: float, t1: float | None = None,
                 **args) -> None:
        """Record a complete span from an explicit start time ``t0``
        (same clock as this recorder — ``now()``) to ``t1``/now: the
        manual twin of :meth:`span` for phases whose extent does not
        fit a ``with`` block cleanly."""
        self._complete(name, t0, self._clock() if t1 is None else t1,
                       args)

    def now(self) -> float:
        return self._clock()

    def instant(self, name: str, **args) -> None:
        """One instant ("i") mark at the current monotonic time."""
        self._append({"name": name, "ph": "i", "s": "t",
                      "ts": self._us(self._clock()),
                      "pid": self._pid,
                      "tid": threading.get_ident(),
                      "args": args})

    def _complete(self, name: str, t0: float, t1: float,
                  args: dict) -> None:
        self._append({"name": name, "ph": "X",
                      "ts": self._us(t0),
                      "dur": max(0, self._us(t1) - self._us(t0)),
                      "pid": self._pid,
                      "tid": threading.get_ident(),
                      "args": args})

    def _us(self, t: float) -> int:
        return int(round((t - self._t0) * 1e6))

    def _append(self, ev: dict) -> None:
        # BOUNDED acquire: instants are emitted from the signal-handler
        # drain path (SignalDrain.request -> obs.event -> instant), and
        # a handler interrupting the very thread that holds this
        # non-reentrant lock mid-append would deadlock the drain it is
        # recording — on timeout the event is dropped, never the run
        if not self._lock.acquire(timeout=0.2):
            self._note_drop()
            return
        try:
            if len(self._events) >= self._max:
                self._note_drop()
                return
            self._events.append(ev)
        finally:
            self._lock.release()

    def _note_drop(self) -> None:
        self.dropped += 1
        hook = self.on_drop
        if hook is not None:
            try:
                hook()     # a metrics hook must never kill the drop
            except Exception:
                pass

    # ---- output --------------------------------------------------------
    def to_dict(self) -> dict:
        with self._lock:
            events = list(self._events)
        out = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"anchor_wall_s":
                             round(self.anchor_wall_s, 6),
                             "pid": self._pid}}
        if self.dropped:
            out["otherData"]["dropped_events"] = self.dropped
        return out

    def write(self, path: str) -> None:
        """Publish the trace atomically (``utils.fsio``): a viewer —
        or a crash mid-write — never sees half a JSON document."""
        from pwasm_tpu.utils.fsio import write_durable_text
        write_durable_text(path, json.dumps(self.to_dict()))
