"""NDJSON event-log querying (``pwasm-tpu logs``).

Incident reconstruction used to be "grep two files by hand" — the
live ``--log-json`` file plus its rotated ``.1`` generation, in the
right order.  This module is that grep, done once and shared by the
two surfaces (ISSUE 14 satellite):

- ``pwasm-tpu logs FILE [filters]`` reads a log on disk directly;
- ``pwasm-tpu logs --socket=PATH [filters]`` asks a live daemon (or
  router) over the ``logs`` protocol verb — the daemon runs the same
  :func:`query_log` over its own ``--log-json`` path, so remote and
  local filtering cannot disagree.

Filters: ``trace_id`` (matches the record's ``trace_id`` OR its
``run_id`` — a served job's own run events carry the trace identity
as run_id), ``job_id``, and ``event`` (exact event-type match).
Results come back oldest-first across the rotation seam
(``FILE.1`` before ``FILE``), bounded by ``limit`` keeping the NEWEST
matches — an incident query wants the end of the story, not the
beginning of the file.

jax-free and read-only, like everything in ``pwasm_tpu/obs/``.
"""

from __future__ import annotations

import json


def iter_log_records(path: str):
    """Yield parsed event dicts from ``path``'s rotated generation
    (``path + '.1'``, when present) then ``path`` itself — oldest
    first across the seam.  Unparseable lines (a torn tail from a
    crash, a hand edit) are skipped, never fatal: the log exists to
    explain failures, so reading it must not add one."""
    for p in (path + ".1", path):
        try:
            f = open(p, encoding="utf-8")
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    yield rec


def record_matches(rec: dict, trace_id: str | None = None,
                   job_id: str | None = None,
                   event: str | None = None) -> bool:
    """One record against the filter set (all given filters must
    match).  ``trace_id`` matches either the explicit ``trace_id``
    field or ``run_id`` — a served job's cli.run stamps its trace
    identity as the run_id on its own event lines."""
    if trace_id is not None and rec.get("trace_id") != trace_id \
            and rec.get("run_id") != trace_id:
        return False
    if job_id is not None and rec.get("job_id") != job_id:
        return False
    if event is not None and rec.get("event") != event:
        return False
    return True


def query_log(path: str, trace_id: str | None = None,
              job_id: str | None = None, event: str | None = None,
              limit: int = 1000) -> list[dict]:
    """The newest ``limit`` matching records, oldest-first, across
    the rotation seam."""
    from collections import deque
    out: deque = deque(maxlen=max(1, int(limit)))
    for rec in iter_log_records(path):
        if record_matches(rec, trace_id=trace_id, job_id=job_id,
                          event=event):
            out.append(rec)
    return list(out)
