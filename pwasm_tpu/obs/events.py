"""Structured NDJSON event log: run-lifecycle events as they happen.

``--log-json=FILE|-`` turns the run's lifecycle into one append-only
stream of JSON lines a fleet can tail, ship and replay: breaker
trip/half-open/reclose, OOM demotion/re-promotion, fallbacks,
checkpoint writes, drains, and (in the serve daemon) job
admit/start/finish/evict.  Every record carries both clocks —
``ts_wall`` (epoch seconds, for correlation across machines) and
``ts_mono`` (monotonic seconds, for intra-run ordering that survives
NTP steps) — plus the run/job id, so one grep over a fleet's logs
reconstructs any incident timeline.

The log is strictly additive observability: emission never raises
(a full disk or closed pipe must not kill the run it observes), lines
are flushed as written (a crashed run's log ends at its last whole
event), and nothing here ever touches the report stream — the
byte-parity contract (`-o`/`-s`/`-w` identical with logging on or
off) is part of the test suite.
"""

from __future__ import annotations

import json
import threading
import time


def new_run_id() -> str:
    """A short unique id stamped on every event of one run (and handed
    to operators in incident timelines) — uuid4-derived, no coordination
    needed between the fleet's processes."""
    import uuid
    return uuid.uuid4().hex[:12]


class EventLog:
    """One NDJSON event sink.  ``stream`` is any text file object;
    ``owns_stream`` says whether :meth:`close` closes it (False for
    ``-`` = the run's stdout).  Thread-safe: daemon workers and the
    accept loop emit concurrently, one whole line per event.

    Size-capped rotation (``--log-json-max-bytes=N``): construct with
    ``path=``/``max_bytes=`` instead of a stream and the log rotates
    once the file passes ``max_bytes`` — the current file moves to
    ``<path>.1`` (ONE rotation generation: a long-lived serve daemon
    holds at most ~2x the cap on disk, instead of growing its NDJSON
    log without bound) and a ``log_rotate`` event opens the fresh
    file, so a tailing collector sees the seam.  Rotation failures
    degrade to appending on (emit-never-raises holds throughout)."""

    def __init__(self, stream=None, run_id: str | None = None,
                 owns_stream: bool = True, path: str | None = None,
                 max_bytes: int | None = None):
        self._lock = threading.Lock()
        self.path = path
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.rotations = 0
        if stream is None and path is not None:
            stream = open(path, "a")    # may raise: caller maps it to
            #   the usual cannot-open diagnostic, like the stream form
            owns_stream = True
        self._fh = stream
        self._owns = owns_stream
        self.run_id = run_id or new_run_id()

    def _maybe_rotate(self) -> None:
        """Rotate under the held lock once the file passed the cap.
        Best-effort: any failure keeps the current handle appending."""
        if self.max_bytes is None or self.path is None \
                or self._fh is None:
            return
        try:
            if self._fh.tell() < self.max_bytes:
                return
            import os
            self._fh.close()
            os.replace(self.path, self.path + ".1")
            self._fh = open(self.path, "a")
            self.rotations += 1
            rec = {"event": "log_rotate", "run_id": self.run_id,
                   "ts_wall": round(time.time(), 6),
                   "ts_mono": round(time.perf_counter(), 6),
                   "rotations": self.rotations,
                   "previous": self.path + ".1"}
            self._fh.write(json.dumps(rec, separators=(",", ":"))
                           + "\n")
            self._fh.flush()
        except Exception:
            # a failed rotation must not kill the log (or the run):
            # reopen the path if the handle died, else keep appending
            if self._fh is None or self._fh.closed:
                try:
                    self._fh = open(self.path, "a")
                except Exception:
                    self._fh = None

    def emit(self, event: str, **fields) -> None:
        """Append one event line.  Never raises — and is safe to call
        from a signal handler's drain path: the lock acquire is
        BOUNDED, because a handler running on the very thread that
        holds the (non-reentrant) lock mid-write would otherwise
        deadlock the drain it is trying to log.  On timeout — self-
        reentrancy or a wedged sink — the line is dropped, never the
        run."""
        if self._fh is None:
            return
        rec = {"event": event, "run_id": self.run_id,
               "ts_wall": round(time.time(), 6),
               "ts_mono": round(time.perf_counter(), 6)}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        if not self._lock.acquire(timeout=0.2):
            return
        try:
            self._maybe_rotate()
            fh = self._fh
            if fh is not None:
                fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
                fh.flush()
        except Exception:
            pass
        finally:
            self._lock.release()

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None and self._owns:
            try:
                fh.close()
            except Exception:
                pass
