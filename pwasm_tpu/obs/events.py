"""Structured NDJSON event log: run-lifecycle events as they happen.

``--log-json=FILE|-`` turns the run's lifecycle into one append-only
stream of JSON lines a fleet can tail, ship and replay: breaker
trip/half-open/reclose, OOM demotion/re-promotion, fallbacks,
checkpoint writes, drains, and (in the serve daemon) job
admit/start/finish/evict.  Every record carries both clocks —
``ts_wall`` (epoch seconds, for correlation across machines) and
``ts_mono`` (monotonic seconds, for intra-run ordering that survives
NTP steps) — plus the run/job id, so one grep over a fleet's logs
reconstructs any incident timeline.

The log is strictly additive observability: emission never raises
(a full disk or closed pipe must not kill the run it observes), lines
are flushed as written (a crashed run's log ends at its last whole
event), and nothing here ever touches the report stream — the
byte-parity contract (`-o`/`-s`/`-w` identical with logging on or
off) is part of the test suite.
"""

from __future__ import annotations

import json
import threading
import time


def new_run_id() -> str:
    """A short unique id stamped on every event of one run (and handed
    to operators in incident timelines) — uuid4-derived, no coordination
    needed between the fleet's processes."""
    import uuid
    return uuid.uuid4().hex[:12]


class EventLog:
    """One NDJSON event sink.  ``stream`` is any text file object;
    ``owns_stream`` says whether :meth:`close` closes it (False for
    ``-`` = the run's stdout).  Thread-safe: daemon workers and the
    accept loop emit concurrently, one whole line per event."""

    def __init__(self, stream, run_id: str | None = None,
                 owns_stream: bool = True):
        self._lock = threading.Lock()
        self._fh = stream
        self._owns = owns_stream
        self.run_id = run_id or new_run_id()

    def emit(self, event: str, **fields) -> None:
        """Append one event line.  Never raises — and is safe to call
        from a signal handler's drain path: the lock acquire is
        BOUNDED, because a handler running on the very thread that
        holds the (non-reentrant) lock mid-write would otherwise
        deadlock the drain it is trying to log.  On timeout — self-
        reentrancy or a wedged sink — the line is dropped, never the
        run."""
        fh = self._fh
        if fh is None:
            return
        rec = {"event": event, "run_id": self.run_id,
               "ts_wall": round(time.time(), 6),
               "ts_mono": round(time.perf_counter(), 6)}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        if not self._lock.acquire(timeout=0.2):
            return
        try:
            fh.write(json.dumps(rec, separators=(",", ":")) + "\n")
            fh.flush()
        except Exception:
            pass
        finally:
            self._lock.release()

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
        if fh is not None and self._owns:
            try:
                fh.close()
            except Exception:
                pass
