"""MSA container: progressive merging, gap propagation, consensus, writers.

Equivalent of the reference's GSeqAlign + MSAColumns + GAlnColumn
(GapAssem.h:255-461, GapAssem.cpp:593-1367).  Differences in mechanism (not
behavior):

- Pileup counts are a single (columns, 6) int32 tensor built with
  scatter-adds instead of per-column count objects — the exact tensor the
  TPU consensus kernel consumes.
- The per-member position walks (injectGap/removeColumn/evalClipping) use
  prefix sums + binary search over the same monotone walk positions.
- The consensus vote implements bestChar's stable-sort + '-'/'N' yield rule
  (GapAssem.cpp:1048-1069, quirk SURVEY.md §2.5.10) as a closed-form rule
  over the 6 counts.
"""

from __future__ import annotations

import sys
from typing import IO

import numpy as np

from pwasm_tpu.align.gapseq import FLAG_BAD_ALN, FLAG_PREPPED, GapSeq
from pwasm_tpu.core.errors import PwasmError, ZeroCoverageError

# column buckets, exactly this order (GapAssem.h:257-264)
NUC_ORDER = b"ACGTN-"
_BUCKET = np.full(256, 4, dtype=np.int8)  # default: N bucket
for _i, _c in enumerate(b"ACGT"):
    _BUCKET[_c] = _i
    _BUCKET[_c + 32] = _i  # lowercase
_BUCKET[ord("-")] = 5
_BUCKET[ord("*")] = 5


def _rank_by_column(cols: np.ndarray, codes: np.ndarray):
    """Sort (column, code) contributions by column and rank each
    contribution within its column: returns (sorted_cols, sorted_codes,
    occurrence_rank) where rank 0 is a column's first occupant."""
    order = np.argsort(cols, kind="stable")
    sc = cols[order]
    occ = np.arange(len(sc)) - np.searchsorted(sc, sc, side="left")
    return sc, codes[order], occ


def best_char_from_counts(counts, layers: int) -> int:
    """The consensus vote for one column.

    Reference bestChar (GapAssem.cpp:1048-1069): stable-sort the six
    buckets by count descending (initial order A,C,G,T,N,-), then while the
    best is '-' or 'N' and tied with the next, yield to the next.  Closed
    form: if any of A/C/G/T reaches the max count, the first of them wins;
    else if N and '-' tie at the max, '-' wins; else whichever of N/'-' has
    the max.  Returns the winning character code (int), or 0 if the column
    has no layers."""
    if layers == 0:
        return 0
    a, c, g, t, n, gap = (int(x) for x in counts)
    m = max(a, c, g, t, n, gap)
    for val, ch in ((a, ord("A")), (c, ord("C")), (g, ord("G")),
                    (t, ord("T"))):
        if val == m:
            return ch
    if n == m and gap == m:
        return ord("-")
    return ord("N") if n == m else ord("-")


def device_counts_votes(pile: np.ndarray, mesh=None):
    """Device counts + votes for a (rows, cols) int8 code pileup (codes
    0..6): one fused Pallas launch (``consensus_pallas``), or the
    depth-``psum`` sharded program over ``mesh``.  Returns
    ``(chars (cols,) int64 — vote character codes, 0 = zero coverage;
    counts (cols, 6) int32)``.  Shared by ``Msa._device_count_votes``
    and the native-engine device delegation (cli.py), so both product
    paths run the identical kernel program."""
    import jax.numpy as jnp

    ncols = pile.shape[1]
    if mesh is not None:
        from pwasm_tpu.parallel.mesh import sharded_counts_votes

        d_ax = mesh.shape["depth"]
        c_ax = int(np.prod([mesh.shape[a] for a in mesh.axis_names
                            if a != "depth"]))
        pad_d = -len(pile) % d_ax
        pad_c = -ncols % c_ax
        if pad_d or pad_c:
            pile = np.pad(pile, ((0, pad_d), (0, pad_c)),
                          constant_values=6)
        votes, counts = sharded_counts_votes(mesh)(jnp.asarray(pile))
        votes = votes[:ncols]
        counts = np.asarray(counts)[:ncols]
    else:
        from pwasm_tpu.ops.consensus import consensus_pallas

        # engine-built pileups carry only codes 0..6: remap-free kernel
        votes, counts = consensus_pallas(jnp.asarray(pile),
                                         assume_valid=True)
        counts = np.asarray(counts)
    v = np.asarray(votes)
    table = np.frombuffer(b"ACGTN-", dtype=np.uint8)
    chars = np.zeros(len(v), dtype=np.int64)
    valid = v >= 0
    chars[valid] = table[v[valid]]
    return chars, counts


class MsaColumns:
    """Column pileup: (size, 6) count tensor + live [mincol, maxcol] window
    (reference MSAColumns, GapAssem.h:345-376).  ``layers`` counts every
    contribution including gaps; clipped bases contribute only a witness
    flag (GAlnColumn::addNuc clipped path, GapAssem.h:299-308)."""

    def __init__(self, size: int, baseoffset: int = 0):
        self.size = size
        self.baseoffset = baseoffset
        self.counts = np.zeros((size, 6), dtype=np.int32)
        self.layers = np.zeros(size, dtype=np.int32)
        self.has_clip = np.zeros(size, dtype=bool)
        self.mincol = np.iinfo(np.int64).max
        self.maxcol = 0

    def update_min_max(self, minc: int, maxc: int) -> None:
        if minc < self.mincol:
            self.mincol = minc
        if maxc > self.maxcol:
            self.maxcol = maxc

    def len(self) -> int:
        return self.maxcol - self.mincol + 1

    def best_char(self, col: int) -> int:
        return best_char_from_counts(self.counts[col], int(self.layers[col]))


class Msa:
    """A multiple sequence alignment (reference GSeqAlign)."""

    def __init__(self, s1: GapSeq | None = None, s2: GapSeq | None = None,
                 cov_spans: tuple | None = None):
        self.seqs: list[GapSeq] = []
        self.length = 0
        self.minoffset = 0
        self.ng_len = 0
        self.ng_minofs = 0
        self.ordnum = 0
        self.badseqs = 0
        self.consensus = bytearray()
        self.msacolumns: MsaColumns | None = None
        self._device_vote_chars: np.ndarray | None = None
        self.refined = False
        self.engine_fallbacks = 0   # device stages demoted to host (the
        #                             engine-level analog of the CLI's
        #                             batch-level fallback_batches)
        if s1 is not None and s2 is not None:
            s1.msa = self
            s2.msa = self
            self.seqs = [s1, s2]
            self.minoffset = min(s1.offset, s2.offset)
            self.ng_minofs = self.minoffset
            self.length = max(s1.end_offset(), s2.end_offset()) - self.minoffset
            self.ng_len = max(s1.end_ng_offset(), s2.end_ng_offset()) \
                - self.ng_minofs
            if cov_spans is not None:
                self._init_coverage(s1, s2, cov_spans)

    @staticmethod
    def _init_coverage(s1: GapSeq, s2: GapSeq, cov_spans: tuple) -> None:
        """Opt-in coverage bookkeeping of the pairwise seed — the
        reference's ALIGN_COVERAGE_DATA ctor (GapAssem.cpp:599-639):
        +1 over each aligned span (half-open [l, r)), -1 per base of the
        shorter mismatched overhang at each end.  (The reference's
        compiled-out loop decrements a single boundary cell msml/msmr
        times and mixes inclusive/exclusive ends, GapAssem.cpp:627-639 —
        index slips in dead code; this implements the symmetric per-base
        intent.)"""
        (l1, r1), (l2, r2) = cov_spans
        s1.enable_coverage()
        s2.enable_coverage()
        s1.cov[l1:r1] += 1
        s2.cov[l2:r2] += 1
        msml = min(l1, l2)
        if msml > 0:
            s1.cov[l1 - msml:l1] -= 1
            s2.cov[l2 - msml:l2] -= 1
        msmr = min(s1.seqlen - r1, s2.seqlen - r2)
        if msmr > 0:
            s1.cov[r1:r1 + msmr] -= 1
            s2.cov[r2:r2 + msmr] -= 1

    def count(self) -> int:
        return len(self.seqs)

    # ---- membership / offsets ------------------------------------------
    def add_seq(self, s: GapSeq, soffs: int, ngofs: int) -> None:
        """(GSeqAlign::addSeq, GapAssem.cpp:694-716)"""
        s.offset = soffs
        s.ng_ofs = ngofs
        s.msa = self
        self.seqs.append(s)
        if soffs < self.minoffset:
            self.length += self.minoffset - soffs
            self.minoffset = soffs
        if ngofs < self.ng_minofs:
            self.ng_len += self.ng_minofs - ngofs
            self.ng_minofs = ngofs
        if s.end_offset() - self.minoffset > self.length:
            self.length = s.end_offset() - self.minoffset
        if s.end_ng_offset() - self.ng_minofs > self.ng_len:
            self.ng_len = s.end_ng_offset() - self.ng_minofs

    # ---- gap propagation -----------------------------------------------
    def _alpos_of(self, seq: GapSeq, pos: int) -> int:
        """Layout position of seq[pos]
        (the alpos computation, GapAssem.cpp:721-725)."""
        return seq.offset + pos + int(np.sum(seq.gaps[:pos + 1]))

    def inject_gap(self, seq: GapSeq, pos: int, xgap: int) -> None:
        """Propagate a gap in ``seq`` at ``pos`` through every member
        (GSeqAlign::injectGap, GapAssem.cpp:720-753)."""
        alpos = self._alpos_of(seq, pos)
        for s in self.seqs:
            if s is seq:
                spos = pos
            else:
                if s.offset >= alpos:
                    s.offset += xgap
                    continue
                spos = s.find_walk_pos(alpos)
                if spos >= s.seqlen:
                    continue
            s.add_gap(spos, xgap)
        self.length += xgap

    def remove_column(self, column: int) -> None:
        """Delete one layout column from every member
        (GSeqAlign::removeColumn, GapAssem.cpp:755-779)."""
        alpos = column + self.minoffset
        for s in self.seqs:
            if s.offset >= alpos:
                s.offset -= 1
                continue
            spos = s.find_walk_pos(alpos)
            if spos >= s.seqlen:
                continue
            s.remove_base(spos)
        self.length -= 1

    def remove_base(self, seq: GapSeq, pos: int) -> None:
        """(GSeqAlign::removeBase, GapAssem.cpp:781-812)"""
        alpos = self._alpos_of(seq, pos)
        for s in self.seqs:
            if s is seq:
                spos = pos
            else:
                if s.offset >= alpos:
                    s.offset -= 1
                    continue
                spos = s.find_walk_pos(alpos)
                if spos >= s.seqlen:
                    continue
            s.remove_base(spos)
        self.length -= 1

    # ---- merging --------------------------------------------------------
    def add_align(self, seq: GapSeq, omsa: "Msa", oseq: GapSeq) -> bool:
        """Merge ``omsa`` into this MSA through the shared sequence
        ``seq``/``oseq`` (same id/length), propagating gap differences both
        ways (GSeqAlign::addAlign, GapAssem.cpp:645-690)."""
        if seq.seqlen != oseq.seqlen:
            raise PwasmError(
                f"GSeqAlign Error: invalid merge {seq.name}"
                f"(len {seq.seqlen}) vs {oseq.name}(len {oseq.seqlen})\n")
        if seq.revcompl != oseq.revcompl:
            omsa.rev_complement()
        seq.add_coverage(oseq)  # no-op unless coverage tracking is on
        for i in range(seq.seqlen):
            d = seq.gap(i) - oseq.gap(i)
            if d > 0:
                omsa.inject_gap(oseq, i, d)
            elif d < 0:
                self.inject_gap(seq, i, -d)
        for s in omsa.seqs:
            if s is oseq:
                continue
            self.add_seq(s, seq.offset + s.offset - oseq.offset,
                         seq.ng_ofs + s.ng_ofs - oseq.ng_ofs)
        return True

    def rev_complement(self) -> None:
        """(GSeqAlign::revComplement, GapAssem.cpp:998-1004)"""
        for s in self.seqs:
            s.rev_complement(self.length)
        self.seqs.sort(key=lambda s: s.offset)

    def finalize(self) -> None:
        """prepSeq every member (GSeqAlign::finalize,
        GapAssem.cpp:1006-1012)."""
        for s in self.seqs:
            if len(s.seq) == 0:
                raise PwasmError(
                    f"Error: sequence for {s.name} not loaded!\n")
            if not s.has_flag(FLAG_PREPPED):
                s.prep_seq()

    # ---- pileup / consensus --------------------------------------------
    def _column_geometry(self, s: GapSeq):
        """Shared layout math for the pileup builders: returns
        (base_cols, unclipped mask, gap-run columns before unclipped
        bases).  ``base_cols[i]`` is the layout column of base i under the
        walk semantics (1 + gap per base; negative gaps collapse deleted
        bases onto their neighbor's column).

        Post-deletion placement is a repo-defined extension: this walk
        follows the reference's *salpos* accumulation (cumsum of 1+gap,
        so a negative gap pulls the deleted base's successors left),
        NOT its GASeq::toMSA gap loop (GapAssem.cpp:569-588), which
        advances ``max(ofs,0)+1`` and never pulls back.  The two agree
        everywhere the reference can actually reach (buildMSA runs once,
        before any removal); after a library-level remove_base the
        reference has no defined behavior, and host, device, and the
        native C++ engine all implement THIS semantics and are verified
        mutually exact."""
        if len(s.seq) == 0 or len(s.seq) != s.seqlen:
            raise PwasmError(
                f"GapSeq toMSA Error: invalid sequence data '{s.name}' "
                f"(len={len(s.seq)}, seqlen={s.seqlen})\n")
        clipL, clipR = s.clip_lr()
        gaps = s.gaps.astype(np.int64)
        base_cols = (s.offset - self.minoffset
                     + np.arange(s.seqlen, dtype=np.int64) + np.cumsum(gaps))
        idx = np.arange(s.seqlen)
        unclipped = ~((idx < clipL) | (idx >= s.seqlen - clipR))
        gmask = unclipped & (gaps > 0)
        if gmask.any():
            gcols = np.concatenate(
                [np.arange(base_cols[i] - gaps[i], base_cols[i])
                 for i in np.nonzero(gmask)[0]])
        else:
            gcols = np.empty(0, dtype=np.int64)
        # a deleted base can collapse its neighbors' columns off the left
        # edge of the layout (library-level remove_base on the leftmost
        # member).  Counting such a layout is meaningless on BOTH the
        # host scatter path (numpy would wrap the negative index) and
        # the device pileup — refuse loudly instead of drifting.
        live_min = base_cols[unclipped].min() if unclipped.any() else 0
        if live_min < 0 or (len(gcols) and gcols.min() < 0):
            raise PwasmError(
                f"MSA layout error: sequence {s.name} has contributions "
                "outside the layout (stranded deleted base)\n")
        return base_cols, unclipped, gcols

    def _seq_to_columns(self, s: GapSeq, cols: MsaColumns,
                        count: bool = True) -> None:
        """Pour one sequence into the column pileup (GASeq::toMSA,
        GapAssem.cpp:551-591) — vectorized scatter-adds.  With
        ``count=False`` only the geometry side effects happen (clip
        witnesses + the live window); the counts are expected to come
        from the device pileup kernel instead."""
        base_cols, unclipped, gcols = self._column_geometry(s)
        gaps = s.gaps.astype(np.int64)
        clipped = ~unclipped
        # clip-region deletions may push clipped columns off the layout
        # edge; they carry no counts, so drop (not wrap) their witnesses
        ccols = base_cols[clipped]
        cols.has_clip[ccols[(ccols >= 0) & (ccols < cols.size)]] = True
        if count:
            codes = _BUCKET[np.frombuffer(bytes(s.seq),
                                          dtype=np.uint8)].astype(np.int64)
            # nucleotides (clipped ones only set the witness flag)
            np.add.at(cols.counts, (base_cols[unclipped],
                                    codes[unclipped]), 1)
            np.add.at(cols.layers, base_cols[unclipped], 1)
            # gap columns before each unclipped base
            if len(gcols):
                np.add.at(cols.counts, (gcols, np.full(len(gcols), 5)), 1)
                np.add.at(cols.layers, gcols, 1)
        # min/max over the unclipped span: mincol includes the gap run
        # before the first unclipped base (GapAssem.cpp:565-590)
        if unclipped.any():
            first = int(np.argmax(unclipped))
            last = s.seqlen - 1 - int(np.argmax(unclipped[::-1]))
            mincol = int(base_cols[first] - max(int(gaps[first]), 0))
            maxcol = int(base_cols[last])
            cols.update_min_max(mincol, maxcol)

    def pileup_matrix(self) -> np.ndarray:
        """Render the MSA as a (rows, length) int8 code matrix for the
        device consensus path: A0 C1 G2 T3 N4, gap columns 5, and 6 (the
        kernels' PAD_CODE) where a row contributes nothing.  Device pileup
        counts over this matrix equal the CPU column counts bit-for-bit.

        Rows 0..depth-1 are the members.  With deleted bases (negative
        gaps, created by remove_column/remove_base during refinement) the
        cumsum layout collapses dead bases onto neighboring columns, so
        one member can contribute MORE than one symbol to a column — the
        host scatter-add counts them all (matching the engine's walk
        semantics; this post-deletion placement is a repo-defined
        extension, see _column_geometry).  A one-symbol-per-cell matrix
        can't hold that in the member's own row, so the extra occupants
        spill onto appended rows: counts are a sum over rows, so the
        device reduction stays exact with any row assignment.  Pre-refine
        (no deletions) there are no collisions and the matrix is exactly
        the historical (depth, length) form.

        Layouts whose contributions fall outside [0, length) — possible
        via library-level remove_base calls that strand a deleted base
        before the first live column — raise PwasmError from the shared
        geometry (such a layout is uncountable on the host scatter path
        too)."""
        mat = np.full((len(self.seqs), self.length), 6, dtype=np.int8)
        spill_cols: list[np.ndarray] = []
        spill_codes: list[np.ndarray] = []
        for k, s in enumerate(self.seqs):
            base_cols, unclipped, gcols = self._column_geometry(s)
            codes = _BUCKET[np.frombuffer(bytes(s.seq), dtype=np.uint8)]
            if not (s.gaps < 0).any():
                # fast path (pre-refine, the device hot path): gap runs
                # and base columns are disjoint — direct scatter
                if len(gcols):
                    mat[k, gcols] = 5
                mat[k, base_cols[unclipped]] = codes[unclipped]
                continue
            cols_all = np.concatenate([gcols, base_cols[unclipped]])
            codes_all = np.concatenate(
                [np.full(len(gcols), 5, dtype=np.int8), codes[unclipped]])
            sc, scd, occ = _rank_by_column(cols_all, codes_all)
            mat[k, sc[occ == 0]] = scd[occ == 0]
            if (occ > 0).any():
                spill_cols.append(sc[occ > 0])
                spill_codes.append(scd[occ > 0])
        if spill_cols:
            # pack spills across members: row r carries every column's
            # (r+1)-th excess occupant, so the row count is bounded by
            # the worst per-column collision depth, not the member count
            sc, scd, occ = _rank_by_column(np.concatenate(spill_cols),
                                           np.concatenate(spill_codes))
            rows = np.full((int(occ.max()) + 1, self.length), 6,
                           dtype=np.int8)
            rows[occ, sc] = scd
            mat = np.concatenate([mat, rows], axis=0)
        return mat

    def provenance_matrix(self) -> np.ndarray:
        """(depth, length) int32 companion of ``pileup_matrix``: the
        1-based source position of each member's base at each layout
        column, 0 where the member contributes no base (outside its
        span, clipped, deleted, or a gap column).

        This is the tensor re-design of the reference's per-column
        ``NucOri`` provenance list (GapAssem.h:142-161, nucs in
        GAlnColumn GapAssem.h:255-342): instead of a linked list of
        (seq, pos) per column, one dense index tensor aligned with the
        pileup codes, so "which read put which base here" is a gather.
        Pre-refine MSAs only (enforced below): rows map 1:1 to members,
        and a deleted base would collide two source positions onto one
        cell — unlike pileup_matrix, whose counts are row-order-free and
        so can spill collisions onto extra rows, provenance has no such
        escape."""
        for s in self.seqs:
            if (s.gaps < 0).any():
                raise PwasmError(
                    f"provenance_matrix: sequence {s.name} has deleted "
                    "bases (post-refine MSA); provenance is only exact "
                    "pre-refine\n")
        prov = np.zeros((len(self.seqs), self.length), dtype=np.int32)
        for k, s in enumerate(self.seqs):
            base_cols, unclipped, _g = self._column_geometry(s)
            live = unclipped & (s.gaps >= 0)
            prov[k, base_cols[live]] = np.nonzero(live)[0] + 1
        return prov

    def column_contributors(self, col: int) -> list[tuple]:
        """Who contributes what at layout column ``col``: a list of
        ``(member_index, base_pos, symbol, clipped)`` where symbol is
        the member's base character at that column, '-' for a gap
        column inside its span, and base_pos is the 0-based position in
        the member's sequence (for '-', the base the gap run precedes).
        Members whose span does not cover the column are absent.
        The queryable surface of the reference's NucOri/GAlnColumn
        provenance (clipped contributors mirror the stored clip
        witness, GapAssem.h:295-337)."""
        out = []
        for k, s in enumerate(self.seqs):
            base_cols, unclipped, _g = self._column_geometry(s)
            gaps = s.gaps.astype(np.int64)
            j = int(np.searchsorted(base_cols, col, side="left"))
            if j >= s.seqlen:
                continue
            if base_cols[j] == col:
                if gaps[j] < 0:
                    continue  # deleted base: no contribution
                out.append((k, j, chr(s.seq[j]), not bool(unclipped[j])))
            elif base_cols[j] - max(int(gaps[j]), 0) <= col < base_cols[j]:
                if unclipped[j]:
                    out.append((k, j, "-", False))
        return out

    def column_mismatches(self, col: int) -> list[tuple]:
        """Contributors at ``col`` that disagree with the column's
        consensus vote — the SNP-attribution query the reference's
        provenance list exists for.  Requires ``build_msa()`` (the
        counts).  Returns ``(member_index, base_pos, symbol)`` for every
        unclipped contributor whose symbol differs from the vote."""
        if self.msacolumns is None:
            raise PwasmError(
                "column_mismatches requires build_msa() first\n")
        vote = best_char_from_counts(
            self.msacolumns.counts[col],
            int(self.msacolumns.layers[col]))
        want = chr(vote) if vote else ""
        return [(k, pos, sym) for k, pos, sym, clipped
                in self.column_contributors(col)
                if not clipped and sym.upper() != want]

    def build_msa(self, device: bool = False, mesh=None,
                  supervisor=None) -> None:
        """(GSeqAlign::buildMSA, GapAssem.cpp:1088-1106).  With ``device``
        the column counts (and the consensus votes) come from one Pallas
        launch over ``pileup_matrix()`` (ops.consensus.consensus_pallas —
        the device form of toMSA+bestChar, GapAssem.cpp:1088-1106 /
        1048-1069); the host keeps only the geometry side effects (live
        window, clip witnesses, bad-trim flags).  Bit-exact: the pileup
        matrix reproduces the CPU column counts pre-refine (see
        pileup_matrix)."""
        if self.msacolumns is not None:
            raise PwasmError("Error: cannot call buildMSA() twice!\n")
        # deleted bases are handled via spill rows in pileup_matrix, so
        # the device path is exact post-refine too; a stranded-deleted-
        # base layout raises from the shared geometry on BOTH paths (it
        # is uncountable either way) rather than demoting
        pile = self.pileup_matrix() if device else None
        self.msacolumns = MsaColumns(self.length, self.minoffset)
        for i, s in enumerate(self.seqs):
            s.msaidx = i
            if s.seqlen - s.clp3 - s.clp5 < 1:
                print(f"Warning: sequence {s.name} (length {s.seqlen}) was "
                      f"trimmed too badly ({s.clp5},{s.clp3}) -- should be "
                      f"removed from MSA w/ {self.seqs[0].name}!",
                      file=sys.stderr)
                s.set_flag(FLAG_BAD_ALN)
                self.badseqs += 1
            self._seq_to_columns(s, self.msacolumns, count=not device)
        if device:
            self._device_count_votes(mesh, pile=pile,
                                     supervisor=supervisor)

    def _err_zero_cov(self, col: int) -> None:
        """(GSeqAlign::ErrZeroCov, GapAssem.cpp:1121-1131; exit 5)"""
        print(f"WARNING: 0 coverage column {col} "
              f"(mincol={self.msacolumns.mincol}) found within alignment "
              f"of {self.count()} seqs!", file=sys.stderr)
        for s in self.seqs:
            print(s.name, file=sys.stderr)
        raise ZeroCoverageError(f"zero-coverage column {col}")

    def _device_count_votes(self, mesh=None, pile=None,
                            supervisor=None) -> None:
        """Fill the column counts AND the consensus votes from one device
        launch: ``pileup_matrix()`` → ``consensus_pallas`` (pileup counting
        + the bestChar vote fused in a single Pallas kernel).  This is the
        device form of the reference's toMSA+bestChar hot loop
        (GapAssem.cpp:1088-1106, 1048-1069).  Zero-coverage columns vote 0,
        exactly like ``best_char``.  Bit-exact with the CPU path by
        construction: integer counts over the same pileup, same closed-form
        vote rule.

        With ``mesh`` (a jax.sharding.Mesh from ``pafreport --shard``)
        the pileup shards (depth, cols) over the mesh and the per-column
        class counts are ``psum``-reduced over the depth axis before the
        vote — the north-star ICI collective (SURVEY.md §0).  Same
        integers, so still bit-exact."""
        cols = self.msacolumns
        if pile is None:
            pile = self.pileup_matrix()
        if supervisor is not None:
            from pwasm_tpu.resilience.guardrails import check_consensus

            def host_counts():
                # TPU→CPU degradation: numpy class counts over the SAME
                # pileup; chars=None routes refine_msa to its host vote
                # over these counts — bit-exact by the vote contract
                from pwasm_tpu.ops.consensus_host import \
                    host_class_counts
                self.engine_fallbacks += 1
                return None, host_class_counts(pile)

            chars, counts = supervisor.run(
                "consensus",
                lambda: device_counts_votes(pile, mesh=mesh),
                validate=lambda r: check_consensus(r[0], r[1], pile),
                fallback=host_counts)
        else:
            chars, counts = device_counts_votes(pile, mesh=mesh)
        cols.counts[:] = counts
        cols.layers[:] = counts.sum(axis=1, dtype=np.int32)
        self._device_vote_chars = chars

    def refine_msa(self, remove_cons_gaps: bool = True,
                   refine_clipping: bool = True,
                   device: bool = False, mesh=None,
                   supervisor=None) -> None:
        """Consensus construction + clipping refinement driver
        (GSeqAlign::refineMSA, GapAssem.cpp:1133-1183).  The two flags are
        the reference's MSAColumns statics; pafreport runs with
        remove_cons_gaps=False (SURVEY.md §2.5.8).  With ``device`` both
        the column counts and the votes come from one Pallas launch over
        the pileup tensor (see build_msa/_device_count_votes) instead of
        host scatter-adds + per-column votes (same integer rule,
        bit-exact)."""
        self.build_msa(device=device, mesh=mesh, supervisor=supervisor)
        cols = self.msacolumns
        if device and self._device_vote_chars is not None:
            votes = self._device_vote_chars[cols.mincol:cols.maxcol + 1]
        else:
            # native single-core vote over the whole live window when
            # available (bit-exact with best_char_from_counts; parity
            # covered by tests/test_native.py)
            from pwasm_tpu.native import consensus_vote_counts
            span = slice(cols.mincol, cols.maxcol + 1)
            votes = consensus_vote_counts(cols.counts[span],
                                          cols.layers[span])
            if votes is None:
                # native library unavailable (PWASM_NATIVE=0 / no
                # toolchain): the per-column Python vote below is
                # bit-exact but an engine-level demotion — surface it
                # (VERDICT r3 weak #4)
                print("pwasm: native consensus vote unavailable; using "
                      "per-column host vote", file=sys.stderr)
                self.engine_fallbacks += 1
        cols_removed = 0
        consensus = bytearray()
        for col in range(cols.mincol, cols.maxcol + 1):
            # votes is None when the native library is unavailable
            # (PWASM_NATIVE=0 / no toolchain): per-column Python vote
            c = int(votes[col - cols.mincol]) if votes is not None \
                else cols.best_char(col)
            if c == 0:
                self._err_zero_cov(col)
            if c in (ord("-"), ord("*")):
                if remove_cons_gaps:
                    self.remove_column(col - cols_removed)
                    cols_removed += 1
                    continue
                c = ord("*")
            consensus.append(c)
        self.consensus = consensus
        # X-drop clipping refinement: one 2-D pass over all members
        # (refineMSA's member loop, GapAssem.cpp:1169-1180; members are
        # independent given the fixed consensus, so batching is exact)
        from pwasm_tpu.align.gapseq import refine_clipping_batch

        def _cpos(s):
            return s.offset - self.minoffset - cols.mincol

        if refine_clipping:
            self.engine_fallbacks += refine_clipping_batch(
                self.seqs, bytes(self.consensus),
                [_cpos(s) for s in self.seqs], device=device, mesh=mesh,
                supervisor=supervisor)
        second: list = []
        for s in self.seqs:
            grem = s.remove_clip_gaps() if remove_cons_gaps else 0
            if grem != 0 and refine_clipping:
                second.append(s)
        if second:
            self.engine_fallbacks += refine_clipping_batch(
                second, bytes(self.consensus),
                [_cpos(s) for s in second], skip_dels=True,
                device=device, mesh=mesh, supervisor=supervisor)
        self.refined = True

    # ---- clipping transaction (library capability) ---------------------
    def eval_clipping(self, seq: GapSeq, c5: int, c3: int, clipmax: float,
                      clipops: "AlnClipOps") -> bool:
        """Propagate a proposed end-trim of ``seq`` to every member,
        refusing if any member would be over-clipped
        (GSeqAlign::evalClipping, GapAssem.cpp:823-996)."""
        if c5 >= 0:
            pos = seq.seqlen - c5 - 1 if seq.revcompl != 0 else c5
            alpos = self._alpos_of(seq, pos)
            for s in self.seqs:
                if s is seq:
                    if not clipops.add5(s, c5, clipmax):
                        return False
                    continue
                if s.offset >= alpos:
                    if seq.revcompl != 0:
                        return False  # s would be clipped entirely
                    continue
                spos = s.find_walk_pos(alpos)
                if spos >= s.seqlen:
                    if seq.revcompl == 0:
                        return False
                    continue
                if seq.revcompl != 0:  # trimming the right side of the msa
                    if s.revcompl != 0:
                        if not clipops.add5(s, s.seqlen - spos - 1, clipmax):
                            return False
                    else:
                        if not clipops.add3(s, s.seqlen - spos - 1, clipmax):
                            return False
                else:  # trimming the left side
                    if s.revcompl != 0:
                        if not clipops.add3(s, spos, clipmax):
                            return False
                    else:
                        if not clipops.add5(s, spos, clipmax):
                            return False
        if c3 >= 0:
            pos = c3 if seq.revcompl != 0 else seq.seqlen - c3 - 1
            alpos = self._alpos_of(seq, pos)
            for s in self.seqs:
                if s is seq:
                    if not clipops.add3(s, c3, clipmax):
                        return False
                    continue
                if s.offset >= alpos:
                    if seq.revcompl == 0:
                        return False
                    continue
                spos = s.find_walk_pos(alpos)
                if spos >= s.seqlen:
                    if seq.revcompl != 0:
                        return False
                    continue
                if seq.revcompl != 0:  # trim left side
                    if s.revcompl != 0:
                        if not clipops.add3(s, spos, clipmax):
                            return False
                    else:
                        if not clipops.add5(s, spos, clipmax):
                            return False
                else:  # trim right side
                    if s.revcompl != 0:
                        if not clipops.add5(s, s.seqlen - spos - 1, clipmax):
                            return False
                    else:
                        if not clipops.add3(s, s.seqlen - spos - 1, clipmax):
                            return False
        return True

    def apply_clipping(self, clipops: "AlnClipOps") -> None:
        """(GSeqAlign::applyClipping, GapAssem.cpp:814-822)"""
        for s, clp5, clp3 in clipops.ops:
            if clp5 >= 0:
                s.clp5 = clp5
            if clp3 >= 0:
                s.clp3 = clp3

    # ---- output ---------------------------------------------------------
    def print_layout(self, f: IO[str], sep: str = "") -> None:
        """Debug layout view (GSeqAlign::print, GapAssem.cpp:1013-1037)."""
        self.finalize()
        width = max((len(s.name) for s in self.seqs), default=0)
        if sep:
            f.write(f"{'':>{width}}   " + sep * self.length + "\n")
        for s in self.seqs:
            orientation = "-" if s.revcompl == 1 else "+"
            f.write(f"{s.name:>{width}} {orientation} ")
            s.print_gapped_seq(f, self.minoffset)

    def write_msa(self, f: IO[str], linelen: int = 60) -> None:
        """Multifasta MSA (GSeqAlign::writeMSA, GapAssem.cpp:1039-1046)."""
        self.finalize()
        for s in self.seqs:
            s.print_mfasta(f, linelen)

    def write_ace(self, f: IO[str], name: str,
                  remove_cons_gaps: bool = True,
                  refine_clipping: bool = True,
                  device: bool = False) -> None:
        """ACE contig output (GSeqAlign::writeACE, GapAssem.cpp:1200-1262)."""
        if not self.refined:
            self.refine_msa(remove_cons_gaps, refine_clipping, device=device)
        fwd = sum(1 for s in self.seqs if s.revcompl == 0)
        rvs = self.count() - fwd
        cons_dir = "C" if rvs > fwd else "U"
        f.write(f"CO {name} {len(self.consensus)} {self.count()} 0 "
                f"{cons_dir}\n")
        cons = self.consensus.decode("ascii", "replace")
        for i in range(0, len(cons), 60):
            f.write(cons[i:i + 60] + "\n")
        f.write("\nBQ \n\n")
        mincol = self.msacolumns.mincol
        for s in self.seqs:
            sc = "U" if s.revcompl == 0 else "C"
            f.write(f"AF {s.name} {sc} "
                    f"{s.offset - self.minoffset - mincol + 1}\n")
        f.write("\n")
        for s in self.seqs:
            gapped_len = s.seqlen + s.numgaps
            f.write(f"RD {s.name} {gapped_len} 0 0\n")
            s.print_gapped_fasta(f)
            clpl, clpr = s.clip_lr()
            l, r = clpl, clpr
            for j in range(1, r + 1):
                clpr += int(s.gaps[s.seqlen - j])
            for j in range(l + 1):
                clpl += int(s.gaps[j])
            seql = clpl + 1
            seqr = gapped_len - clpr
            if seqr < seql:
                print(f"Bad trimming for {s.name} of gapped len "
                      f"{gapped_len} ({seql}, {seqr})", file=sys.stderr)
                seqr = seql + 1
            f.write(f"\nQA {seql} {seqr} {seql} {seqr}\nDS \n\n")

    def write_cons(self, f: IO[str], name: str,
                   remove_cons_gaps: bool = True,
                   refine_clipping: bool = True,
                   device: bool = False, linelen: int = 60) -> None:
        """Consensus sequence as FASTA (refined on demand, like
        write_ace/write_info; '*' marks kept all-gap columns)."""
        if not self.refined:
            self.refine_msa(remove_cons_gaps, refine_clipping, device=device)
        cons = self.consensus.decode("ascii", "replace")
        f.write(f">{name}_cons {self.count()} seqs\n")
        for i in range(0, len(cons), linelen):
            f.write(cons[i:i + linelen] + "\n")

    def write_info(self, f: IO[str], name: str,
                   remove_cons_gaps: bool = True,
                   refine_clipping: bool = True,
                   device: bool = False) -> None:
        """Contig-info output with per-seq pid and run-length alndata
        (GSeqAlign::writeInfo, GapAssem.cpp:1264-1367).

        Parity notes (we mirror the code, not the comments):
        - the reference's comment documents alndata as '5g4d2g2-30d12g'
          (offsets before every indel) but the code only emits the
          '<ofs><type><len>-' form for indels longer than 2; short indels
          emit bare type characters (GapAssem.cpp:1337-1344);
        - ``asml``/``asmr`` carry a double '+1' (GapAssem.cpp:1305-1307),
          so the pid comparison reads the consensus shifted one column
          right of the sequence — pid is systematically understated
          (usually 0 for perfect alignments)."""
        if not self.refined:
            self.refine_msa(remove_cons_gaps, refine_clipping, device=device)
        cons = self.consensus.decode("ascii", "replace")
        f.write(f">{name} {self.count()} {cons}\n")
        mincol = self.msacolumns.mincol
        for s in self.seqs:
            gapped_len = s.seqlen + s.numgaps
            seqoffset = s.offset - self.minoffset - mincol + 1
            clpl, clpr = s.clip_lr()
            asml = seqoffset + 1
            asmr = asml - 1
            pid = 0.0
            aligned_len = 0
            indel_ofs = 0
            alndata: list[str] = []
            for j in range(s.clp5, s.seqlen - s.clp3):
                indel = int(s.gaps[j])
                indel_type = ""
                asmr += indel + 1
                if indel < 0:
                    indel_type = "d"
                    indel = -indel
                else:
                    if indel > 0:
                        indel_type = "g"
                    else:
                        indel_ofs += 1
                    if (0 <= asmr - 1 < len(cons)
                            and chr(s.seq[j]).upper()
                            == cons[asmr - 1].upper()):
                        pid += 1
                    aligned_len += 1
                if indel_type:
                    if indel > 2:
                        alndata.append(f"{indel_ofs}{indel_type}{indel}-")
                    else:
                        alndata.append(indel_type * indel)
                    indel_ofs = 0
            pid = (pid * 100.0) / aligned_len if aligned_len else 0.0
            seql = clpl + 1
            seqr = len(s.seq) - clpr
            if seqr < seql:
                print(f"WARNING: Bad trimming for {s.name} of gapped len "
                      f"{gapped_len} ({seql}, {seqr})", file=sys.stderr)
                seqr = seql + 1
            if s.revcompl:
                seql, seqr = seqr, seql
            f.write(f"{s.name} {len(s.seq)} {seqoffset} {asml} {asmr} "
                    f"{seql} {seqr} {pid:4.2f} {''.join(alndata)}\n")


class AlnClipOps:
    """Staged clipping transaction (reference AlnClipOps,
    GapAssem.h:183-253): collect per-seq clip updates, refusing any that
    exceed ``clipmax`` or leave a read under 25% of its length."""

    def __init__(self):
        self.ops: list[tuple[GapSeq, int, int]] = []
        self.total = 0
        self.d5 = 0
        self.d3 = 0
        self.q_rev = False

    @staticmethod
    def _maxovh(s: GapSeq, clipmax: float) -> int:
        return int(clipmax) if clipmax > 1 else int(round(
            clipmax * float(s.seqlen)))

    def add5(self, s: GapSeq, clp: int, clipmax: float) -> bool:
        if s.clp5 < clp:
            if clipmax > 0 and clp > self._maxovh(s, clipmax):
                return False
            if s.seqlen - s.clp3 - clp < (s.seqlen >> 2):
                return False
            self.total += 10000 + clp - s.clp5
            self.ops.append((s, clp, -1))
        return True

    def add3(self, s: GapSeq, clp: int, clipmax: float) -> bool:
        if s.clp3 < clp:
            if clipmax > 0 and clp > self._maxovh(s, clipmax):
                return False
            if s.seqlen - s.clp5 - clp < (s.seqlen >> 2):
                return False
            self.total += 10000 + clp - s.clp3
            self.ops.append((s, -1, clp))
        return True

    def add(self, s: GapSeq, clp5: int, clp3: int, clipmax: float) -> bool:
        newclp5 = -1
        newclp3 = -1
        addsc = 0
        if s.clp5 < clp5:
            if clipmax > 0 and clp5 > self._maxovh(s, clipmax):
                return False
            if s.seqlen - s.clp3 - clp5 < (s.seqlen >> 2):
                return False
            addsc += 10000 + clp5 - s.clp5
            newclp5 = clp5
        else:
            clp5 = s.clp5
        if s.clp3 < clp3:
            if clipmax > 0 and clp3 > self._maxovh(s, clipmax):
                return False
            if s.seqlen - clp5 - clp3 < (s.seqlen >> 2):
                return False
            addsc += 10000 + clp3 - s.clp3
            newclp3 = clp3
        if addsc > 0:
            self.total += addsc
            self.ops.append((s, newclp5, newclp3))
        return True
