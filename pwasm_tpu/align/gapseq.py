"""Gapped sequence model.

Equivalent of the reference's GASeq (GapAssem.h:35-138, GapAssem.cpp:27-591):
a sequence plus a per-base gap array ``gaps[i]`` = number of gap columns
*before* base ``i`` in the MSA layout; a negative value marks the base
itself as deleted.  Offsets position the sequence in the layout.

The gap array is a numpy int32 tensor, so layout positions are prefix sums
(`layout_walk_positions`) rather than the reference's O(pos) walks — the
same math the device kernels use.
"""

from __future__ import annotations

import sys

import numpy as np

from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.errors import PwasmError

# per-seq bit flags (GapAssem.h:12-16)
FLAG_IS_REF = 0
FLAG_HAS_PARENT = 1
FLAG_PREPPED = 2
FLAG_BAD_ALN = 7


class GapSeq:
    """A sequence in an MSA layout: bases + gap counts + offsets + clips."""

    def __init__(self, name: str, descr: str = "", seq: bytes = b"",
                 seqlen: int | None = None, offset: int = 0,
                 clp5: int = 0, clp3: int = 0, revcompl: int = 0):
        self.name = name
        self.descr = descr or ""
        self.seq = bytearray(seq)
        self.seqlen = len(seq) if seqlen is None else seqlen
        self.gaps = np.zeros(self.seqlen, dtype=np.int32)
        self.numgaps = 0
        self.offset = offset
        self.ng_ofs = offset
        self.revcompl = revcompl
        self.clp5 = clp5
        self.clp3 = clp3
        self.ext5 = 0
        self.ext3 = 0
        self.flags = 0
        self.msa = None
        self.msaidx = -1
        self.delops: list[tuple[int, bool]] = []  # (pos, revcompl) pairs
        # per-base overlap coverage, opt-in (the reference's compile-time
        # ALIGN_COVERAGE_DATA capability, GapAssem.h:42-46)
        self.cov: np.ndarray | None = None

    # ---- flags ----------------------------------------------------------
    def set_flag(self, bit: int) -> None:
        self.flags |= 1 << bit

    def clear_flag(self, bit: int) -> None:
        self.flags ^= 1 << bit

    def has_flag(self, bit: int) -> bool:
        return (self.flags >> bit) & 1 != 0

    # ---- basic ops ------------------------------------------------------
    def __repr__(self):
        return (f"GapSeq({self.name!r}, len={self.seqlen}, "
                f"offset={self.offset}, gaps={self.numgaps})")

    def allupper(self) -> None:
        self.seq = bytearray(bytes(self.seq).upper())

    def reverse_complement_bases(self) -> None:
        """RC the base string only (FastaSeq::reverseComplement)."""
        self.seq = bytearray(revcomp(bytes(self.seq)))

    def end_offset(self) -> int:
        return self.offset + self.seqlen + self.numgaps

    def end_ng_offset(self) -> int:
        return self.ng_ofs + self.seqlen

    def gap(self, pos: int) -> int:
        return int(self.gaps[pos])

    def set_gap(self, pos: int, gaplen: int = 1) -> None:
        """Set the gap length before ``pos`` (GapAssem.cpp:104-111)."""
        if pos < 0 or pos >= self.seqlen:
            raise PwasmError(
                f"Error: invalid gap position ({pos + 1}) given for "
                f"sequence {self.name}\n")
        self.numgaps -= int(self.gaps[pos])
        self.gaps[pos] = gaplen
        self.numgaps += gaplen

    def add_gap(self, pos: int, gapadd: int) -> None:
        """Extend the gap before ``pos`` (GapAssem.cpp:113-120)."""
        if pos < 0 or pos >= self.seqlen:
            raise PwasmError(
                f"Error: invalid gap position ({pos + 1}) given for "
                f"sequence {self.name}\n")
        self.numgaps += gapadd
        self.gaps[pos] += gapadd

    def remove_base(self, pos: int) -> None:
        """Remove one layout column at ``pos``: a gap if one exists, else
        the base itself (gap count goes negative = deleted base;
        GapAssem.cpp:122-180)."""
        if pos < 0 or pos >= self.seqlen:
            raise PwasmError(
                f"Error: invalid gap position ({pos + 1}) given for "
                f"sequence {self.name}\n")
        self.gaps[pos] -= 1
        self.numgaps -= 1

    # ---- layout math ----------------------------------------------------
    def layout_walk_positions(self) -> np.ndarray:
        """W[j] = layout position one past base j, i.e. the reference's
        ``salpos`` after processing position j in its walk loops
        (GapAssem.cpp:739-744).  The first j with W[j] > alpos is the walk's
        stopping position.  Monotone nondecreasing, so searchsorted replaces
        the O(pos) walk."""
        return self.offset + np.cumsum(1 + self.gaps.astype(np.int64))

    def find_walk_pos(self, alpos: int) -> int:
        """First position j with W[j] > alpos (== reference walk result);
        returns seqlen if the walk runs off the end."""
        w = self.layout_walk_positions()
        return int(np.searchsorted(w, alpos, side="right"))

    # ---- gap/strand transforms -----------------------------------------
    def reverse_gaps(self) -> None:
        """Reverse the gap array in place, keeping index 0 fixed
        (GapAssem.cpp:351-364 — 'shifted by 1 because the first ofs is
        always 0')."""
        if self.seqlen > 1:
            self.gaps[1:] = self.gaps[1:][::-1]

    # ---- coverage tracking (opt-in; the reference's compile-time
    # ALIGN_COVERAGE_DATA capability, GapAssem.h:42-46,131-133) ---------
    def enable_coverage(self) -> None:
        """Allocate the per-base coverage array (zeros), like the
        GCALLOC in the reference ctors (GapAssem.cpp:36-79)."""
        if self.cov is None:
            self.cov = np.zeros(self.seqlen, dtype=np.int32)

    def add_coverage(self, other: "GapSeq") -> None:
        """Merge another instance's coverage of the SAME sequence,
        flipping when orientations differ (GASeq::addCoverage,
        GapAssem.cpp:394-410)."""
        if self.seqlen != other.seqlen:
            raise ValueError(
                f"invalid addCoverage {self.name}(len {self.seqlen}) vs "
                f"{other.name}(len {other.seqlen})")
        if self.cov is None or other.cov is None:
            return
        if self.revcompl != other.revcompl:
            self.cov += other.cov[::-1]
        else:
            self.cov += other.cov

    def rev_complement(self, alignlen: int = 0) -> None:
        """Reverse-complement within an alignment layout
        (GASeq::revComplement, GapAssem.cpp:366-392)."""
        if alignlen > 0:
            self.offset = alignlen - self.end_offset()
            if self.msa is not None:
                self.ng_ofs = self.msa.ng_len - self.end_ng_offset()
                if self.msa.minoffset > self.offset:
                    self.msa.minoffset = self.offset
                if self.msa.ng_minofs > self.ng_ofs:
                    self.msa.ng_minofs = self.ng_ofs
        self.revcompl = 0 if self.revcompl else 1
        if len(self.seq) == self.seqlen:
            self.reverse_complement_bases()
        self.reverse_gaps()
        if self.cov is not None:  # GapAssem.cpp:383-391
            self.cov = self.cov[::-1].copy()

    def prep_seq(self) -> None:
        """Apply deferred deletions, then RC if needed; once per sequence
        (GASeq::prepSeq, GapAssem.cpp:89-101)."""
        for pos, rc in self.delops:
            p = len(self.seq) - pos - 1 if rc else pos
            self.remove_base(p)
        if self.revcompl == 1:
            self.reverse_complement_bases()
        self.set_flag(FLAG_PREPPED)

    def clip_lr(self) -> tuple[int, int]:
        """(clipL, clipR) in layout orientation (strand-aware aliasing of
        clp5/clp3, e.g. GapAssem.cpp:188-189)."""
        if self.revcompl != 0:
            return self.clp3, self.clp5
        return self.clp5, self.clp3

    def remove_clip_gaps(self) -> int:
        """Zero gaps inside the clipped ends, fixing the offset
        (GapAssem.cpp:522-549)."""
        clipL, clipR = self.clip_lr()
        delgaps_l = 0
        delgaps_r = 0
        for i in range(self.seqlen):
            if i <= clipL:
                delgaps_l += int(self.gaps[i])
                self.gaps[i] = 0
                continue
            if i >= self.seqlen - clipR:
                delgaps_r += int(self.gaps[i])
                self.gaps[i] = 0
        self.offset += delgaps_l
        self.numgaps -= delgaps_l + delgaps_r
        return delgaps_l + delgaps_r

    # ---- X-drop end re-alignment ---------------------------------------
    XDROP = -16
    MATCH_SC = 1
    MISMATCH_SC = -3

    def refine_clipping(self, cons: bytes, cpos: int,
                        skip_dels: bool = False) -> None:
        """Re-align the clipped ends against the consensus with an X-drop
        extension, updating clp5/clp3 (GASeq::refineClipping,
        GapAssem.cpp:182-349).  ``cpos`` is this sequence's start column
        on the consensus.

        Delegates to ``refine_clipping_batch`` with a single member —
        ONE vectorized implementation serves both the per-member and the
        whole-MSA paths, and the member-by-member fuzz against the
        transliterated reference walk (``refine_clipping_scalar``,
        tests/test_gapseq_refine.py) gates them both.
        """
        refine_clipping_batch([self], cons, [cpos], skip_dels=skip_dels)

    def refine_clipping_scalar(self, cons: bytes, cpos: int,
                               skip_dels: bool = False) -> None:
        """Direct transliteration of the reference walk (the parity
        oracle for the vectorized ``refine_clipping``)."""
        if self.clp3 == 0 and self.clp5 == 0:
            return
        cons_len = len(cons)
        rev = self.revcompl != 0
        clipL, clipR = self.clip_lr()
        glen = self.seqlen + self.numgaps
        allocsize = glen
        gclipR = clipR
        gclipL = clipL
        if skip_dels:
            for i in range(1, clipR + 1):
                if self.gaps[self.seqlen - i] < 0:
                    allocsize += 1
                else:
                    gclipR += int(self.gaps[self.seqlen - i])
            for i in range(clipL):
                if self.gaps[i] < 0:
                    allocsize += 1
                else:
                    gclipL += int(self.gaps[i])
        else:
            for i in range(1, clipR + 1):
                gclipR += int(self.gaps[self.seqlen - i])
            for i in range(clipL):
                gclipL += int(self.gaps[i])
        gseq = bytearray()
        gxpos: list[int] = []
        for i in range(self.seqlen):
            g = int(self.gaps[i])
            if g < 0:
                if not skip_dels:
                    continue
                if clipL <= i < self.seqlen - clipR:
                    continue
                glen += 1
            for _ in range(max(g, 0)):
                gseq.append(ord("*"))
                gxpos.append(-1)
            gseq.append(self.seq[i])
            gxpos.append(i)
        if glen != allocsize:
            raise PwasmError(
                f"Length mismatch (allocsize {allocsize} vs. glen {glen}) "
                f"while refineClipping for seq {self.name} !\n")
        star = ord("*")

        def write_back():
            # the reference's clipL/clipR are int& aliases of clp5/clp3, so
            # every increment persists even on early returns — mirror that
            if rev:
                self.clp3, self.clp5 = clipL, clipR
            else:
                self.clp5, self.clp3 = clipL, clipR

        if clipR > 0:
            cp = cpos + glen - gclipR - 1
            sp = glen - gclipR - 1
            ok = True
            while (sp < 0 or cp < 0 or cp >= cons_len
                   or gseq[sp] != cons[cp] or gseq[sp] == star):
                if sp >= 0 and gseq[sp] != star:
                    clipR += 1
                sp -= 1
                cp -= 1
                if sp < gclipL:
                    print(f"Warning: reached clipL trying to find an "
                          f"initial match on {self.name}!", file=sys.stderr)
                    ok = False
                    break
            if not ok:
                write_back()
                return
            score = self.MATCH_SC
            maxscore = self.MATCH_SC
            startpos = sp
            bestpos = sp
            while score > self.XDROP:
                cp += 1
                sp += 1
                if cp >= cons_len or sp >= glen:
                    break
                if gseq[sp] == cons[cp]:
                    if gseq[sp] != star:
                        score += self.MATCH_SC
                        if score > maxscore:
                            bestpos = sp
                            maxscore = score
                else:
                    if gseq[sp] != star:
                        score += self.MISMATCH_SC
            if bestpos > startpos:
                clipR = self.seqlen - gxpos[bestpos] - 1
        if clipL > 0:
            cp = cpos + gclipL
            sp = gclipL
            ok = True
            while (sp >= glen or cp >= cons_len or cp < 0
                   or gseq[sp] != cons[cp] or gseq[sp] == star):
                if sp < glen and gseq[sp] != star:
                    clipL += 1
                sp += 1
                cp += 1
                if sp >= glen - gclipR:
                    print(f"Warning: reached clipR trying to find an "
                          f"initial match on {self.name}!", file=sys.stderr)
                    ok = False
                    break
            if not ok:
                write_back()
                return
            score = self.MATCH_SC
            maxscore = self.MATCH_SC
            startpos = sp
            bestpos = sp
            while score > self.XDROP:
                cp -= 1
                sp -= 1
                if cp < 0 or sp < 0:
                    break
                if gseq[sp] == cons[cp]:
                    if gseq[sp] != star:
                        score += self.MATCH_SC
                        if score > maxscore:
                            bestpos = sp
                            maxscore = score
                else:
                    if gseq[sp] != star:
                        score += self.MISMATCH_SC
            if bestpos < startpos:
                clipL = gxpos[bestpos]
        write_back()

    # ---- printers -------------------------------------------------------
    def _check_loaded(self, what: str) -> None:
        if len(self.seq) == 0 or len(self.seq) != self.seqlen:
            raise PwasmError(
                f"GapSeq {what} Error: invalid sequence data '{self.name}' "
                f"(len={len(self.seq)}, seqlen={self.seqlen})\n")

    def print_gapped_seq(self, f, baseoffs: int = 0) -> None:
        """Debug layout line (GASeq::printGappedSeq, GapAssem.cpp:412-440)."""
        self._check_loaded("print")
        clipL, clipR = self.clip_lr()
        out = [" " * (self.offset - baseoffs)]
        for i in range(self.seqlen):
            g = int(self.gaps[i])
            if g < 0:
                continue  # deleted base
            out.append("-" * g)
            c = chr(self.seq[i])
            if i < clipL or i >= self.seqlen - clipR:
                c = c.lower()
            out.append(c)
        f.write("".join(out) + "\n")

    def print_gapped_fasta(self, f) -> None:
        """ACE-style gapped sequence, '*' gaps, 60-col wrap
        (GASeq::printGappedFasta, GapAssem.cpp:442-480; the exact-multiple
        trailing blank line is preserved)."""
        self._check_loaded("print")
        out = []
        printed = 0
        for i in range(self.seqlen):
            g = int(self.gaps[i])
            if g < 0:
                continue
            for _ in range(g):
                out.append("*")
                printed += 1
                if printed == 60:
                    out.append("\n")
                    printed = 0
            printed += 1
            if printed == 60:
                out.append(chr(self.seq[i]) + "\n")
                printed = 0
            else:
                out.append(chr(self.seq[i]))
        if printed < 60:
            out.append("\n")
        f.write("".join(out))

    def print_mfasta(self, f, llen: int = 60) -> None:
        """Offset-padded multifasta record (GASeq::printMFasta,
        GapAssem.cpp:482-520)."""
        self._check_loaded("print")
        if self.descr:
            f.write(f">{self.name} {self.descr}\n")
        else:
            f.write(f">{self.name}\n")
        out = []
        printed = 0

        def put(ch: str):
            nonlocal printed
            printed += 1
            if printed == llen:
                out.append(ch + "\n")
                printed = 0
            else:
                out.append(ch)

        for _ in range(self.offset):
            put("-")
        for i in range(self.seqlen):
            g = int(self.gaps[i])
            if g < 0:
                continue
            for _ in range(g):
                put("-")
            put(chr(self.seq[i]))
        if printed < llen:
            out.append("\n")
        f.write("".join(out))


# ---------------------------------------------------------------------------
# batched X-drop clipping refinement: all MSA members in ONE 2-D pass
# ---------------------------------------------------------------------------
def refine_clipping_batch(seqs: list[GapSeq], cons: bytes,
                          cposes: list[int],
                          skip_dels: bool = False,
                          device: bool = False,
                          mesh=None, supervisor=None) -> int:
    """Refine the clipped ends of MANY members against the consensus in
    one vectorized pass (the refineMSA member loop,
    GapAssem.cpp:1133-1183, flattened into (members, layout) tensors).

    Per member this runs the exact ``GapSeq.refine_clipping`` program —
    same initial-match seek, same X-drop extension, same clip-bump and
    abort semantics (fuzz-gated member-by-member in
    tests/test_gapseq_refine.py) — but the seek and extension passes are
    single 2-D numpy programs over every clipped member at once instead
    of a Python loop of 1-D passes.  Members with no clips are skipped
    outright (the common case costs nothing).

    With ``device`` the two phase computations run as one jitted dense
    program on the accelerator (ops/refine_clip.py) over the same
    padded tensors — bit-exact — with the host layout build and
    write-back unchanged.  Returns the number of engine-level device
    demotions (0 on success or on a pure-host run; 1 when a requested
    device pass fell back to the host phases).
    """
    sel = [i for i, s in enumerate(seqs) if s.clp5 or s.clp3]
    if not sel:
        return 0
    cons_arr = np.frombuffer(cons, dtype=np.uint8)
    cons_len = len(cons)
    star = ord("*")
    M = len(sel)
    XDROP = GapSeq.XDROP
    MATCH_SC = GapSeq.MATCH_SC
    MISMATCH_SC = GapSeq.MISMATCH_SC

    # --- per-member gapped layout build (ragged -> padded 2-D) ----------
    # NB two different lengths per member, exactly like the 1-D pass:
    # ``glen`` is the REFERENCE walk length (seqlen + numgaps, plus the
    # clip-kept deletions under skip_dels — GapAssem.cpp:243) used for
    # every bound, while ``totals`` is the actual rendered layout array
    # length used for index validity; doubly-deleted bases (gap <= -2)
    # make them differ.
    glen = np.zeros(M, dtype=np.int64)
    totals = np.zeros(M, dtype=np.int64)
    gclipL = np.zeros(M, dtype=np.int64)
    gclipR = np.zeros(M, dtype=np.int64)
    clipL0 = np.zeros(M, dtype=np.int64)
    clipR0 = np.zeros(M, dtype=np.int64)
    seqlens = np.zeros(M, dtype=np.int64)
    cpos = np.asarray([cposes[i] for i in sel], dtype=np.int64)
    rows = []
    xrows = []
    for k, i in enumerate(sel):
        s = seqs[i]
        g = s.gaps.astype(np.int64)
        cl, cr = s.clip_lr()
        clipL0[k], clipR0[k] = cl, cr
        seqlens[k] = s.seqlen
        glen0 = s.seqlen + s.numgaps
        allocsize = glen0
        gl, gr = cl, cr
        if skip_dels:
            right = g[s.seqlen - cr:] if cr else g[:0]
            left = g[:cl]
            allocsize += int((right < 0).sum()) + int((left < 0).sum())
            gr += int(right[right >= 0].sum())
            gl += int(left[left >= 0].sum())
            in_clip = np.zeros(s.seqlen, dtype=bool)
            if cl:
                in_clip[:cl] = True
            if cr:
                in_clip[s.seqlen - cr:] = True
            include = (g >= 0) | in_clip
        else:
            gr += int(g[s.seqlen - cr:].sum()) if cr else 0
            gl += int(g[:cl].sum())
            include = g >= 0
        gclipL[k], gclipR[k] = gl, gr
        glen[k] = glen0 + int((include & (g < 0)).sum())
        if glen[k] != allocsize:
            raise PwasmError(
                f"Length mismatch (allocsize {allocsize} vs. glen "
                f"{glen[k]}) while refineClipping for seq {s.name} !\n")
        stars = np.maximum(g, 0)
        counts = stars + include
        ends = np.cumsum(counts)
        total = int(ends[-1]) if s.seqlen else 0
        totals[k] = total
        gseq = np.full(total, star, dtype=np.uint8)
        gxpos = np.full(total, -1, dtype=np.int64)
        seq_arr = np.frombuffer(bytes(s.seq), dtype=np.uint8)
        base_idx = (ends - 1)[include]
        gseq[base_idx] = seq_arr[include]
        gxpos[base_idx] = np.nonzero(include)[0]
        rows.append(gseq)
        xrows.append(gxpos)
    L = max(1, int(totals.max()))
    gseq2 = np.full((M, L), star, dtype=np.uint8)
    gxpos2 = np.full((M, L), -1, dtype=np.int64)
    for k in range(M):
        gseq2[k, :totals[k]] = rows[k]
        gxpos2[k, :totals[k]] = xrows[k]

    demotions = 0
    if device:
        def _device_phases():
            from pwasm_tpu.ops.refine_clip import refine_phases_device
            return refine_phases_device(
                gseq2, gxpos2, cons_arr, cpos, glen, totals, gclipL,
                gclipR, clipL0, clipR0, seqlens, XDROP, MATCH_SC,
                MISMATCH_SC, mesh=mesh)

        try:
            if supervisor is not None:
                # supervised: bounded retries + clip-bound guardrails
                # before the host demotion (resilience.supervisor)
                from pwasm_tpu.resilience.guardrails import \
                    check_refine_clips
                clipL, clipR, missR, missL = supervisor.run(
                    "refine", _device_phases,
                    validate=lambda r: check_refine_clips(
                        r[0], r[1], seqlens))
            else:
                clipL, clipR, missR, missL = _device_phases()
        except Exception as e:  # backend down / jax unavailable:
            # replay on the host phases (bit-exact), surfaced by count
            from pwasm_tpu.core.errors import PwasmError as _PErr
            if isinstance(e, _PErr):
                raise   # --fallback=fail (ResilienceError): abort loudly
            if supervisor is not None:
                # supervised give-up: count + warn through the
                # supervisor so res_fallbacks reflects this degradation
                supervisor.note_degraded(
                    "refine", "degrading clip refinement to the host "
                    f"phases ({e})")
            else:
                from pwasm_tpu.utils import exc_detail

                print(f"pwasm: device clip refinement fell back to "
                      f"host ({exc_detail(e)})", file=sys.stderr)
            demotions = 1
        else:
            for km in np.nonzero(missR)[0]:
                print(f"Warning: reached clipL trying to find an "
                      f"initial match on {seqs[sel[km]].name}!",
                      file=sys.stderr)
            for km in np.nonzero(missL)[0]:
                print(f"Warning: reached clipR trying to find an "
                      f"initial match on {seqs[sel[km]].name}!",
                      file=sys.stderr)
            _write_back_clips(seqs, sel, clipL, clipR)
            return 0

    clipL = clipL0.copy()
    clipR = clipR0.copy()
    aborted = np.zeros(M, dtype=bool)
    ridx = np.arange(M)

    cons2 = np.broadcast_to(cons_arr, (M, cons_len))
    CH = 128   # chunk of walk steps per round: the seek usually hits and
    #            the X-drop usually fires within a few steps, so chunked
    #            scans with early exit do O(M x CH) work instead of
    #            O(M x layout)

    def take2(arr2, pos, valid, width):
        out = np.zeros(pos.shape, dtype=arr2.dtype)
        if width <= 0:          # degenerate: empty consensus/layout
            return out
        safe = np.clip(pos, 0, width - 1)
        vals = np.take_along_axis(arr2, safe, axis=1)
        out[valid] = vals[valid]
        return out

    def seek2(active, sp0, n_cand, direction):
        """Batched initial-match seek, chunked with early exit.  Returns
        (hit row mask, first-hit step k, bumps) where bumps counts
        non-star candidates before the hit — or over ALL candidates for
        rows with no hit (the scalar abort semantics)."""
        found = np.zeros(M, dtype=bool)
        k = np.zeros(M, dtype=np.int64)
        bumps = np.zeros(M, dtype=np.int64)
        Dmax = int(n_cand[active].max()) if active.any() else 0
        for d0 in range(0, Dmax, CH):
            todo = active & ~found & (d0 < n_cand)
            if not todo.any():
                break
            d = d0 + np.arange(min(CH, Dmax - d0))[None, :]
            sp = sp0[:, None] + direction * d
            cmask = todo[:, None] & (d < n_cand[:, None])
            valid_s = cmask & (sp >= 0) & (sp < totals[:, None])
            gs = take2(gseq2, sp, valid_s, L)
            cp = cpos[:, None] + sp
            valid_c = cmask & (cp >= 0) & (cp < cons_len)
            cs = take2(cons2, cp, valid_c, cons_len)
            hit = valid_s & valid_c & (gs == cs) & (gs != star)
            bump = valid_s & (gs != star)
            hh = hit.any(axis=1)
            kk = np.argmax(hit, axis=1)
            bc = np.cumsum(bump, axis=1)
            newly = todo & hh
            k[newly] = d0 + kk[newly]
            bumps[newly] += (bc[ridx, kk] - bump[ridx, kk])[newly]
            not_yet = todo & ~hh
            if bump.shape[1]:
                bumps[not_yet] += bc[not_yet, -1]
            found |= newly
        return found & active, k, bumps

    def extend2(active, sp_m, direction):
        """Batched X-drop extension, chunked with early exit; returns
        bestpos (== sp_m when no improvement)."""
        cp_m = cpos + sp_m
        if direction > 0:
            K = np.minimum(glen - 1 - sp_m, cons_len - 1 - cp_m)
        else:
            K = np.minimum(sp_m, cp_m)
        K = np.where(active, np.maximum(K, 0), 0)
        Kmax = int(K.max()) if active.any() else 0
        best = np.full(M, XDROP, dtype=np.int64)
        bestk = np.zeros(M, dtype=np.int64)
        carry = np.full(M, MATCH_SC, dtype=np.int64)
        alive = active & (K > 0)
        for k0 in range(0, Kmax, CH):
            if not alive.any():
                break
            w = min(CH, Kmax - k0)
            ks = k0 + 1 + np.arange(w)[None, :]
            within = alive[:, None] & (ks <= K[:, None])
            pos = sp_m[:, None] + direction * ks
            gs = take2(gseq2, pos, within, L)
            cp2 = cp_m[:, None] + direction * ks
            cs = take2(cons2, cp2, within, cons_len)
            nonstar = within & (gs != star)
            eq = gs == cs
            delta = np.where(nonstar,
                             np.where(eq, MATCH_SC, MISMATCH_SC), 0)
            scores = carry[:, None] + np.cumsum(delta, axis=1)
            stop = within & (scores <= XDROP)
            has_stop = stop.any(axis=1)
            first_stop = np.where(has_stop, np.argmax(stop, axis=1), w)
            in_limit = within & (np.arange(w)[None, :]
                                 <= first_stop[:, None])
            cand = np.where(eq & nonstar & in_limit, scores, XDROP)
            cbest = cand.max(axis=1, initial=XDROP)
            # strict >: an equal max from an earlier chunk keeps the
            # scalar walk's first-occurrence tie-break
            improve = alive & (cbest > best)
            best = np.where(improve, cbest, best)
            bestk = np.where(improve,
                             k0 + 1 + np.argmax(cand, axis=1), bestk)
            carry = scores[:, -1] if w else carry
            alive = alive & ~has_stop & (K > k0 + w)
        improved = active & (best > MATCH_SC)
        return np.where(improved, sp_m + direction * bestk, sp_m)

    # --- clipR phase ----------------------------------------------------
    actR = clipR0 > 0
    if actR.any():
        sp0 = glen - gclipR - 1
        n_cand = np.where(sp0 >= gclipL, sp0 - gclipL + 1, 1)
        has_hit, k, bumps = seek2(actR, sp0, n_cand, -1)
        miss = actR & ~has_hit
        for km in np.nonzero(miss)[0]:
            print(f"Warning: reached clipL trying to find an initial "
                  f"match on {seqs[sel[km]].name}!", file=sys.stderr)
        clipR = np.where(actR, clipR + bumps, clipR)
        aborted |= miss
        hitm = actR & has_hit
        sp_m = sp0 - k
        bestpos = extend2(hitm, sp_m, +1)
        upd = hitm & (bestpos > sp_m)
        newR = seqlens - take2(gxpos2, bestpos[:, None],
                               upd[:, None], L)[:, 0] - 1
        clipR = np.where(upd, newR, clipR)

    # --- clipL phase ----------------------------------------------------
    actL = (clipL0 > 0) & ~aborted
    if actL.any():
        sp0 = gclipL
        hi = glen - gclipR - 1
        n_cand = np.where(hi >= sp0, hi - sp0 + 1, 1)
        has_hit, k, bumps = seek2(actL, sp0, n_cand, +1)
        miss = actL & ~has_hit
        for km in np.nonzero(miss)[0]:
            print(f"Warning: reached clipR trying to find an initial "
                  f"match on {seqs[sel[km]].name}!", file=sys.stderr)
        clipL = np.where(actL, clipL + bumps, clipL)
        hitm = actL & has_hit
        sp_m = sp0 + k
        bestpos = extend2(hitm, sp_m, -1)
        upd = hitm & (bestpos < sp_m)
        newL = take2(gxpos2, bestpos[:, None], upd[:, None], L)[:, 0]
        clipL = np.where(upd, newL, clipL)

    # --- write back (strand-aware aliasing, GapAssem.cpp:188-189) -------
    _write_back_clips(seqs, sel, clipL, clipR)
    return demotions


def _write_back_clips(seqs, sel, clipL, clipR) -> None:
    for k, i in enumerate(sel):
        s = seqs[i]
        if s.revcompl:
            s.clp3, s.clp5 = int(clipL[k]), int(clipR[k])
        else:
            s.clp5, s.clp3 = int(clipL[k]), int(clipR[k])
