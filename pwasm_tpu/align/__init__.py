"""Gapped-sequence / MSA engine (bit-exact CPU path).

Equivalent capability set to the reference's GapAssem library (GapAssem.h,
GapAssem.cpp): gapped-coordinate bookkeeping, gap propagation across an MSA,
progressive pairwise->MSA merging, column voting/consensus, X-drop clip
refinement, and the MFA/ACE/contig-info writers.  The device path
(`pwasm_tpu.ops`) consumes the pileup tensors this layer produces.
"""

from pwasm_tpu.align.gapseq import GapSeq  # noqa: F401
from pwasm_tpu.align.msa import Msa, MsaColumns, best_char_from_counts  # noqa: F401
