"""Device trace hooks (SURVEY.md §5 tracing/profiling).

``device_trace(dir)`` wraps a region in a ``jax.profiler`` trace when a
directory is given: the dump is viewable in TensorBoard/Perfetto and
covers every device program launched inside (the batched analysis
kernels under ``--device=tpu``).  With no directory it is a no-op and
jax is never imported — the CPU path stays jax-free.
"""

from __future__ import annotations

import sys
from contextlib import contextmanager


@contextmanager
def device_trace(profile_dir: str | None, stderr=None):
    if not profile_dir:
        yield
        return
    stderr = stderr or sys.stderr
    import jax

    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        print(f"device trace written to {profile_dir}", file=stderr)
