"""Structured per-run statistics.

Implements the observability the reference gestures at but never ships:
its documented ``-s`` summary is parsed and dropped (pafreport.cpp:20,274,
quirk SURVEY.md §2.5.1), and there are no throughput counters anywhere.
``RunStats`` tracks the run-level counters (alignments, skipped lines,
aligned bases, wall time) and writes one JSON object; the per-event
`Summary` (pwasm_tpu.report.diff_report) remains the -s payload.
"""

from __future__ import annotations

import json
import time
from typing import IO

# The --stats JSON is a VERSIONED schema now that programs (the serve
# daemon's roll-up, the bench gates, fleet wrappers) read it, not just
# eyeballs: additive changes (new keys/blocks) keep the version; a
# renamed/retyped/removed key must bump it.  Documented in
# docs/SERVICE.md ("--stats as an interface").
STATS_VERSION = 1


class RunStats:
    def __init__(self) -> None:
        self.t0 = time.perf_counter()
        self.lines = 0            # PAF lines seen (non-blank, non-comment)
        self.alignments = 0       # alignments accepted for analysis
        self.skipped_bad = 0      # lines dropped by --skip-bad-lines
        self.skipped_dedup = 0    # gene-mode duplicate (q,t) pairs
        self.skipped_self = 0     # query==target self alignments
        self.resumed_past = 0     # alignments skipped by --resume
        self.aligned_bases = 0    # sum of per-alignment target span
        self.events = 0           # diff events reported
        self.device_batches = 0   # device flushes (--device=tpu)
        self.fallback_batches = 0  # device batches replayed on host
        self.device_events = 0    # events analyzed by the device program
        self.scalar_events = 0    # events analyzed on host: out of
        #                           device scope (evtlen > MAX_EV) OR
        #                           part of a fallback-replayed batch
        #                           (then fallback_batches > 0 tells
        #                           the two causes apart)
        self.realigned = 0        # alignments re-aligned (--realign)
        self.msa_dropped = 0      # reported alignments excluded from
        #                           the MSA (bad gap structure)
        self.engine_fallbacks = 0  # engine-level device/native demotions
        #                            inside the MSA consensus path
        # backend-probe accounting (utils.backend.probe_counters,
        # diffed around the CLI's startup gate): the warm-pool reuse
        # gate — a job served by a warm process records warm_hits > 0
        # and probes == 0 once the first job initialized the backend
        self.backend_probes = 0     # bounded subprocess probes PAID
        self.backend_warm_hits = 0  # probe checks answered warm
        # resilience counters (pwasm_tpu.resilience.supervisor): the
        # supervised device pipeline's decisions, reported as one
        # nested "resilience" block in the JSON
        self.res_retries = 0           # re-executed device attempts
        self.res_fallbacks = 0         # batches degraded to the host
        self.res_guardrail_rejects = 0  # outputs rejected as corrupt
        self.res_deadline_timeouts = 0  # attempts past --device-deadline
        self.res_breaker_trips = 0     # GLOBAL breaker opens (probe-
        #                                confirmed dead backend — the
        #                                page-an-operator alarm)
        self.res_site_breaker_trips = 0  # per-site breaker opens (one
        #                                persistently-failing program on
        #                                a healthy backend)
        self.res_injected_faults = 0   # faults injected (--inject-faults)
        self.res_checkpoints = 0       # durable batch checkpoints written
        # memory-pressure counters (OOM-aware bisection): allocation
        # failures are classified apart from transient faults — they
        # bisect the batch instead of retrying the same shape, and they
        # never trip the breaker
        self.res_oom_events = 0        # device allocation failures seen
        #                                (real RESOURCE_EXHAUSTED or the
        #                                injected oom= leg)
        self.res_batch_splits = 0      # batches bisected after an OOM
        self.res_bucket_demotions = 0  # pow2 batch-ceiling lowerings
        #                                (each one shrinks every later
        #                                flush for the rest of the run)
        self.res_bucket_repromotions = 0  # probation-raises of a
        #                                demoted ceiling after N
        #                                consecutive clean flushes —
        #                                the up-transition, so one OOM
        #                                does not chunk a long-lived
        #                                run (or serve process) forever
        self.preempted = False         # the run exited via a graceful
        #                                drain (SIGTERM/SIGINT or the
        #                                preempt= leg): stats are
        #                                PARTIAL and the report is a
        #                                resumable prefix
        # recovery counters (pwasm_tpu.resilience.health): the
        # flap-recovery layer's decisions — a degraded run that heals
        # shows recloses/recovered > 0; one that stays walled shows
        # degraded_batches growing with recloses == 0
        self.res_breaker_recloses = 0  # global breaker RECLOSES (the
        #                                mid-run CPU->device
        #                                re-promotion operators watch
        #                                for after an outage page)
        self.res_reprobe_attempts = 0  # bounded backend re-probes made
        #                                while the global breaker was
        #                                open (capped-exponential)
        self.res_degraded_batches = 0  # batches skipped straight to the
        #                                host because the global breaker
        #                                was open
        self.res_recovered_batches = 0  # successful device batches
        #                                 executed after a reclose
        self.res_degraded_wall_s = 0.0  # wall seconds spent with the
        #                                 global breaker open
        # host stage walls (the where-the-time-goes breakdown of the
        # host report path, BASELINE.md ceiling analysis): parse
        # (PAF/cs), event extraction, columnar analysis, byte
        # formatting.  parse/extract accumulate on the main input
        # loop, analyze/format on the host pipeline worker — disjoint
        # fields, so the two threads never tear each other's sums.
        # Reported as one nested "host" block in the JSON and folded
        # into pwasm_host_stage_seconds_total{stage} (obs/catalog.py).
        self.host_parse_s = 0.0
        self.host_extract_s = 0.0
        self.host_analyze_s = 0.0
        self.host_format_s = 0.0
        # dispatch-budget counters (VERDICT r5 item 3): every device
        # round-trip costs a host<->device dispatch (~1-2 ms through a
        # tunnel), so the device path must stay dispatch-lean at scale.
        # A "dispatch" is one device program launch; a "flush" is one
        # host-BLOCKING round-trip (the host waits on device results).
        # Reported as one nested "device" block in the JSON; the
        # realistic-scale test gates device_flushes at single digits.
        self.device_dispatches = 0     # device program launches
        self.device_flushes = 0        # host-blocking result fetches
        self.dispatches_by_site = {}   # site -> launch count
        # utilization accounting (ISSUE 11): pow2-bucket padding waste
        # (live rows vs launched slots in each padded device batch)
        # and the compile-vs-steady split of supervised attempt walls
        # (a site's FIRST attempt pays the XLA compile; the split says
        # how much of the device wall was compile, not work)
        self.device_pad_items = 0      # live rows in padded launches
        self.device_pad_slots = 0      # total slots (live + pad)
        self.device_compile_s = 0.0    # first-attempt-per-site wall
        self.device_steady_s = 0.0     # subsequent attempt wall
        self._compiled_sites: set = set()

    def note_dispatch(self, site: str, n: int = 1) -> None:
        """Count ``n`` device program launches at ``site`` (ctx_scan,
        realign, consensus, refine, many2many, ...)."""
        self.device_dispatches += n
        self.dispatches_by_site[site] = \
            self.dispatches_by_site.get(site, 0) + n

    def note_flush(self, n: int = 1) -> None:
        """Count ``n`` host-blocking device round-trips (a fetch the
        host waits on)."""
        self.device_flushes += n

    def note_pad(self, items: int, slots: int) -> None:
        """Count one pow2-padded device launch: ``items`` live rows in
        ``slots`` launched slots (the pad-waste-ratio source)."""
        self.device_pad_items += items
        self.device_pad_slots += slots

    def note_attempt_wall(self, site: str, wall_s: float) -> None:
        """Split one supervised attempt's wall into compile-inclusive
        (the site's first attempt this run) vs steady."""
        if site in self._compiled_sites:
            self.device_steady_s += wall_s
        else:
            self._compiled_sites.add(site)
            self.device_compile_s += wall_s

    @property
    def wall_s(self) -> float:
        return time.perf_counter() - self.t0

    def rate(self) -> float:
        """Aligned target bases per second of wall clock."""
        dt = self.wall_s
        return self.aligned_bases / dt if dt > 0 else 0.0

    def as_dict(self) -> dict:
        return {
            "stats_version": STATS_VERSION,
            "lines": self.lines,
            "alignments": self.alignments,
            "skipped_bad_lines": self.skipped_bad,
            "skipped_duplicates": self.skipped_dedup,
            "skipped_self": self.skipped_self,
            "resumed_past": self.resumed_past,
            "aligned_bases": self.aligned_bases,
            "events": self.events,
            "device_batches": self.device_batches,
            "fallback_batches": self.fallback_batches,
            "device_events": self.device_events,
            "scalar_events": self.scalar_events,
            "realigned": self.realigned,
            "msa_dropped": self.msa_dropped,
            "engine_fallbacks": self.engine_fallbacks,
            "backend": {
                "probes": self.backend_probes,
                "warm_hits": self.backend_warm_hits,
            },
            "device": {
                "dispatches": self.device_dispatches,
                "flushes": self.device_flushes,
                "by_site": dict(self.dispatches_by_site),
                # additive (stats_version unchanged): utilization
                # accounting — pow2 pad waste + compile/steady split
                "pad_items": self.device_pad_items,
                "pad_slots": self.device_pad_slots,
                "compile_s": round(self.device_compile_s, 6),
                "steady_s": round(self.device_steady_s, 6),
            },
            "host": {
                "parse_s": round(self.host_parse_s, 6),
                "extract_s": round(self.host_extract_s, 6),
                "analyze_s": round(self.host_analyze_s, 6),
                "format_s": round(self.host_format_s, 6),
            },
            "resilience": {
                "retries": self.res_retries,
                "fallbacks": self.res_fallbacks,
                "guardrail_rejects": self.res_guardrail_rejects,
                "deadline_timeouts": self.res_deadline_timeouts,
                "breaker_trips": self.res_breaker_trips,
                "site_breaker_trips": self.res_site_breaker_trips,
                "injected_faults": self.res_injected_faults,
                "checkpoints": self.res_checkpoints,
                "oom_events": self.res_oom_events,
                "batch_splits": self.res_batch_splits,
                "bucket_demotions": self.res_bucket_demotions,
                "bucket_repromotions": self.res_bucket_repromotions,
                "breaker_recloses": self.res_breaker_recloses,
                "reprobe_attempts": self.res_reprobe_attempts,
                "degraded_batches": self.res_degraded_batches,
                "recovered_batches": self.res_recovered_batches,
                "degraded_wall_s": round(self.res_degraded_wall_s, 3),
            },
            "preempted": self.preempted,
            "wall_s": round(self.wall_s, 3),
            "aligned_bases_per_s": round(self.rate(), 1),
        }

    def write(self, f: IO[str]) -> None:
        json.dump(self.as_dict(), f)
        f.write("\n")

    def brief(self) -> str:
        """One human line for -v stderr output."""
        d = self.as_dict()
        return (f"{d['alignments']} alignments, {d['events']} events, "
                f"{d['aligned_bases']} aligned bases in {d['wall_s']}s "
                f"({d['aligned_bases_per_s']:.0f} bases/s)")
