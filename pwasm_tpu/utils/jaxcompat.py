"""jax API compatibility shims.

The container images this repo runs in pin different jax releases, and
the ``shard_map`` surface moved twice across them: jax < 0.6 ships it as
``jax.experimental.shard_map.shard_map`` with a ``check_rep`` flag,
newer releases export it at top level with the flag renamed
``check_vma``.  Every sharded entry point in the repo imports the
wrapper below instead of touching either surface directly, so a jax
pin change degrades nothing (the baseline container, jax 0.4.37, lost
every ``parallel/`` test to this import before the shim existed).

The same rule covers the COLLECTIVES the sharded programs use:
``psum``/``ppermute`` (and the ``pcast`` annotation) are re-exported
here, and a static gate (``qa/check_supervision.py``
``find_sharding_violations``, tier-1) fails any module outside this
shim that imports ``shard_map`` or calls ``jax.lax.psum``/
``jax.lax.ppermute`` directly — so the next ``jax.lax`` surface move
is one edit here, not an archaeology pass over ``parallel/``.
"""

from __future__ import annotations

from typing import Any


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma: bool | None = None, **kw: Any):
    """``jax.shard_map`` on new jax, ``jax.experimental.shard_map`` on
    old — with ``check_vma`` translated to the old ``check_rep`` flag
    (same meaning: verify the per-device replication/varying-axes
    analysis; both callers here disable it for collective-free blocks
    whose constant carries the checker rejects)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.6
        if check_vma is not None:
            kw["check_vma"] = check_vma
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm
        if check_vma is not None:
            kw["check_rep"] = check_vma
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kw)


def psum(x, axis_name):
    """``jax.lax.psum`` behind the shim: the ICI all-reduce every
    depth-sharded consensus program uses (per-column base counts summed
    over the device axis before the vote).  One indirection so a
    ``jax.lax`` surface move costs one edit here, enforced by the
    static sharding-API gate."""
    import jax

    return jax.lax.psum(x, axis_name)


def ppermute(x, axis_name, perm):
    """``jax.lax.ppermute`` behind the shim (the wavefront ring's halo
    exchange)."""
    import jax

    return jax.lax.ppermute(x, axis_name, perm=perm)


def pcast(x, axis_name, to: str = "varying"):
    """``jax.lax.pcast`` where it exists (the explicit varying-axes
    annotation newer shard_map type checking wants), identity on old
    jax — whose ``check_rep`` analysis needs no annotation for values
    that are about to vary per device."""
    import jax

    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to=to)
    return x


def enable_compilation_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at ``path`` and widen
    it to cache EVERY program (min-compile-time/min-entry-size floors
    zeroed — the repeated-invocation CLI pattern amortizes even small
    programs).  The three config keys have moved/appeared across jax
    pins, so each update is tolerated independently; returns whether
    the directory knob itself took (the others are refinements).
    Lives here so the rest of the repo never touches the
    ``jax.config`` persistent-cache surface directly — the next key
    rename costs one edit in this shim."""
    import os

    import jax

    ok = False
    try:
        from pwasm_tpu.utils.fsio import ensure_private_dir
        ensure_private_dir(path)
    except OSError:
        return False
    for key, val in (
            ("jax_compilation_cache_dir", path),
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(key, val)
            ok = ok or key == "jax_compilation_cache_dir"
        except Exception:
            pass
    return ok


def pin_cpu_platform() -> None:
    """Pin jax to the CPU backend before its first init — a
    ``--device=cpu`` job must never touch a (possibly unhealthy) TPU
    tunnel.  A no-op once a backend is already up (``update`` raises
    then; callers deliberately keep whatever is live).  Lives here so
    textually-jax-free layers (``pwasm_tpu/stream/``, gated by
    ``find_stream_violations``) can request the pin without importing
    jax themselves."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
