"""Bounded jax-backend probing (SURVEY.md §5 failure detection).

The TPU here sits behind a tunnel that goes down for multi-hour
stretches; an unguarded first `jax.devices()` then hangs indefinitely.
Every front end that can touch the device — the CLI's `--device=tpu`
path, `bench.py`, `tpu_smoke.py` — probes through this module first:
a subprocess asks which platform initializes under the current env,
bounded by a timeout, so a dead tunnel costs seconds, not a hang.
"""

from __future__ import annotations

import os
import stat as _stat
import subprocess
import sys

_probe_cache: tuple[float, str | None, str] | None = None  # (ts, platform, why)

# process-wide probe accounting (the warm-pool observability the serve
# daemon's reuse gate reads): "probes" counts subprocess probes
# actually PAID (a full jax import + backend init each), "warm_hits"
# counts reachability checks answered from warm state instead — an
# already-initialized in-process backend, the in-process TTL cache, or
# the cross-process TTL marker.  The CLI diffs these around its
# startup gate into the per-run --stats "backend" block.
probe_counters = {"probes": 0, "warm_hits": 0}


def probe_backend(env: dict, timeout: float) -> tuple[str | None, str]:
    """Ask a subprocess which jax platform initializes under ``env``.
    Returns ``(platform, "")`` on success, or ``(None, diagnostic)`` on
    error OR hang — both failure modes have been observed on the
    tunnel (an init error in round 1, multi-hour hangs since)."""
    probe_counters["probes"] += 1
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM=%s:%d' % (d[0].platform, len(d)))")
    try:
        r = subprocess.run([sys.executable, "-c", code], env=env,
                           capture_output=True, timeout=timeout,
                           text=True)
    except subprocess.TimeoutExpired:
        return None, f"probe hang (> {timeout:.0f}s)"
    except Exception as e:
        return None, f"probe spawn failed: {type(e).__name__}: {e}"
    if r.returncode != 0:
        return None, r.stderr[-500:]
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1].split(":")[0], ""
    return None, r.stderr[-500:]


def _marker_uid() -> int:
    """The uid the marker directory is keyed on AND verified against —
    one definition so the path key and the trust check cannot drift."""
    return os.getuid() if hasattr(os, "getuid") else 0


def _success_marker() -> str | None:
    """Path of the cross-process probe-success marker, keyed on the
    env bits that select the backend (a CPU-pinned shell and a
    tunnel-pointed shell must not share a verdict).  The marker lives
    in a per-uid 0700 subdirectory of the temp dir: in a sticky-bit
    /tmp another local user can pre-create (and the victim cannot
    unlink) files at any predictable shared name, so per-file trust
    checks alone can be griefed into permanently disabling the cache —
    owning the whole directory removes the foreign-file case.  Returns
    None when the directory cannot be created/trusted (cache disabled,
    probes still work)."""
    import hashlib
    import tempfile

    d = os.path.join(tempfile.gettempdir(),
                     f"pwasm_probe_{_marker_uid()}")
    try:
        os.makedirs(d, mode=0o700, exist_ok=True)
        st = os.lstat(d)
        if not _stat.S_ISDIR(st.st_mode) or st.st_uid != _marker_uid():
            return None     # squatted by another user: no cache
        if st.st_mode & 0o077:
            # makedirs(mode=0o700) does NOT tighten a pre-existing
            # directory: one we own but with group/world bits set (an
            # old or foreign-created dir) would leak the trust the 0700
            # design assumes — tighten it, or refuse the cache
            os.chmod(d, 0o700)
    except OSError:
        return None
    key = "|".join(os.environ.get(k, "") for k in
                   ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS",
                    "JAX_PLATFORM_NAME"))
    h = hashlib.sha256(key.encode()).hexdigest()[:16]
    return os.path.join(d, f"ok_{h}")


def _backend_already_initialized() -> bool:
    """True only when an in-process jax BACKEND exists (a mere
    ``import jax`` does not initialize one and proves nothing about
    tunnel health)."""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge as xb

        return bool(getattr(xb, "_backends", {}))
    except Exception:
        return False


def invalidate_probe_cache() -> None:
    """Drop every cached healthy-probe verdict — the in-process tuple
    AND the cross-process TTL marker.  Called when the circuit breaker
    confirms a dead backend mid-run: a sibling process (or the next
    run inside the TTL) must re-probe instead of inheriting a stale
    "healthy" and hanging on its first device touch."""
    global _probe_cache
    _probe_cache = None
    marker = _success_marker()
    if marker is not None:
        try:
            os.unlink(marker)
        except OSError:
            pass


def device_backend_reachable() -> tuple[bool, str]:
    """Bounded health check before the CLI's first device touch.

    Returns ``(True, "")`` when a jax backend initializes under the
    current env (whatever platform — CPU-pinned test runs are healthy),
    or ``(False, diagnostic)``.  The probe subprocess pays a full jax
    import + backend init, so the healthy verdict is cached two ways:
    per process, and cross-process via a TTL success marker in the temp
    dir (``PWASM_DEVICE_PROBE_TTL`` seconds, default 300) keyed on the
    backend-selecting env — consecutive healthy ``--device=tpu`` runs
    probe once, not every run.  Skipped (True) when jax is already
    imported in-process — its backend either initialized already or
    will fail fast — or when ``PWASM_DEVICE_PROBE=0``.
    ``PWASM_DEVICE_PROBE_TIMEOUT`` bounds the probe (default 150 s,
    matching the bench)."""
    global _probe_cache
    import time

    if os.environ.get("PWASM_DEVICE_PROBE", "1") == "0":
        return True, ""     # probing disabled: neither paid nor warm
    if _backend_already_initialized():
        # the warmest hit of all: a live in-process backend answers
        # for free — the serve daemon's jobs 2..N land here
        probe_counters["warm_hits"] += 1
        return True, ""
    try:
        ttl = float(os.environ.get("PWASM_DEVICE_PROBE_TTL", "300"))
    except ValueError:
        ttl = 300.0
    now = time.time()
    paid = False
    if _probe_cache is None or (ttl > 0 and now - _probe_cache[0] > ttl):
        marker = _success_marker()
        if marker is not None:
            try:
                # the 0700 per-uid directory already excludes other
                # users; the lstat + regular-file + uid check is belt
                # and braces — anything unexpected is removed and falls
                # through to a real probe rather than skipping the
                # health check.
                st = os.lstat(marker)
                if (_stat.S_ISREG(st.st_mode)
                        and st.st_uid == _marker_uid()):
                    if ttl > 0 and now - st.st_mtime < ttl:
                        _probe_cache = (now, "cached", "")
                        probe_counters["warm_hits"] += 1
                        return True, ""
                else:
                    try:  # a squatting directory needs rmdir, not
                        # unlink, or the cache never recovers here
                        if _stat.S_ISDIR(st.st_mode):
                            os.rmdir(marker)
                        else:
                            os.unlink(marker)
                    except OSError:
                        pass
            except OSError:
                pass
        try:
            timeout = float(os.environ.get(
                "PWASM_DEVICE_PROBE_TIMEOUT", "150"))
        except ValueError:
            timeout = 150.0
        platform, why = probe_backend(dict(os.environ), timeout)
        paid = True
        _probe_cache = (now, platform, why)
        if platform is not None and marker is not None:
            try:  # refresh the cross-process marker (never through a
                # symlink, even inside the owned dir)
                fd = os.open(marker,
                             os.O_WRONLY | os.O_CREAT | os.O_TRUNC
                             | getattr(os, "O_NOFOLLOW", 0), 0o600)
                os.close(fd)
                os.utime(marker, None)  # O_TRUNC on empty keeps mtime:
                #                         refresh it explicitly
            except OSError:
                pass
    _ts, platform, why = _probe_cache
    if platform is not None and not paid:
        # answered from the fresh in-process cache of a prior call
        probe_counters["warm_hits"] += 1
    return platform is not None, why
