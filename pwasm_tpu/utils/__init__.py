"""Observability and run-control utilities (SURVEY.md §5).

The reference has none of this — its only observability is ``-v``
stderr messages and the ``-D`` layout dump, its failure model is
fail-fast ``GError``/exit, and there is no checkpoint/resume
(pafreport.cpp:296-460 is a single streaming pass).  The new framework
adds the subsystems §5 calls for: structured run stats, device trace
hooks, a resumable report cursor, and batch-level bad-line skipping
(the latter two live in pwasm_tpu/cli.py).
"""

from pwasm_tpu.utils.runstats import RunStats  # noqa: F401
from pwasm_tpu.utils.profiling import device_trace  # noqa: F401


def exc_detail(e: BaseException, limit: int = 200) -> str:
    """One-line ``TypeName: message`` for device-demotion stderr
    messages — newlines flattened and truncated so a shape/dtype
    programming bug reads differently from a backend outage without
    breaking the one-warning-per-line convention."""
    return f"{type(e).__name__}: " + str(e).replace("\n", " ")[:limit]
