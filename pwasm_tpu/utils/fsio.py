"""Durable state writes: the one audited fsync-then-replace.

Every file this tree treats as *state* — the ``<report>.ckpt`` resume
journal, the ``.fai`` FASTA sidecar, the published native build
artifacts — must survive a crash at any instant with either the old
content or the new content on disk, never a torn prefix.  ``os.replace``
alone does NOT give that: without an fsync of the tmp file the rename
can land before the data blocks do (a crash then leaves a *complete
rename of an empty file*), and without an fsync of the parent directory
the rename itself may not be durable.  The full pattern is

    write tmp -> flush -> fsync(tmp) -> os.replace(tmp, dest)
              -> fsync(parent dir)

and it lives HERE, once: ``qa/check_durability.py`` (tier-1) fails any
``os.replace``/``os.rename`` call site elsewhere in the tree, so a new
state writer cannot quietly ship the torn-file bug this module exists
to close.
"""

from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory (makes a just-landed rename
    durable).  Silently a no-op where directories cannot be opened or
    fsynced (some filesystems, non-POSIX platforms) — the rename is
    still atomic there, just not crash-durable, which is the best the
    platform offers."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def replace_durable(tmp: str, dest: str) -> None:
    """``os.replace`` + parent-directory fsync.  The caller owns the
    tmp file's own fsync (``write_durable_*`` below do it; a caller
    publishing e.g. a freshly compiled artifact does it on its own
    handle)."""
    os.replace(tmp, dest)
    fsync_dir(os.path.dirname(os.path.abspath(dest)))


def write_durable_bytes(dest: str, data: bytes,
                        tmp_suffix: str | None = None) -> None:
    """Atomically and durably publish ``data`` at ``dest`` via the full
    tmp-write/fsync/replace/dir-fsync pattern.  ``tmp_suffix`` names
    the tmp file (default ``.<pid>.tmp`` — process-unique so
    concurrent writers of the same dest never share a tmp)."""
    tmp = dest + (tmp_suffix if tmp_suffix is not None
                  else f".{os.getpid()}.tmp")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        replace_durable(tmp, dest)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_durable_text(dest: str, text: str,
                       tmp_suffix: str | None = None) -> None:
    write_durable_bytes(dest, text.encode("utf-8"), tmp_suffix)


def truncate_durable(path: str, nbytes: int) -> None:
    """Truncate ``path`` to ``nbytes`` and fsync.  A truncation is a
    state write too: the resume path uses it to drop a torn report
    tail past the checkpointed prefix, and without the fsync a crash
    could resurrect the very bytes the checkpoint said were gone."""
    with open(path, "ab") as f:
        f.truncate(nbytes)
        f.flush()
        os.fsync(f.fileno())
