"""Durable state writes: the one audited fsync-then-replace.

Every file this tree treats as *state* — the ``<report>.ckpt`` resume
journal, the ``.fai`` FASTA sidecar, the published native build
artifacts — must survive a crash at any instant with either the old
content or the new content on disk, never a torn prefix.  ``os.replace``
alone does NOT give that: without an fsync of the tmp file the rename
can land before the data blocks do (a crash then leaves a *complete
rename of an empty file*), and without an fsync of the parent directory
the rename itself may not be durable.  The full pattern is

    write tmp -> flush -> fsync(tmp) -> os.replace(tmp, dest)
              -> fsync(parent dir)

and it lives HERE, once: ``qa/check_durability.py`` (tier-1) fails any
``os.replace``/``os.rename`` call site elsewhere in the tree, so a new
state writer cannot quietly ship the torn-file bug this module exists
to close.
"""

from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory (makes a just-landed rename
    durable).  Silently a no-op where directories cannot be opened or
    fsynced (some filesystems, non-POSIX platforms) — the rename is
    still atomic there, just not crash-durable, which is the best the
    platform offers."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def replace_durable(tmp: str, dest: str) -> None:
    """``os.replace`` + parent-directory fsync.  The caller owns the
    tmp file's own fsync (``write_durable_*`` below do it; a caller
    publishing e.g. a freshly compiled artifact does it on its own
    handle)."""
    os.replace(tmp, dest)
    fsync_dir(os.path.dirname(os.path.abspath(dest)))


def write_durable_bytes(dest: str, data: bytes,
                        tmp_suffix: str | None = None) -> None:
    """Atomically and durably publish ``data`` at ``dest`` via the full
    tmp-write/fsync/replace/dir-fsync pattern.  ``tmp_suffix`` names
    the tmp file (default ``.<pid>.tmp`` — process-unique so
    concurrent writers of the same dest never share a tmp)."""
    tmp = dest + (tmp_suffix if tmp_suffix is not None
                  else f".{os.getpid()}.tmp")
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        replace_durable(tmp, dest)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def write_durable_text(dest: str, text: str,
                       tmp_suffix: str | None = None) -> None:
    write_durable_bytes(dest, text.encode("utf-8"), tmp_suffix)


def ensure_private_dir(path: str) -> str:
    """Create ``path`` (parents included) OWNER-ONLY (0700) and return
    it.  The service tree's state directories — result spool, result
    cache, journal dirs — hold job payloads, results and capability
    material; a default-umask 0755 directory leaks every other local
    user read access to all of it.  Mode is applied *at creation*: a
    PRE-EXISTING directory keeps whatever mode the operator gave it
    (deliberately widened shared storage stays shared — we refuse to
    silently chmod a directory we did not make).  The static gate
    (``qa/check_supervision.py::find_perm_violations``) fails any bare
    ``os.makedirs`` call site elsewhere in the package so a new state
    dir cannot quietly ship world-readable."""
    try:
        os.makedirs(path, mode=0o700)
    except FileExistsError:
        if os.path.isdir(path):
            return path
        raise
    try:
        # makedirs' mode is filtered through the umask; re-assert the
        # exact bits on the leaf we just created so the contract is
        # deterministic, not umask-dependent
        os.chmod(path, 0o700)
    except OSError:
        pass
    return path


def payload_crc(payload) -> int:
    """CRC32 over a JSON payload in canonical form (sorted keys, no
    whitespace) — THE self-validating-state checksum, shared by the
    ckpt-v2 writer/verifier (``cli.py``) and the result-spool
    writer/reader (``service/daemon.py``) so the two canonicalizations
    cannot drift.  Stable across write/parse round-trips because every
    payload is ints/strings/bools/containers only."""
    import json
    import zlib
    return zlib.crc32(json.dumps(
        payload, sort_keys=True, separators=(",", ":")).encode())


def truncate_durable(path: str, nbytes: int) -> None:
    """Truncate ``path`` to ``nbytes`` and fsync.  A truncation is a
    state write too: the resume path uses it to drop a torn report
    tail past the checkpointed prefix, and without the fsync a crash
    could resurrect the very bytes the checkpoint said were gone."""
    with open(path, "ab") as f:
        f.truncate(nbytes)
        f.flush()
        os.fsync(f.fileno())


class DurableAppender:
    """Fsync-per-record append log: the durable-write primitive for
    NDJSON journals (the serve daemon's job journal).  The replace
    pattern above is wrong for a journal — replacing the whole file per
    record is O(n²) and loses the append-only torn-tail property a
    crash-time reader depends on (every complete line is durable; at
    most the LAST line is torn).  The corresponding pattern is

        open append -> fsync(parent dir, creation durability)
        per record: write -> flush -> fsync(file)

    and it lives HERE so the static gate (``qa/check_durability.py``)
    can hold journal writers to it the same way state publishers are
    held to ``write_durable_*``: a raw ``os.fsync`` call site outside
    this module's registry is a gate failure."""

    def __init__(self, path: str):
        self.path = path
        existed = os.path.exists(path)
        self._f = open(path, "ab")
        if not existed:
            # make the file's CREATION durable too: a journal whose
            # first records survive but whose directory entry doesn't
            # is indistinguishable from "journaling was off"
            fsync_dir(os.path.dirname(os.path.abspath(path)))

    def append(self, data: bytes) -> None:
        """Durably append one record (caller supplies the trailing
        newline).  Raises OSError on a failed write — the caller owns
        the degrade-or-die policy."""
        self._f.write(data)
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    def __enter__(self) -> "DurableAppender":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
