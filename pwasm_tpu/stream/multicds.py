"""Many-to-many jobs: one multi-CDS submit, one device session.

BASELINE.md config 3's shape (ROADMAP item 3b): hundreds of bacterial
CDS queries scored against many assembly targets.  Run naively that is
N sequential jobs — N interpreter startups, N backend probes, N
compile-cache warmups — for work that is one embarrassingly-parallel
(Q x T) batch.  This driver is the job type that amortizes all of it:
every query in the ``-r`` FASTA scores against every target in the
positional FASTA through ONE ``many2many_scores_ragged`` session
(queries bucketed by exact length, targets padded per query bucket —
``parallel/bucketing.py``), under ONE backend probe and ONE
``BatchSupervisor`` ``many2many`` site (retries, guardrails, TPU→CPU
degradation all inherited).

Output contract (the parity gate ``tests/test_stream.py`` enforces):
the report is a sequence of per-CDS sections, each depending only on
(that query, the targets) —

.. code-block:: text

    >cds1	1500	200          # query id, query length, target count
    asm000	101442	1423         # target id, target length, score
    ...

— so a multi-CDS job's section bytes are IDENTICAL to N single-CDS
runs of the same driver, and the ``-s`` summary (one roll-up line per
CDS: id, targets, best target, best score, score sum) concatenates the
same way.  What changes is the cost: one session instead of N
(``backend.probes + backend.warm_hits == 1`` in ``--stats``), and the
bench leg ``realistic_many2many_vs_sequential_ratio`` gates the
multiplier.

Scores are the banded affine-gap DP global scores (``NEG`` for pairs
whose end diagonal no band placement covers — rendered as ``.`` so a
"no alignment under this band" verdict is explicit, not a plausible
number).

jax-free at module level (the ``find_stream_violations`` gate): the
device stack loads lazily inside :func:`many2many_main`, exactly like
``cli._main_loop`` does.
"""

from __future__ import annotations

from pwasm_tpu.core.errors import EXIT_USAGE, PwasmError

M2M_USAGE = """Usage:
 pafreport --many2many <targets.fa> -r <cds_multi.fa> [-o <scores.tsv>]
    [-s <summary.txt>] [--device=cpu|tpu] [--band=N] [--stats=FILE]
    [--max-retries=N] [--fallback=cpu|fail] [--result-cache=DIR|off]
    [-v]

   Score EVERY query in the -r FASTA against EVERY target in
   <targets.fa> through one device session (banded affine-gap DP,
   parallel/many2many.py).  The report is one section per CDS
   (">id\\tlen\\tn_targets" then one "target\\tlen\\tscore" row per
   target, in FASTA order); -s writes one roll-up line per CDS
   (id, targets, best target, best score, score sum).  Sections are
   byte-identical to running each CDS as its own job — the multi
   submit only amortizes the session.

   --result-cache=DIR caches at PER-CDS SECTION granularity
   (service/cache.py): each section keys on (its query record digest,
   the whole target-set digest, --band), so a job re-scoring 9 cached
   CDS + 1 new one dispatches ONLY the new one to the device and
   splices the byte-identical stored sections around it.  A served
   job under `serve --result-cache` inherits the daemon's dir.
"""


class M2mUsageError(PwasmError):
    exit_code = EXIT_USAGE


def _usage_err(msg: str) -> M2mUsageError:
    return M2mUsageError(f"{M2M_USAGE}\n{msg}\n")


def load_fasta(path, what):
    """Load a FASTA into parallel (names, upper-cased seqs) lists —
    shared by the one-shot driver and the surveil stream session so
    both parse targets identically (the byte-parity precondition)."""
    from pwasm_tpu.core.fasta import FastaFile
    try:
        fa = FastaFile(str(path))
    except (OSError, PwasmError):
        raise PwasmError(
            f"Error: invalid FASTA file {path} !\n")
    if not len(fa):
        raise PwasmError(
            f"Error: invalid FASTA file {path} !\n")
    seqs = []
    for name in fa.names:
        s = fa.fetch(name)
        if not s:
            raise PwasmError(
                f"Error: could not retrieve sequence for {name} "
                f"({what})!\n")
        seqs.append(s.upper())
    return fa.names, seqs


def parse_m2m_opts(opts: dict):
    """Validate the option surface shared by ``--many2many`` and the
    surveil ``--m2m-stream`` session (device/band/retries/fallback/
    result-cache/deadline).  Returns a plain namespace; raises
    :class:`M2mUsageError` with the usage text on bad values."""
    from types import SimpleNamespace

    for bad, why in (("w", "builds an MSA"), ("ace", "builds an MSA"),
                     ("info", "builds an MSA"), ("cons", "builds an "
                      "MSA"), ("realign", "rewrites PAF gaps"),
                     ("follow", "tails a PAF"), ("resume", "resumes a "
                      "report"), ("shard", "is a report-path knob")):
        if bad in opts:
            raise _usage_err(f"Error: --many2many scores sequences; "
                             f"-{'-' if len(bad) > 1 else ''}{bad} "
                             f"{why} and does not apply")
    rpath = opts.get("r")
    if not rpath or rpath is True:
        raise _usage_err("Error: query FASTA file (-r) is required!")
    device = str(opts.get("device", "cpu"))
    if device not in ("cpu", "tpu"):
        raise _usage_err(f"Error: invalid --device value: {device}")
    band = 64
    if "band" in opts:
        val = opts["band"]
        if val is True or not str(val).isascii() \
                or not str(val).isdigit() or int(val) < 1:
            raise _usage_err(f"Error: invalid --band value: {val}")
        band = int(val)
    max_retries = 2
    if "max-retries" in opts:
        val = opts["max-retries"]
        if val is True or not str(val).isascii() \
                or not str(val).isdigit():
            raise _usage_err(
                f"Error: invalid --max-retries value: {val}")
        max_retries = int(val)
    fallback = str(opts.get("fallback", "cpu"))
    if fallback not in ("cpu", "fail"):
        raise _usage_err(f"Error: invalid --fallback value: {fallback}")
    deadline_s = None
    if "deadline-s" in opts:
        val = opts["deadline-s"]
        try:
            deadline_s = float(str(val))
        except (TypeError, ValueError):
            deadline_s = None
        import math
        if deadline_s is None or not math.isfinite(deadline_s) \
                or deadline_s <= 0:
            raise _usage_err(
                f"Error: invalid --deadline-s value: {val}")
    rc_dir = opts.get("result-cache")
    if rc_dir is True:
        raise _usage_err("Error: --result-cache requires a directory "
                         "(or off)")
    rc_max = None
    if "result-cache-max-bytes" in opts:
        val = opts["result-cache-max-bytes"]
        if val is True or not str(val).isascii() \
                or not str(val).isdigit() or int(val) < 1:
            raise _usage_err("Error: invalid "
                             f"--result-cache-max-bytes value: {val}")
        rc_max = int(val)
    return SimpleNamespace(
        rpath=rpath, device=device, band=band,
        max_retries=max_retries, fallback=fallback,
        deadline_s=deadline_s, rc_dir=rc_dir, rc_max=rc_max,
        verbose=bool(opts.get("v")) or bool(opts.get("D")))


def open_section_store(rc_dir, rc_max, warm, stderr):
    """Resolve and open the per-CDS section cache (flag first, warm
    context second); ``None`` when caching is off or the dir is
    unusable."""
    if not isinstance(rc_dir, str) or not rc_dir or rc_dir == "off":
        rc_dir = getattr(warm, "result_cache_dir", None) \
            if warm is not None else None
    if not rc_dir:
        return None
    from pwasm_tpu.service.cache import CacheStore
    try:
        return CacheStore(rc_dir, max_bytes=rc_max)
    except OSError as e:
        print(f"Warning: --result-cache dir {rc_dir} unusable "
              f"({e}); caching disabled", file=stderr)
        return None


def lane_span_mesh(use_device, warm, stderr, verbose=False):
    """ROADMAP item 3: a leased m2m session spans its WHOLE lane —
    when the device lease covers more than one chip, build the 2-D
    tile mesh over exactly that device span (`make_mesh2d(devices=)`
    via jaxcompat, the ISSUE 8 placement pattern) instead of scoring
    on the lane's first device only.  Returns ``None`` (single-device
    session, the pre-existing behavior) for cold runs, cpu jobs, and
    single-device leases."""
    if not use_device or warm is None:
        return None
    from pwasm_tpu.cli import _lane_device_pool, _lane_devices
    span = _lane_devices(warm)
    if not span or span[1] - span[0] <= 1:
        return None
    pool = _lane_device_pool(span, stderr, warn=False)
    if pool is None or len(pool) <= 1:
        return None
    from pwasm_tpu.parallel.many2many import make_mesh2d
    try:
        mesh = make_mesh2d(devices=pool)
    except Exception as e:       # mesh shape/backend quirks demote,
        print(f"Warning: lane-span mesh over {len(pool)} device(s) "
              f"unavailable ({e}); session stays single-device",
              file=stderr)      # never kill the job
        return None
    if verbose:
        print(f"many2many: lane-span mesh over {len(pool)} "
              "device(s)", file=stderr)
    return mesh


def format_sections(qnames, qlens, tnames, tlens, scores, neg) -> str:
    """Render the per-CDS report sections (pure, unit-testable).  One
    query's section reads only its own score row, so multi-vs-single
    byte parity holds by construction."""
    out = []
    for qi, qn in enumerate(qnames):
        out.append(f">{qn}\t{qlens[qi]}\t{len(tnames)}\n")
        row = scores[qi]
        for ti, tn in enumerate(tnames):
            s = int(row[ti])
            out.append(f"{tn}\t{tlens[ti]}\t"
                       f"{'.' if s == neg else s}\n")
    return "".join(out)


def format_summary(qnames, tnames, scores, neg) -> str:
    """One roll-up line per CDS: ``id  n_targets  best_target
    best_score  score_sum`` (ties break to FASTA order; an all-NEG row
    reports ``.`` — nothing aligned under the band)."""
    out = []
    for qi, qn in enumerate(qnames):
        row = [int(v) for v in scores[qi]]
        live = [(v, ti) for ti, v in enumerate(row) if v != neg]
        if live:
            best, bi = max(live, key=lambda p: (p[0], -p[1]))
            total = sum(v for v, _t in live)
            out.append(f"{qn}\t{len(tnames)}\t{tnames[bi]}\t{best}"
                       f"\t{total}\n")
        else:
            out.append(f"{qn}\t{len(tnames)}\t.\t.\t0\n")
    return "".join(out)


def many2many_main(opts: dict, positional: list, stdout, stderr,
                   warm=None) -> int:
    """The ``--many2many`` job type (dispatched from ``cli.run``, so it
    is submittable to the serve daemon like any other job and shares
    the warm-context contract: one probe, inherited supervisor state,
    per-lane placement under a device lease)."""
    import time

    from pwasm_tpu.utils import RunStats

    cfg = parse_m2m_opts(opts)
    if len(positional) != 1:
        raise _usage_err("Error: --many2many takes exactly one "
                         "<targets.fa> argument")
    rpath, device, band = cfg.rpath, cfg.device, cfg.band
    max_retries, fallback = cfg.max_retries, cfg.fallback
    verbose, deadline_s = cfg.verbose, cfg.deadline_s
    t0_mono = time.monotonic()

    qnames, qs = load_fasta(rpath, "-r query")
    tnames, ts = load_fasta(positional[0], "target")
    tlens = [len(t) for t in ts]
    stats = RunStats()

    # ---- per-CDS SECTION cache (ISSUE 15): each query's report
    # section depends only on (that query record, the target set, the
    # band) — exactly the per-section parity contract — so sections
    # cache INDEPENDENTLY: a job re-scoring 9 cached CDS + 1 new one
    # dispatches only the new one and splices byte-identical stored
    # sections around it.  Flag first (a cold --many2many run),
    # warm-context second (a served job under `serve --result-cache`).
    skeys: list = [None] * len(qs)
    sections: list = [None] * len(qs)
    sums: list = [None] * len(qs)
    store = open_section_store(cfg.rc_dir, cfg.rc_max, warm, stderr)
    t_digs = None
    q_digs = None
    if store is not None:
        import hashlib

        from pwasm_tpu.service.cache import record_digest, section_key
        t_digs = [record_digest(tn, t)
                  for tn, t in zip(tnames, ts)]
        th = hashlib.sha256()
        for d in t_digs:
            th.update(d.encode())
        tdig = th.hexdigest()
        q_digs = [record_digest(qn, q)
                  for qn, q in zip(qnames, qs)]
        for qi in range(len(qs)):
            skeys[qi] = section_key(q_digs[qi], tdig, band)
            got = store.get(skeys[qi])
            if got is not None and "o" in got[1] \
                    and "s" in got[1]:
                sections[qi] = got[1]["o"]
                sums[qi] = got[1]["s"]
    miss = [qi for qi in range(len(qs)) if sections[qi] is None]

    # ---- superset/near-hit reuse (ISSUE 17b): an exact-section miss
    # whose FAMILY (query record + band) holds a cached entry with a
    # target SUBSET of ours reuses every cached (digest, score) pair
    # and dispatches only the delta targets.  The final section is
    # REBUILT from the merged score values through the same formatting
    # functions a cold run uses, so splice parity is by construction —
    # and the band lives in the family, so a different band never
    # donates scores.
    partial: dict[int, dict[str, int]] = {}
    if store is not None and miss and t_digs is not None:
        from pwasm_tpu.service.cache import m2m_family_key
        pool: dict[str, list] = {}
        for _key, man in store.m2m_scan():
            fam = man["m2m"].get("family")
            if isinstance(fam, str):
                pool.setdefault(fam, []).append(man)
        cur = set(t_digs)
        for qi in miss:
            fam = m2m_family_key(q_digs[qi], band)
            best = None
            for man in pool.get(fam, ()):
                rows = man["m2m"].get("targets")
                if not isinstance(rows, list):
                    continue
                try:
                    got_map = {str(d): int(s) for d, s in rows}
                except (TypeError, ValueError):
                    continue
                if not got_map or not set(got_map) <= cur:
                    continue     # not a subset: nothing to vouch for
                covered = sum(1 for d in t_digs if d in got_map)
                if best is None or covered > best[0]:
                    best = (covered, got_map)
            if best is not None:
                partial[qi] = best[1]

    # per-miss target indices still owed to the device; the map keys
    # double as score-row keys (record digests with a store, plain
    # indices without one)
    tkey = t_digs if t_digs is not None else list(range(len(ts)))
    need: dict[int, tuple] = {}
    for qi in miss:
        pm = partial.get(qi)
        if pm is None:
            need[qi] = tuple(range(len(ts)))
        else:
            need[qi] = tuple(ti for ti, d in enumerate(tkey)
                             if d not in pm)
    pairs = sum(len(need[qi]) for qi in miss)
    stats.lines = pairs

    from pwasm_tpu.resilience import BatchSupervisor, ResiliencePolicy
    supervisor = BatchSupervisor(
        ResiliencePolicy(max_retries=max_retries, fallback=fallback),
        stats=stats, stderr=stderr)
    if warm is not None and getattr(warm, "supervisor_state", None):
        supervisor.restore_state(warm.supervisor_state)

    from pwasm_tpu.ops.banded_dp import NEG
    use_device = device == "tpu" and pairs > 0
    computed: dict[int, dict] = {}
    done_pairs = 0
    done_bases = 0
    preempted = False

    def finalize(qi):
        # per-CDS section emission: format + cache-insert ONE query's
        # section as soon as its scores are complete, so a deadline
        # preemption keeps every finished section (the cache IS the
        # resume mechanism — a re-run splices them and dispatches only
        # the unfinished remainder)
        pm = partial.get(qi, {})
        cm = computed.get(qi, {})
        row = [pm[d] if d in pm else cm[d] for d in tkey]
        sec = format_sections(
            [qnames[qi]], [len(qs[qi])], tnames, tlens,
            [row], NEG).encode("utf-8")
        sm = format_summary([qnames[qi]], tnames, [row],
                            NEG).encode("utf-8")
        sections[qi], sums[qi] = sec, sm
        if store is not None and skeys[qi] is not None:
            from pwasm_tpu.service.cache import m2m_family_key
            extra = {"m2m": {
                "family": m2m_family_key(q_digs[qi], band),
                "targets": [[d, int(row[ti])]
                            for ti, d in enumerate(t_digs)]}}
            store.insert(skeys[qi], {"o": sec, "s": sm},
                         extra=extra)
        if store is not None and pm:
            store.note_delta(len(ts) - len(need[qi]), len(ts))

    if pairs:
        # the one session gate: identical to cli._main_loop's — a
        # bounded probe before the first jax touch, demoting loudly to
        # cpu, with per-run probe/warm-hit accounting (the "one warm
        # device session" acceptance reads these).  An ALL-HIT job
        # never reaches this block: zero probes, zero device touches.
        if use_device:
            from pwasm_tpu.utils import backend as _backend
            from pwasm_tpu.utils.backend import \
                device_backend_reachable
            _p0 = _backend.probe_counters["probes"]
            _w0 = _backend.probe_counters["warm_hits"]
            ok, why = device_backend_reachable()
            stats.backend_probes += \
                _backend.probe_counters["probes"] - _p0
            stats.backend_warm_hits += \
                _backend.probe_counters["warm_hits"] - _w0
            if not ok:
                print(f"Warning: jax backend unreachable "
                      f"({why.strip()}); running with --device=cpu",
                      file=stderr)
                use_device = False
                stats.engine_fallbacks += 1
        if not use_device:
            # never let a pinned-but-unhealthy TPU tunnel hijack a cpu
            # scoring job at backend init (same guard as
            # flush_realign; via the compat shim so this module stays
            # textually jax-free for the find_stream_violations gate)
            from pwasm_tpu.utils.jaxcompat import pin_cpu_platform
            pin_cpu_platform()
        else:
            from pwasm_tpu.ops import enable_compilation_cache
            # flag first (a cold --many2many run), warm-context second
            # (a served job under `serve --compile-cache-dir`)
            cache_dir = opts.get("compile-cache-dir")
            if not isinstance(cache_dir, str) or not cache_dir:
                cache_dir = getattr(warm, "compile_cache_dir", None) \
                    if warm is not None else None
            enable_compilation_cache(cache_dir)

        from types import SimpleNamespace

        from pwasm_tpu.cli import _lane_device_scope
        from pwasm_tpu.parallel.many2many import \
            many2many_scores_ragged
        if verbose:
            extra_note = ""
            if len(miss) < len(qs):
                extra_note += (f" ({len(qs) - len(miss)} section(s) "
                               "from cache)")
            if partial:
                extra_note += (f" ({len(partial)} section(s) spliced "
                               "from a cached target subset)")
            print(f"many2many: {pairs} of {len(qs) * len(ts)} "
                  f"pair(s), band {band}, one "
                  f"{'device' if use_device else 'cpu'} session"
                  + extra_note, file=stderr)
        # a served job holding a device lease places on ITS lane,
        # exactly like cli._main_loop jobs (the ISSUE 8
        # lane-isolation contract); inert for cold runs and
        # single-lane daemons.  A MULTI-device lease additionally
        # spans the whole lane with a 2-D tile mesh (lane_span_mesh).
        # queries owing the same target subset share one ragged
        # dispatch, so a superset job costs one call for the delta
        # column(s) plus one for any full-miss queries
        groups: dict[tuple, list[int]] = {}
        for qi in miss:
            if need[qi]:
                groups.setdefault(need[qi], []).append(qi)
        with _lane_device_scope(
                SimpleNamespace(device="tpu" if use_device
                                else "cpu"), warm, stderr):
            mesh = lane_span_mesh(use_device, warm, stderr, verbose)
            for idxs, qis in groups.items():
                # the end-to-end deadline is enforced at the per-CDS
                # dispatch boundary (the report-batch contract, rc 75
                # + resumable): never start a group the budget can't
                # see, and every group that DID finish is already
                # cached by finalize() below
                if deadline_s is not None and \
                        time.monotonic() - t0_mono >= deadline_s:
                    preempted = True
                    break
                scores = many2many_scores_ragged(
                    [qs[qi] for qi in qis],
                    [ts[ti] for ti in idxs], band=band, mesh=mesh,
                    supervisor=supervisor)
                for k, qi in enumerate(qis):
                    computed[qi] = {
                        tkey[ti]: int(scores[k][j])
                        for j, ti in enumerate(idxs)}
                    finalize(qi)
                done_pairs += len(qis) * len(idxs)
                done_bases += sum(tlens[ti]
                                  for ti in idxs) * len(qis)
    elif miss and verbose:
        print(f"many2many: all {len(miss)} missing section(s) "
              "spliced from cached target subsets — no device "
              "session", file=stderr)
    elif verbose:
        print(f"many2many: all {len(qs)} section(s) served from the "
              "result cache — no device session", file=stderr)
    for qi in miss:
        if sections[qi] is None and not need[qi]:
            finalize(qi)     # pure splice — no device work owed
    # honest accounting: the counters describe work this run actually
    # DID; cached sections and spliced subset rows ride in as bytes,
    # not as alignments — and a preempted run reports only the pairs
    # it dispatched before the budget ran out
    stats.lines = done_pairs
    stats.alignments = done_pairs
    stats.aligned_bases = done_bases
    stats.device_batches = 0   # the ragged driver dispatches per
    #   bucket; the supervisor's site counters carry the attempt story

    if preempted:
        from pwasm_tpu.core.errors import EXIT_PREEMPTED
        stats.preempted = True
        reason = (f"deadline_exceeded: --deadline-s={deadline_s:g} "
                  "budget spent")
        drain = getattr(warm, "drain", None) if warm is not None \
            else None
        if drain is not None and not drain.requested:
            drain.request(reason)
        print(f"Warning: many2many preempted at a per-CDS dispatch "
              f"boundary ({reason}); "
              f"{sum(1 for s in sections if s is not None)} of "
              f"{len(qs)} section(s) finished"
              + (" and cached — resubmit to continue"
                 if store is not None else ""), file=stderr)
        supervisor.finalize_stats()
        if warm is not None:
            warm.supervisor_state = {
                k: v for k, v in supervisor.export_state().items()
                if k != "fault_calls"}
        if "stats" in opts:
            try:
                with open(str(opts["stats"]), "w") as f:
                    stats.write(f)
            except OSError:
                raise PwasmError(
                    f"Cannot open file {opts['stats']} for "
                    "writing!\n")
        return EXIT_PREEMPTED

    body = b"".join(sections)
    if "o" in opts:
        try:
            with open(str(opts["o"]), "wb") as f:
                f.write(body)
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['o']} for writing!\n")
    else:
        stdout.write(body.decode("utf-8"))
    if "s" in opts:
        try:
            with open(str(opts["s"]), "wb") as f:
                f.write(b"".join(sums))
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['s']} for writing!\n")
    supervisor.finalize_stats()
    if warm is not None:
        warm.supervisor_state = {
            k: v for k, v in supervisor.export_state().items()
            if k != "fault_calls"}
    if "stats" in opts:
        try:
            with open(str(opts["stats"]), "w") as f:
                stats.write(f)
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['stats']} for writing!\n")
    if verbose:
        print(stats.brief(), file=stderr)
    return 0
