"""Streaming ingestion + multi-CDS jobs (ISSUE 10).

Two workload shapes the one-CDS/one-file/one-shot CLI left closed
(ROADMAP item 3):

- **streaming** (``pafstream``): PAF records arrive incrementally —
  from a growing file (``--follow``, the minimap2-pipe-into-a-file use
  case) or over the service socket (``stream``/``stream-data``/
  ``stream-end`` frames) — and accumulate into the EXISTING
  flush-cadence batches, emitting report bytes as batches fill and
  riding the batch-boundary checkpoint machinery, so a stream is
  preemptible/resumable and journal-replayable like any run;
- **many-to-many** (``multicds``): one multi-CDS submit scores every
  query in the FASTA against every target through ONE device session
  (``parallel.many2many_scores_ragged`` + the bucketing library)
  instead of N sequential jobs.

Like ``pwasm_tpu/service/`` and ``pwasm_tpu/obs/``, this package is
host-side and jax-free (gated by
``qa/check_supervision.py::find_stream_violations``): device work is
reached only through the supervised sites in ``pwasm_tpu/parallel/``,
imported lazily inside the dispatch path.
"""

from pwasm_tpu.stream.pafstream import (FollowReader, LineAssembler,
                                        StreamFeed)

__all__ = ["FollowReader", "LineAssembler", "StreamFeed"]
