"""Incremental PAF ingestion: tail a growing file, or drink frames.

The one-shot CLI's main loop is already record-at-a-time (``for line
in inf``) with flush-cadence batching and batch-boundary checkpoints —
so streaming ingestion needs no second report engine, only input
objects that *yield complete lines as they arrive* and end cleanly:

- :class:`FollowReader` — ``tail -F`` semantics over a growing file
  (``pafreport in.paf --follow[=IDLE_S]``): poll the file for appended
  bytes, survive rotation/truncation via (inode, offset) tracking, and
  yield only newline-terminated lines (a partially-written record is
  "not yet arrived", never a parse error).  The stream ends after
  ``idle_timeout_s`` seconds with no growth (the bench/ETL contract),
  or resumably on a drain request (SIGTERM → exit 75, the preemption
  contract every run already honors);
- :class:`StreamFeed` — the socket-stream twin: a thread-safe line
  source the serve daemon feeds from ``stream-data`` protocol frames
  (arbitrary byte chunking — frames need not align to record
  boundaries) and closes on ``stream-end``.  The executing job blocks
  on it exactly like a file read; arrival chunks drain as counted
  batches (the ``pwasm_stream_batches_total`` unit).

Both yield ``str`` lines (``"\\n"``-terminated, like a text-mode file
object), so ``cli._main_loop`` consumes them unchanged — which is WHY
a completed stream's report is byte-identical to the one-shot run over
the same records: same loop, same batches, same bytes.

jax-free by the ``find_stream_violations`` gate (see package
docstring).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

# ceiling on ONE unterminated record's buffered bytes.  The record
# quota (StreamBook) counts complete LINES, so without this a client
# sending newline-less chunks would grow the assembler's partial-line
# tail unboundedly while never tripping the quota; any real PAF line
# (coords + tags + cs string) is far under 4 MiB, so a tail past it is
# a protocol violation, not data.  Any frame that carries a newline
# resets the tail to at most that frame's own length, which the
# protocol frame ceiling already bounds.
MAX_RECORD_BYTES = 4 << 20


class LineAssembler:
    """Reassemble complete lines from arbitrarily-chunked text.

    ``push`` returns the newline-terminated lines the chunk completed
    (the partial tail is buffered for the next chunk); ``flush``
    surrenders the final unterminated tail — only correct at a CLEAN
    end of stream, where it mirrors a file whose last record lacks the
    trailing newline (the one-shot reader processes that line too, so
    byte parity requires the stream side to as well).

    Line endings are UNIVERSAL-NEWLINE normalized (``\\r\\n`` and lone
    ``\\r`` become ``\\n``), because the one-shot CLI opens its input
    in text mode — a CRLF PAF must stream to the same bytes it parses
    to whole (a ``\\r\\n`` split across two chunks is held as one
    terminator via the carried ``\\r``)."""

    def __init__(self) -> None:
        self._tail = ""
        self._held_cr = False    # chunk ended mid-"\r\n": decide when
        #                          the next chunk shows its first byte

    @property
    def pending(self) -> str:
        return self._tail

    def completed(self, data: str) -> int:
        """How many lines ``push(data)`` would yield from this chunk's
        OWN terminators — the admission check the daemon runs against
        the stream's buffer quota before committing the chunk
        (all-or-nothing per frame, so a rejected frame can be resent
        verbatim after backoff).  A ``\\r\\n`` pair split exactly at a
        chunk boundary can count one extra — the conservative
        direction for a quota."""
        return data.count("\n") + data.count("\r") \
            - data.count("\r\n")

    def _normalize(self, data: str) -> str:
        if self._held_cr:
            data = "\r" + data
            self._held_cr = False
        if data.endswith("\r"):
            data = data[:-1]
            self._held_cr = True
        return data.replace("\r\n", "\n").replace("\r", "\n")

    def push(self, data: str) -> list[str]:
        data = self._normalize(data)
        if "\n" not in data:
            self._tail += data
            return []
        body, self._tail = (self._tail + data).rsplit("\n", 1)
        return [ln + "\n" for ln in body.split("\n")]

    def preview(self, data: str) -> list[str]:
        """The lines ``push(data)`` WOULD yield, without committing —
        the stream-delta hold path digests a frame's lines before
        deciding whether to commit it (a queue-full reject must leave
        the assembler resendable-verbatim, same contract as
        ``completed``)."""
        if self._held_cr:
            data = "\r" + data
        if data.endswith("\r"):
            data = data[:-1]
        data = data.replace("\r\n", "\n").replace("\r", "\n")
        if "\n" not in data:
            return []
        body, _rest = (self._tail + data).rsplit("\n", 1)
        return [ln + "\n" for ln in body.split("\n")]

    def flush(self) -> list[str]:
        # a held final "\r" is a line terminator in text mode; the
        # main loop rstrips "\n" anyway, so the bare tail matches what
        # the one-shot reader's last line parses to
        self._held_cr = False
        tail, self._tail = self._tail, ""
        return [tail] if tail else []


class BlockLineReader:
    """Block-scan line reader for the jax-free host path (ROADMAP
    item 5): the one-shot CLI used to consume its input PAF through
    Python's line-at-a-time text iterator — one readline call, one
    newline scan, one str build per record.  This reader instead
    walks the file in 1 MiB blocks, pushing each through the same
    :class:`LineAssembler` the streaming readers use, so per-record
    overhead collapses to the assembler's single ``split`` per block
    while byte semantics stay IDENTICAL to the text-mode read
    (universal newlines via the assembler, an INCREMENTAL utf-8
    decoder so a multi-byte character straddling a block boundary
    reassembles, strict errors so undecodable input fails as loudly
    as the text reader did, final newline-less record yielded at
    EOF).

    Deliberately NOT ``mmap``-backed: this reader runs inside the
    serve daemon's workers (every served job's ingest), and touching
    a mapped page past the EOF of a file a client truncated mid-job
    raises SIGBUS — killing the whole multi-client process, where a
    bounded ``read`` merely observes a short file.  Sequential block
    reads hit the page cache at the same speed; the win over readline
    is the batching, not the mapping.

    ``hasher`` (e.g. ``hashlib.sha256()``) is updated with every RAW
    block as it is consumed, so the content digest the result cache
    keys on (``service/cache.py``) rides the same single pass as the
    ingest — keying an input adds no second read.  ``hexdigest()`` is
    meaningful once the reader is exhausted.
    """

    def __init__(self, path: str, block_bytes: int = 1 << 20,
                 hasher=None):
        self.path = path
        self.block_bytes = max(1, int(block_bytes))
        self.hasher = hasher
        self._f = open(path, "rb")
        self._asm = LineAssembler()
        import codecs
        self._dec = codecs.getincrementaldecoder("utf-8")("strict")
        self._lines: deque[str] = deque()
        self._done = False
        self.consumed = False          # reached EOF (digest is whole)

    def _next_block(self) -> bytes:
        return self._f.read(self.block_bytes)

    def hexdigest(self) -> str | None:
        """The content digest of everything read so far (the whole
        file once ``consumed``); None without a hasher."""
        return self.hasher.hexdigest() if self.hasher is not None \
            else None

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass

    def __iter__(self) -> "BlockLineReader":
        return self

    def __next__(self) -> str:
        while True:
            if self._lines:
                return self._lines.popleft()
            if self._done:
                raise StopIteration
            chunk = self._next_block()
            if not chunk:
                self._done = True
                self.consumed = True
                tail = self._dec.decode(b"", final=True)
                if tail:
                    self._lines.extend(self._asm.push(tail))
                self._lines.extend(self._asm.flush())
                continue
            if self.hasher is not None:
                self.hasher.update(chunk)
            text = self._dec.decode(chunk)
            if text:
                self._lines.extend(self._asm.push(text))


class FollowReader:
    """Iterate the lines of a growing file, ``tail -F``-style.

    Yields ``str`` lines (newline-terminated) as the writer appends
    them.  Rotation-safe: the open file's inode is compared against
    the path on every empty poll — a replaced file (rotation) or a
    shrunk one (truncation) reopens from offset 0, discarding any
    partial-line buffer from the old incarnation (its terminating
    bytes will never arrive).

    End conditions:

    - ``idle_timeout_s`` elapsed with no growth → the stream is
      declared complete: the final unterminated line (if any) is
      yielded, then iteration stops and the run finishes NORMALLY
      (exit 0, full MSA/summary tail).  ``None`` = follow forever;
    - a bound drain flag (``bind_drain``) was requested → iteration
      stops WITHOUT the partial tail; the main loop then takes its
      standard preempted path (final checkpoint, exit 75, resumable)
      — ``--resume`` over the completed file finishes byte-identically.

    The file may not exist yet when following starts (the writer races
    the reader); the reader waits for it like ``tail -F`` does.

    ``hasher`` mirrors :class:`BlockLineReader`: it rides every raw
    chunk consumed, so a CLEANLY idle-ended follow (everything on disk
    was processed) carries the same whole-file content digest the
    one-shot reader would — what lets a completed ``--follow`` run
    populate the result cache.  A rotation/truncation invalidates it
    (the stream no longer equals any one file's bytes): ``consumed``
    stays False and ``hexdigest()`` returns None.
    """

    def __init__(self, path: str, idle_timeout_s: float | None = None,
                 poll_s: float = 0.05, hasher=None):
        self.path = path
        self.idle_timeout_s = idle_timeout_s
        self.poll_s = max(0.005, float(poll_s))
        self.rotations = 0
        self.hasher = hasher
        self.consumed = False   # cleanly idle-ended, digest is whole
        self._f = None
        self._ino: int | None = None
        self._asm = LineAssembler()
        self._lines: deque[str] = deque()
        self._drain = None
        self._last_growth = time.monotonic()
        self._done = False

    def hexdigest(self) -> str | None:
        """Content digest of the consumed stream, or None (no hasher,
        or a rotation made the stream unequal to any file)."""
        return self.hasher.hexdigest() \
            if self.hasher is not None and not self.rotations else None

    # the CLI main loop binds its SignalDrain here so a SIGTERM landing
    # while the reader is blocked between records drains at THIS record
    # boundary instead of waiting out the idle timeout
    def bind_drain(self, drain) -> None:
        self._drain = drain

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def _open(self) -> bool:
        try:
            f = open(self.path, "rb")
        except OSError:
            return False
        self._f = f
        try:
            self._ino = os.fstat(f.fileno()).st_ino
        except OSError:
            self._ino = None
        return True

    def _rotated(self) -> bool:
        """The path no longer names the open file (rotation), or the
        open file shrank (truncation): either way the byte offset is
        meaningless now — start over on the current incarnation."""
        try:
            st = os.stat(self.path)
        except OSError:
            return False          # mid-rotation gap: keep the old fd
        try:
            pos = self._f.tell()
        except OSError:
            return True
        return st.st_ino != self._ino or st.st_size < pos

    def _poll_once(self) -> bool:
        """Read appended bytes into the line buffer; True when the
        file grew.  Reads are BOUNDED (1 MiB per poll) so following a
        file that already holds gigabytes streams at flat memory like
        the one-shot reader, instead of slurping the backlog whole —
        the consumer drains the buffered lines before the next poll
        reads more."""
        if self._f is None and not self._open():
            return False
        chunk = self._f.read(1 << 20)
        if chunk:
            if self.hasher is not None:
                self.hasher.update(chunk)
            self._lines.extend(self._asm.push(
                chunk.decode("utf-8", "replace")))
            return True
        if self._rotated():
            self.close()
            self._asm = LineAssembler()   # the old tail's newline will
            #                               never arrive
            self.rotations += 1
            if self._open():
                return self._poll_once()
        return False

    def __iter__(self) -> "FollowReader":
        return self

    def __next__(self) -> str:
        while True:
            if self._lines:
                return self._lines.popleft()
            if self._done:
                raise StopIteration
            if self._drain is not None and self._drain.requested:
                # preempted: stop at this record boundary; the partial
                # tail stays unconsumed (--resume re-reads the file)
                raise StopIteration
            if self._poll_once():
                self._last_growth = time.monotonic()
                continue
            if self.idle_timeout_s is not None \
                    and time.monotonic() - self._last_growth \
                    > self.idle_timeout_s:
                # clean end of stream: surrender the unterminated tail
                # exactly like a file reader at EOF would
                self._done = True
                self.consumed = not self.rotations
                self._lines.extend(self._asm.flush())
                continue
            time.sleep(self.poll_s)


class StreamFeed:
    """Thread-safe line source for a socket-streamed job.

    Connection threads ``feed()`` text chunks (any byte split — the
    :class:`LineAssembler` rebuilds records) and ``end()`` the stream;
    the worker thread executing the job iterates it like a file.  The
    consumer drains whatever has accumulated in one go — that drained
    chunk is the stream's *arrival batch* (counted in ``batches`` and,
    through ``on_batch``, in ``pwasm_stream_batches_total``).

    Backpressure is the CALLER's job (the daemon checks its
    :class:`~pwasm_tpu.service.queue.StreamBook` quota before
    committing a chunk; the feed itself only counts — it carries no
    limit of its own).

    Blocked consumers wake on feed/end, on a bound drain request (the
    job then exits 75 resumable — a dead client cannot wedge a worker
    forever: the daemon's ``--stream-idle-s`` requests exactly that
    drain), and on ``idle_timeout_s`` of silence when one is set.
    """

    def __init__(self, idle_timeout_s: float | None = None):
        self.idle_timeout_s = idle_timeout_s
        self._asm = LineAssembler()
        self._q: deque[str] = deque()
        self._local: deque[str] = deque()
        self._cond = threading.Condition()
        self.ended = False
        self.records_in = 0
        self.records_out = 0
        self.batches = 0
        self.on_batch = None         # daemon metric hook: fn(n_lines)
        self._drain = None
        self._last_activity = time.monotonic()
        self._arrivals: deque = deque()  # (records_in after the feed,
        #   monotonic t) per committing feed — the lag-AGE source
        #   (pwasm_stream_lag_age_seconds): how long the oldest
        #   unconsumed record has been waiting

    def bind_drain(self, drain) -> None:
        self._drain = drain

    @property
    def buffered(self) -> int:
        """Records fed but not yet consumed by the job (the
        ``pwasm_stream_lag_records`` gauge source)."""
        return self.records_in - self.records_out

    @property
    def tail_bytes(self) -> int:
        """Bytes of the buffered UNTERMINATED record (the daemon caps
        it at :data:`MAX_RECORD_BYTES` — see the constant's note)."""
        return len(self._asm.pending)

    def completed(self, data: str) -> int:
        return self._asm.completed(data)

    def lag_age_s(self, now: float | None = None) -> float:
        """Seconds the OLDEST fed-but-unconsumed record has waited
        (0.0 when the buffer is drained) — ``buffered`` says how deep
        the lag is, this says how stale."""
        now = time.monotonic() if now is None else now
        with self._cond:
            consumed = self.records_out
            while self._arrivals and self._arrivals[0][0] <= consumed:
                self._arrivals.popleft()
            if not self._arrivals or self.records_in <= consumed:
                return 0.0
            return max(0.0, now - self._arrivals[0][1])

    def feed(self, data: str) -> int:
        """Commit one chunk; returns the number of complete lines it
        added.  Quota enforcement happens BEFORE this call (see
        ``StreamBook.admit``) so a rejected frame leaves no partial
        assembler state behind."""
        with self._cond:
            if self.ended:
                raise ValueError("stream already ended")
            lines = self._asm.push(data)
            self._q.extend(lines)
            self.records_in += len(lines)
            self._last_activity = time.monotonic()
            if lines:
                # trim already-consumed arrival marks HERE, not only
                # when the lag-age gauge is polled: a daemon nobody
                # scrapes must not grow one tuple per frame forever
                consumed = self.records_out
                while self._arrivals \
                        and self._arrivals[0][0] <= consumed:
                    self._arrivals.popleft()
                self._arrivals.append((self.records_in,
                                       self._last_activity))
            self._cond.notify_all()
            return len(lines)

    def end(self) -> None:
        with self._cond:
            if self.ended:
                return
            self.ended = True
            # final unterminated line: arrives now, like a file's last
            # newline-less record at EOF
            tail = self._asm.flush()
            self._q.extend(tail)
            self.records_in += len(tail)
            self._cond.notify_all()

    def close(self) -> None:       # file-object duck type for cli.run
        pass

    def __iter__(self) -> "StreamFeed":
        return self

    def __next__(self) -> str:
        if self._local:
            self.records_out += 1
            return self._local.popleft()
        with self._cond:
            while not self._q and not self.ended:
                if self._drain is not None and self._drain.requested:
                    raise StopIteration   # preempted: exit 75 path
                if self.idle_timeout_s is not None \
                        and time.monotonic() - self._last_activity \
                        > self.idle_timeout_s:
                    if self._drain is not None:
                        # an abandoned stream becomes a PREEMPTED job
                        # (resumable by re-streaming with --resume),
                        # never a completed one with missing records
                        self._drain.request(
                            "stream idle past the --stream-idle-s "
                            "budget (client gone?)")
                    raise StopIteration
                self._cond.wait(0.1)
            if not self._q:
                raise StopIteration       # clean stream-end
            n = len(self._q)
            self._local.extend(self._q)
            self._q.clear()
        self.batches += 1
        if self.on_batch is not None:
            self.on_batch(n)
        self.records_out += 1
        return self._local.popleft()
