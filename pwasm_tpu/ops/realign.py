"""Banded affine-gap DP **re-alignment**: traceback to gap structures.

The scores-only kernels (``ops/banded_dp.py``) rank candidate targets;
this module turns the same banded Gotoh recurrence into a re-aligner
(SURVEY.md §0 north star: "batched banded affine-gap DP re-alignment ...
gated behind the class boundary"): for every (query segment, target)
pair it emits the optimal alignment *path* and converts it to the exact
gap-record conventions of the CIGAR walk (core/events.py:296-314,
reference pafreport.cpp:680-697), so a re-aligned MSA drops in where the
PAF's own gap structure was used.

Design (TPU-first):

- The forward pass is the shared banded wavefront recurrence with the
  band on the vector axis, vmapped over targets; each row additionally
  emits one packed pointer byte per band cell:
  bits 0-1 = diag argmax (0=M, 1=Ix, 2=Iy), bit 2 = Ix came from extend,
  bit 3 = Iy came from extend.  Pointers live in a (T, m, band) uint8
  tensor on device — O(m x band) per lane, not O(m x n).
- The traceback is ROW-PARALLEL: instead of one sequential step per
  alignment op (m + n tiny data-dependent gathers), the walk advances
  one whole query row per step (m steps).  Within a row the only
  variable-length move is a run of Iy ops (gaps in the query), and the
  run length at every band position is a closed form over the row's
  Iy-extend bits (a cumulative max along the band — vector work, like
  the forward recurrence itself).  Each row therefore emits a fixed
  (iy_run, op) pair: the compressed alignment is (m, 2) per lane, not
  (m + n,) — and the per-step work is band-vectorized.
- Gap records are extracted ON DEVICE from the compressed rows
  (``realign_gaps_batch``): fixed-capacity (pos, len) slots per lane,
  so only O(gaps) ints cross the host link per alignment, not O(m + n)
  op bytes — the host link (PCIe or worse) never sees the path tensor.
- Tie-breaks are DEFINED (M >= Ix >= Iy on maxima; gap-open wins ties
  against gap-extend) and replicated bit-for-bit by the numpy oracle
  ``full_gotoh_traceback`` so CPU/TPU gap structures are identical —
  the same bit-exactness contract as the consensus kernel.

Op codes (forward order): 1 = diagonal (consumes query+target),
2 = Ix (consumes query => gap in target, the CIGAR-walk 'I' case),
3 = Iy (consumes target => gap in query, the CIGAR-walk 'D' case).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from pwasm_tpu.core.events import GapData
from pwasm_tpu.ops.banded_dp import NEG, ScoreParams

OP_DIAG, OP_IX, OP_IY = 1, 2, 3


# ---------------------------------------------------------------------------
# forward pass with pointers (band coordinates, per lane)
# ---------------------------------------------------------------------------
def _forward_lane(q_seg, t, q_len, n: int, dlo, band: int,
                  params: ScoreParams):
    """Forward DP over one lane; rows past q_len are pass-throughs.
    Returns final wavefront (M, Ix, Iy) at row q_len and the (m_max,
    band) pointer tensor (row i stored at index i-1).  ``dlo`` is a
    traced int32 scalar, so band placement changes between flushes
    reuse the compiled program."""
    from pwasm_tpu.ops.banded_dp import initial_wavefront, make_row_step

    m_max = q_seg.shape[0]
    step = make_row_step(n, dlo, band, params, emit_ptrs=True)
    wf0 = initial_wavefront(n, dlo, band, params)

    def row(carry, xs):
        prev_m, prev_ix, prev_iy, i = carry
        qi, = xs
        i = i + 1
        m_new, ix_new, iy_new, ptr = step(prev_m, prev_ix, prev_iy, i,
                                          qi, t)
        keep = i <= q_len
        m_new = jnp.where(keep, m_new, prev_m)
        ix_new = jnp.where(keep, ix_new, prev_ix)
        iy_new = jnp.where(keep, iy_new, prev_iy)
        return (m_new, ix_new, iy_new, i), ptr

    (m_f, ix_f, iy_f, _), ptrs = jax.lax.scan(
        row, (*wf0, jnp.int32(0)), (q_seg.astype(jnp.int32),),
        length=m_max)
    return m_f, ix_f, iy_f, ptrs


# ---------------------------------------------------------------------------
# row-parallel traceback walk (per lane): m vector steps, not m+n scalar
# steps.  Within one query row the walk can only (a) consume a run of Iy
# ops (gaps in the query; moves down the band, stays in the row), then
# (b) leave the row with exactly one DIAG or IX op.  The Iy run length
# entering at band index b is closed-form over the row's Iy-extend bits:
# run(b) = b - lastZero(b) + 1 where lastZero is a cumulative max over
# positions with BY=0 — the same shift-max scan shape as the forward
# recurrence, so the whole walk is band-vectorized.
# ---------------------------------------------------------------------------
def _rowwalk_lane(ptrs, q_len, t_len, m_f, ix_f, iy_f, n: int, dlo,
                  band: int):
    """Walk from cell (q_len, t_len) back to row 0, one query row per
    scan step.  Returns (score, lead_run, iy_runs (m_max,), ops_rows
    (m_max,), ok) with iy_runs/ops_rows in FORWARD row order (row r at
    index r-1; 0 past q_len): forward op string =
    [IY]*lead_run + sum_r([op_r] + [IY]*iy_runs[r-1])."""
    m_max = ptrs.shape[0]
    bidx = jnp.arange(band, dtype=jnp.int32)
    b_end = t_len - q_len - dlo
    in_band = (b_end >= 0) & (b_end < band)
    b0 = jnp.clip(b_end, 0, band - 1)
    mv, xv, yv = m_f[b0], ix_f[b0], iy_f[b0]
    score = jnp.where(in_band, jnp.maximum(mv, jnp.maximum(xv, yv)), NEG)
    mat0 = jnp.where((mv >= xv) & (mv >= yv), 0,
                     jnp.where(xv >= yv, 1, 2)).astype(jnp.int32)

    def row_step(state, xs):
        b, mat = state
        ptr_row, i = xs               # walking row i (m_max down to 1)
        live = i <= q_len
        p = ptr_row.astype(jnp.int32)
        # Iy run length entering this row at every band position
        by = (p >> 3) & 1
        z = jnp.where(by == 0, bidx, -1)
        last_zero = jax.lax.associative_scan(jnp.maximum, z)
        k_at = bidx - last_zero + 1
        is_iy = mat == 2
        k_b = jnp.sum(jnp.where(bidx == b, k_at, 0))
        iy_run = jnp.where(live & is_iy, k_b, 0)
        b_mid = b - iy_run            # an Iy run always lands in M
        mat_mid = jnp.where(is_iy, 0, mat)
        p_mid = jnp.sum(jnp.where(bidx == b_mid, p, 0))
        dm = p_mid & 3
        bx = (p_mid >> 2) & 1
        is_ix = mat_mid == 1
        op = jnp.where(~live, 0,
                       jnp.where(is_ix, OP_IX, OP_DIAG)).astype(jnp.int8)
        nb = jnp.where(is_ix, b_mid + 1, b_mid)
        nmat = jnp.where(is_ix, jnp.where(bx == 1, 1, 0), dm)
        nb = jnp.where(live, nb, b)
        nmat = jnp.where(live, nmat, mat)
        return (nb, nmat), (iy_run.astype(jnp.int32), op)

    rows_desc = jnp.arange(m_max, 0, -1, dtype=jnp.int32)
    (b_f, _mat_f), (iy_rev, ops_rev) = jax.lax.scan(
        row_step, (b0.astype(jnp.int32), mat0),
        (ptrs[::-1], rows_desc))
    # at row 0 only the init Iy chain exists: the remaining j becomes the
    # leading gap-in-query run (reference cs-walk leading '-' case)
    lead = dlo + b_f
    ok = in_band & (score > NEG // 2) & (lead >= 0)
    lead = jnp.where(ok, lead, 0)
    return (score.astype(jnp.int32), lead.astype(jnp.int32),
            iy_rev[::-1], ops_rev[::-1], ok)


@functools.partial(jax.jit, static_argnames=("band", "params"))
def _rowwalk_batch_jit(qs, ts, q_lens, t_lens, dlo, band, params):
    n = ts.shape[1]

    def lane(q_seg, t, q_len, t_len):
        m_f, ix_f, iy_f, ptrs = _forward_lane(q_seg, t, q_len, n, dlo,
                                              band, params)
        return _rowwalk_lane(ptrs, q_len, t_len, m_f, ix_f, iy_f, n,
                             dlo, band)

    return jax.vmap(lane)(qs, ts, q_lens.astype(jnp.int32),
                          t_lens.astype(jnp.int32))


def _select_kernel(m_max: int, n: int, band: int) -> str:
    """Auto kernel choice for ``banded_realign_rows``:
    - resident pallas when target window + query column + carry +
      pointer tiles fit per 128-lane block, double-buffered — about
      (n + m + 8*band) * 1024 bytes against Mosaic's 16 MB scoped vmem
      (band=1024 escalations were seen rejected at ~18 MB);
    - streaming pallas when only the (band+8)-row windows and carries
      are resident — bounded by band alone;
    - the XLA scan off-TPU or for bands no kernel variant fits."""
    from pwasm_tpu.ops import on_tpu_backend

    if band % 8 or not on_tpu_backend():
        return "xla"
    if (n + m_max + 8 * band + 160) * 1024 <= 10 << 20:
        return "pallas"
    if (10 * band + 200) * 1024 <= 10 << 20:
        return "pallas_long"
    return "xla"


def banded_realign_rows(qs: jax.Array, ts: jax.Array,
                        q_lens: jax.Array, t_lens: jax.Array,
                        band: int = 64,
                        params: ScoreParams = ScoreParams(),
                        dlo: int | None = None,
                        kernel: str | None = None):
    """Batched banded re-alignment, compressed row form (all on device).

    qs: (T, m_max) int8 per-lane query segments (codes, pad 127)
    ts: (T, n) int8 per-lane targets (codes, pad 127)
    q_lens / t_lens: (T,) true lengths
    dlo: band placement (diagonals covered are [dlo, dlo+band));
    default centers the band on the main diagonal.  ``dlo`` is traced,
    not static — re-placing the band between flushes reuses the
    compiled program.

    Returns ``(scores, leads, iy_runs, ops_rows, ok)``:
    scores (T,) int32 global scores at (q_len, t_len);
    leads (T,) int32 leading gap-in-query run;
    iy_runs (T, m_max) int32 per-row Iy run AFTER the row's op;
    ops_rows (T, m_max) int8 per-row leaving op (1=DIAG, 2=IX; 0 pad);
    ok (T,) bool — band covered the end cell and the walk closed.
    Lanes with ``ok=False`` need a wider band (see ``realign_pairs``
    escalation) or the host oracle.

    ``kernel``: 'pallas' (fused TPU kernels, sequences resident in
    VMEM; band must be a multiple of 8), 'pallas_long' (same kernels
    with the sequences streamed from HBM in double-buffered windows —
    long-read shapes), 'xla' (lax.scan path, any band, traced dlo), or
    None = auto: resident pallas when the footprint fits VMEM, the
    streaming variant for bigger shapes on TPU, xla elsewhere.  Outputs
    are bit-identical across all three.
    """
    if band < 1:
        raise ValueError(f"band must be >= 1, got {band}")
    if dlo is None:
        dlo = -(band // 2)
    if kernel is None:
        kernel = _select_kernel(qs.shape[1], ts.shape[1], band)
    if kernel in ("pallas", "pallas_long"):
        return _rowwalk_batch_pallas(jnp.asarray(qs), jnp.asarray(ts),
                                     jnp.asarray(q_lens),
                                     jnp.asarray(t_lens),
                                     int(dlo), band, params,
                                     streaming=kernel == "pallas_long")
    return _rowwalk_batch_jit(qs, ts, q_lens, t_lens,
                              jnp.int32(dlo), band, params)


def rows_to_ops_fwd(lead: int, iy_runs: np.ndarray, ops_rows: np.ndarray,
                    q_len: int) -> np.ndarray:
    """Expand one lane's compressed rows to the forward op string
    (host side; only needed when a caller wants the full path)."""
    vals = np.empty(2 * q_len + 1, dtype=np.int8)
    lens = np.empty(2 * q_len + 1, dtype=np.int64)
    vals[0] = OP_IY
    lens[0] = lead
    vals[1::2] = ops_rows[:q_len]
    lens[1::2] = 1
    vals[2::2] = OP_IY
    lens[2::2] = iy_runs[:q_len]
    return np.repeat(vals, lens)


def banded_traceback_batch(qs: jax.Array, ts: jax.Array,
                           q_lens: jax.Array, t_lens: jax.Array,
                           band: int = 64,
                           params: ScoreParams = ScoreParams(),
                           dlo: int | None = None):
    """Batched banded re-alignment with an expanded op-string traceback.

    Compatibility wrapper over ``banded_realign_rows``: fetches the
    compressed rows and expands them on host.  Returns ``(scores,
    ops_bwd, ok)`` with ops_bwd (T, m_max + n) int8 REVERSE-order ops,
    0-padded.  Prefer ``banded_realign_rows`` + ``realign_gaps_batch``
    in throughput paths — they never materialize O(m + n) per lane.
    """
    scores, leads, iy_runs, ops_rows, ok = banded_realign_rows(
        qs, ts, q_lens, t_lens, band=band, params=params, dlo=dlo)
    scores = np.asarray(scores)
    leads = np.asarray(leads)
    iy_runs = np.asarray(iy_runs)
    ops_rows = np.asarray(ops_rows)
    ok = np.asarray(ok)
    T, m_max = iy_runs.shape
    width = m_max + ts.shape[1]
    q_lens = np.asarray(q_lens)
    ops_bwd = np.zeros((T, width), dtype=np.int8)
    for k in range(T):
        if not ok[k]:
            continue
        fwd = rows_to_ops_fwd(int(leads[k]), iy_runs[k], ops_rows[k],
                              int(q_lens[k]))
        ops_bwd[k, :len(fwd)] = fwd[::-1]
    return scores, ops_bwd, ok


# ---------------------------------------------------------------------------
# Pallas TPU kernels: pointer-emitting forward + row-parallel walk.
# Same tile geometry as ops/banded_dp.py's scores kernel (band on the
# sublane axis, block_t targets on the lane axis), but the query is
# per-lane (a (m, block_t) VMEM tile, one vector row per DP row) and the
# grid adds a row-chunk axis: each grid step advances 8 query rows and
# writes one (band, block_t) int32 tile of PACKED pointers (8 rows x 4
# bits).  The walk kernel replays the chunks in reverse, carrying the
# per-lane (band index, matrix) state in scratch, and emits the
# compressed (iy_run, op) row stream — identical, bit for bit, to the
# XLA row-walk (fuzzed in tests/test_realign.py).
# ---------------------------------------------------------------------------
def _fwdptr_init(n, band, dlo, go, ge, block_t):
    bidx = jax.lax.broadcasted_iota(jnp.int32, (band, block_t), 0)
    j0 = dlo + bidx
    return (jnp.where(j0 == 0, 0, NEG),
            jnp.full((band, block_t), NEG, dtype=jnp.int32),
            jnp.where((j0 >= 1) & (j0 <= n), -(go + (j0 - 1) * ge), NEG))


def _fwdptr_block(win, q8, q_len, i0, carry, *, n, band, dlo,
                  match, mismatch, go, ge, block_t, interior=False):
    """8 DP rows over one (>= band+7, block_t) target window starting at
    absolute row i0+1; ``q8`` holds the 8 per-lane query bases.  Shared
    by the resident and HBM-streaming forward kernels, so their pointers
    and scores are identical by construction.  Returns (carry, packed
    pointer tile).

    ``interior`` (trace-time) elides the band-boundary masks — valid
    only when all 8 rows keep the whole band inside 1..n, i.e.
    ``i0 + 1 >= 1 - dlo`` and ``i0 + 8 <= n - band - dlo + 1`` (the
    same condition the scores kernel splits its phases on); the
    per-lane q_len freeze is data-dependent and always stays."""
    bidx = jax.lax.broadcasted_iota(jnp.int32, (band, block_t), 0)
    neg = jnp.full((band, block_t), NEG, dtype=jnp.int32)
    m_prev, ix_prev, iy_prev = carry
    packed = jnp.zeros((band, block_t), jnp.int32)
    for r in range(8):
        i = i0 + r + 1                         # 1-based absolute row
        qi = q8[r:r + 1, :]                    # (1, block_t) per-lane base
        tj = win[r:r + band]
        s = jnp.where((tj == qi) & (qi < 4), match, -mismatch)
        diag = jnp.maximum(m_prev, jnp.maximum(ix_prev, iy_prev))
        dm = jnp.where((m_prev >= ix_prev) & (m_prev >= iy_prev), 0,
                       jnp.where(ix_prev >= iy_prev, 1, 2))
        m_new = diag + s
        up_m = jnp.concatenate([m_prev[1:], neg[:1]], axis=0)
        up_ix = jnp.concatenate([ix_prev[1:], neg[:1]], axis=0)
        bx = (up_ix - ge > up_m - go).astype(jnp.int32)
        ix_new = jnp.maximum(up_m - go, up_ix - ge)
        if not interior:
            j = i + dlo + bidx
            valid = (j >= 1) & (j <= n)
            m_new = jnp.where(valid, m_new, NEG)
            ix_new = jnp.where(j == 0, -(go + (i - 1) * ge), ix_new)
            ix_new = jnp.where((j < 0) | (j > n), NEG, ix_new)
        run = m_new + bidx * ge
        sh = 1
        while sh < band:
            run = jnp.maximum(
                run, jnp.concatenate([neg[:sh], run[:-sh]], axis=0))
            sh *= 2
        run_prev = jnp.concatenate([neg[:1], run[:-1]], axis=0)
        iy_new = run_prev - go - (bidx - 1) * ge
        if not interior:
            iy_new = jnp.where(valid, iy_new, NEG)
        m_left = jnp.concatenate([neg[:1], m_new[:-1]], axis=0)
        iy_left = jnp.concatenate([neg[:1], iy_new[:-1]], axis=0)
        by = (iy_left - ge > m_left - go).astype(jnp.int32)
        packed = packed | ((dm | (bx << 2) | (by << 3)) << (4 * r))
        keep = i <= q_len                      # rows past q_len freeze
        m_prev = jnp.where(keep, m_new, m_prev)
        ix_prev = jnp.where(keep, ix_new, ix_prev)
        iy_prev = jnp.where(keep, iy_new, iy_prev)
    return (m_prev, ix_prev, iy_prev), packed


def _fwdptr_extract(carry, q_len, t_len, band, dlo,
                    score_ref, b0_ref, mat0_ref):
    m_prev, ix_prev, iy_prev = carry
    bidx = jax.lax.broadcasted_iota(jnp.int32, m_prev.shape, 0)
    b_end = t_len - q_len - dlo
    in_band = (b_end >= 0) & (b_end < band)
    sel = bidx == b_end
    mv = jnp.max(jnp.where(sel, m_prev, NEG), axis=0, keepdims=True)
    xv = jnp.max(jnp.where(sel, ix_prev, NEG), axis=0, keepdims=True)
    yv = jnp.max(jnp.where(sel, iy_prev, NEG), axis=0, keepdims=True)
    best = jnp.maximum(mv, jnp.maximum(xv, yv))
    score_ref[...] = jnp.where(in_band, best, NEG)
    b0_ref[...] = jnp.clip(b_end, 0, band - 1)
    mat0_ref[...] = jnp.where((mv >= xv) & (mv >= yv), 0,
                              jnp.where(xv >= yv, 1, 2))


def _fwdptr_kernel(q_ref, t_ref, qlen_ref, tlen_ref,
                   ptr_ref, score_ref, b0_ref, mat0_ref,
                   m_c, ix_c, iy_c, *, n, band, dlo,
                   match, mismatch, go, ge, block_t, m8):
    from jax.experimental import pallas as pl

    p8 = pl.program_id(1)

    @pl.when(p8 == 0)
    def _():
        m0, x0, y0 = _fwdptr_init(n, band, dlo, go, ge, block_t)
        m_c[...] = m0
        ix_c[...] = x0
        iy_c[...] = y0

    q_len = qlen_ref[...]                      # (1, block_t)
    i0 = p8 * 8
    win = t_ref[pl.ds(i0 + dlo + band, band + 7), :]
    q8 = q_ref[pl.ds(i0, 8), :]
    carry_in = (m_c[...], ix_c[...], iy_c[...])
    # all 8 rows keep the whole band inside 1..n: run the statically
    # mask-elided block body (the scores kernel's interior trick); the
    # row-block index is a grid coordinate, so the split is a runtime
    # branch rather than a static phase split
    interior_ok = (i0 + 1 >= 1 - dlo) & (i0 + 8 <= n - band - dlo + 1)

    def run_block(interior):
        carry, packed = _fwdptr_block(
            win, q8, q_len, i0, carry_in,
            n=n, band=band, dlo=dlo, match=match, mismatch=mismatch,
            go=go, ge=ge, block_t=block_t, interior=interior)
        m_c[...], ix_c[...], iy_c[...] = carry
        ptr_ref[0] = packed

    @pl.when(interior_ok)
    def _():
        run_block(True)

    @pl.when(jnp.logical_not(interior_ok))
    def _():
        run_block(False)

    @pl.when(p8 == m8 - 1)
    def _():
        _fwdptr_extract((m_c[...], ix_c[...], iy_c[...]), q_len,
                        tlen_ref[...], band, dlo,
                        score_ref, b0_ref, mat0_ref)


def _fwdptr_kernel_long(q_hbm, t_hbm, qlen_ref, tlen_ref,
                        ptr_ref, score_ref, b0_ref, mat0_ref,
                        m_c, ix_c, iy_c, tbuf0, tbuf1, qbuf0, qbuf1,
                        sems, *, n, band, dlo, match, mismatch, go, ge,
                        block_t, m8):
    """HBM-streaming variant: the target and query stay in HBM/ANY and
    each grid step's (band+8, block_t) window and (8, block_t) query
    rows stream into double-buffered VMEM scratch (the banded_scores_long
    pattern, GapAssem has no analog) — so 50 kb+ re-alignments fit in
    VMEM.  DP math is the shared ``_fwdptr_block``: bit-identical to the
    resident kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tb = pl.program_id(0)
    p8 = pl.program_id(1)

    def t_dma(buf, slot, step):
        return pltpu.make_async_copy(
            t_hbm.at[pl.ds(step * 8 + dlo + band, band + 8),
                     pl.ds(tb * block_t, block_t)], buf, sems.at[slot])

    def q_dma(buf, slot, step):
        return pltpu.make_async_copy(
            q_hbm.at[pl.ds(step * 8, 8),
                     pl.ds(tb * block_t, block_t)], buf,
            sems.at[2 + slot])

    @pl.when(p8 == 0)
    def _():
        m0, x0, y0 = _fwdptr_init(n, band, dlo, go, ge, block_t)
        m_c[...] = m0
        ix_c[...] = x0
        iy_c[...] = y0
        t_dma(tbuf0, 0, 0).start()
        q_dma(qbuf0, 0, 0).start()

    # prefetch the next chunk into the other slot before consuming this
    # one (slots alternate by grid-step parity; the other slot's buffer
    # was consumed on the previous step)
    @pl.when((p8 + 1 < m8) & (p8 % 2 == 0))
    def _():
        t_dma(tbuf1, 1, p8 + 1).start()
        q_dma(qbuf1, 1, p8 + 1).start()

    @pl.when((p8 + 1 < m8) & (p8 % 2 == 1))
    def _():
        t_dma(tbuf0, 0, p8 + 1).start()
        q_dma(qbuf0, 0, p8 + 1).start()

    q_len = qlen_ref[...]
    # mask-elided interior body for fully in-band row blocks (the same
    # runtime split as the resident kernel)
    i0 = p8 * 8
    interior_ok = (i0 + 1 >= 1 - dlo) & (i0 + 8 <= n - band - dlo + 1)

    def compute(tbuf, qbuf, slot, interior):
        t_dma(tbuf, slot, p8).wait()
        q_dma(qbuf, slot, p8).wait()
        carry, packed = _fwdptr_block(
            tbuf[...], qbuf[...], q_len, i0,
            (m_c[...], ix_c[...], iy_c[...]),
            n=n, band=band, dlo=dlo, match=match, mismatch=mismatch,
            go=go, ge=ge, block_t=block_t, interior=interior)
        m_c[...], ix_c[...], iy_c[...] = carry
        ptr_ref[0] = packed

    for parity in (0, 1):
        for inter in (True, False):
            @pl.when((p8 % 2 == parity)
                     & (interior_ok if inter
                        else jnp.logical_not(interior_ok)))
            def _(parity=parity, inter=inter):
                compute(tbuf0 if parity == 0 else tbuf1,
                        qbuf0 if parity == 0 else qbuf1, parity, inter)

    @pl.when(p8 == m8 - 1)
    def _():
        _fwdptr_extract((m_c[...], ix_c[...], iy_c[...]), q_len,
                        tlen_ref[...], band, dlo,
                        score_ref, b0_ref, mat0_ref)


def _walk_kernel(packed_ref, b0_ref, mat0_ref, qlen_ref,
                 walk_ref, bf_ref, b_c, mat_c, *, band, block_t, m8):
    from jax.experimental import pallas as pl

    p8 = pl.program_id(1)
    chunk = m8 - 1 - p8                        # row chunks in reverse
    bidx = jax.lax.broadcasted_iota(jnp.int32, (band, block_t), 0)

    @pl.when(p8 == 0)
    def _():
        b_c[...] = b0_ref[...]
        mat_c[...] = mat0_ref[...]

    q_len = qlen_ref[...]                      # (1, block_t)
    packed = packed_ref[0]
    b = b_c[...]
    mat = mat_c[...]
    for r in range(7, -1, -1):
        i = chunk * 8 + r + 1
        ptr = (packed >> (4 * r)) & 0xF
        by = (ptr >> 3) & 1
        z = jnp.where(by == 0, bidx, -1)
        sh = 1
        while sh < band:                       # cumulative max: lastZero
            z = jnp.maximum(z, jnp.concatenate(
                [jnp.full((sh, block_t), -1, jnp.int32), z[:-sh]],
                axis=0))
            sh *= 2
        k_at = bidx - z + 1
        live = i <= q_len
        is_iy = mat == 2
        k_b = jnp.sum(jnp.where(bidx == b, k_at, 0), axis=0,
                      keepdims=True)
        iy_run = jnp.where(live & is_iy, k_b, 0)
        b_mid = b - iy_run                     # an Iy run lands in M
        p_mid = jnp.sum(jnp.where(bidx == b_mid, ptr, 0), axis=0,
                        keepdims=True)
        dm = p_mid & 3
        bx = (p_mid >> 2) & 1
        is_ix = jnp.where(is_iy, 0, mat) == 1
        op = jnp.where(live, jnp.where(is_ix, OP_IX, OP_DIAG), 0)
        nb = jnp.where(is_ix, b_mid + 1, b_mid)
        nmat = jnp.where(is_ix, jnp.where(bx == 1, 1, 0), dm)
        b = jnp.where(live, nb, b)
        mat = jnp.where(live, nmat, mat)
        walk_ref[0, r:r + 1, :] = iy_run * 4 + op
    b_c[...] = b
    mat_c[...] = mat

    @pl.when(p8 == m8 - 1)
    def _():
        bf_ref[...] = b


@functools.partial(jax.jit, static_argnames=("dlo", "band", "params",
                                             "block_t", "interpret",
                                             "streaming"))
def _rowwalk_batch_pallas(qs, ts, q_lens, t_lens, dlo: int, band: int,
                          params: ScoreParams, block_t: int = 128,
                          interpret: bool | None = None,
                          streaming: bool = False):
    """Pallas path of ``banded_realign_rows`` — same output contract as
    ``_rowwalk_batch_jit``, bit for bit (fuzz-gated in tests).  With
    ``streaming`` the forward kernel keeps sequences in HBM and streams
    per-chunk windows (long-read shapes that don't fit VMEM resident)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        from pwasm_tpu.ops import default_interpret
        interpret = default_interpret()
    T, m_max = qs.shape
    n = ts.shape[1]
    m8 = (m_max + 7) // 8
    m_pad8 = m8 * 8
    pad_t = (T + block_t - 1) // block_t * block_t
    if pad_t != T:
        qs = jnp.pad(qs, ((0, pad_t - T), (0, 0)), constant_values=127)
        ts = jnp.pad(ts, ((0, pad_t - T), (0, 0)), constant_values=127)
        q_lens = jnp.pad(q_lens, (0, pad_t - T))
        t_lens = jnp.pad(t_lens, (0, pad_t - T))
    qs_T = jnp.pad(qs.astype(jnp.int32).T, ((0, m_pad8 - m_max), (0, 0)),
                   constant_values=127)
    ts_T = jnp.pad(ts.astype(jnp.int32).T, ((band, band + 16), (0, 0)),
                   constant_values=127)
    grid = (pad_t // block_t, m8)
    common = dict(n=n, band=band, dlo=dlo, match=params.match,
                  mismatch=params.mismatch, go=params.go,
                  ge=params.gap_extend, block_t=block_t, m8=m8)
    out_specs = [
        pl.BlockSpec((1, band, block_t), lambda tb, p8: (p8, 0, tb)),
        pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb)),
        pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb)),
        pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((m8, band, pad_t), jnp.int32),
        jax.ShapeDtypeStruct((1, pad_t), jnp.int32),
        jax.ShapeDtypeStruct((1, pad_t), jnp.int32),
        jax.ShapeDtypeStruct((1, pad_t), jnp.int32),
    ]
    lens_spec = pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb))
    if streaming:
        # target and query stay in HBM; per-step windows stream into
        # double-buffered VMEM scratch — m and n bounded by HBM only
        ptrs, scores, b0, mat0 = pl.pallas_call(
            functools.partial(_fwdptr_kernel_long, **common),
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                lens_spec,
                lens_spec,
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((band, block_t), jnp.int32),
                pltpu.VMEM((band, block_t), jnp.int32),
                pltpu.VMEM((band, block_t), jnp.int32),
                pltpu.VMEM((band + 8, block_t), jnp.int32),
                pltpu.VMEM((band + 8, block_t), jnp.int32),
                pltpu.VMEM((8, block_t), jnp.int32),
                pltpu.VMEM((8, block_t), jnp.int32),
                pltpu.SemaphoreType.DMA((4,)),
            ],
            interpret=interpret,
        )(qs_T, ts_T, q_lens.astype(jnp.int32)[None, :],
          t_lens.astype(jnp.int32)[None, :])
    else:
        ptrs, scores, b0, mat0 = pl.pallas_call(
            functools.partial(_fwdptr_kernel, **common),
            grid=grid,
            in_specs=[
                pl.BlockSpec((m_pad8, block_t), lambda tb, p8: (0, tb)),
                pl.BlockSpec((n + 2 * band + 16, block_t),
                             lambda tb, p8: (0, tb)),
                lens_spec,
                lens_spec,
            ],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((band, block_t), jnp.int32)] * 3,
            interpret=interpret,
        )(qs_T, ts_T, q_lens.astype(jnp.int32)[None, :],
          t_lens.astype(jnp.int32)[None, :])

    walk = functools.partial(_walk_kernel, band=band, block_t=block_t,
                             m8=m8)
    walk_rows, b_f = pl.pallas_call(
        walk,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, band, block_t),
                         lambda tb, p8: (m8 - 1 - p8, 0, tb)),
            pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb)),
            pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb)),
            pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb)),
        ],
        out_specs=[
            pl.BlockSpec((1, 8, block_t),
                         lambda tb, p8: (m8 - 1 - p8, 0, tb)),
            pl.BlockSpec((1, block_t), lambda tb, p8: (0, tb)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m8, 8, pad_t), jnp.int32),
            jax.ShapeDtypeStruct((1, pad_t), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((1, block_t), jnp.int32)] * 2,
        interpret=interpret,
    )(ptrs, b0, mat0, q_lens.astype(jnp.int32)[None, :])

    rows = walk_rows.reshape(m8 * 8, pad_t)[:m_max, :T].T
    iy_runs = rows // 4
    ops_rows = (rows & 3).astype(jnp.int8)
    scores = scores[0, :T]
    leads = dlo + b_f[0, :T]
    ok = (scores > NEG // 2) & (leads >= 0)
    leads = jnp.where(ok, leads, 0)
    return scores, leads, iy_runs, ops_rows, ok


# ---------------------------------------------------------------------------
# multi-chip: lanes shard over the mesh, every device runs the fused
# kernels on its shard (embarrassingly parallel — no collectives)
# ---------------------------------------------------------------------------
def _shard_specs(mesh):
    from jax.sharding import PartitionSpec as P

    axes = tuple(mesh.axis_names)
    dp = axes if len(axes) > 1 else axes[0]
    return (P(dp, None), P(dp, None), P(dp), P(dp)), \
        (P(dp), P(dp), P(dp, None), P(dp, None), P(dp))


# check_vma off in both wrappers: the block is collective-free, and the
# DP scan's constant initial wavefront is device-invariant while its
# outputs vary per shard — exactly the pattern the varying-axis checker
# rejects
@functools.partial(jax.jit, static_argnames=("mesh", "band", "params",
                                             "dlo", "kernel"))
def _sharded_rows_static(qs, ts, q_lens, t_lens, mesh, band: int,
                         params: ScoreParams, dlo: int, kernel: str):
    """Sharded dispatch for the Pallas kernels (dlo is genuinely static
    there — the unsharded Pallas path recompiles per placement too)."""
    from pwasm_tpu.utils.jaxcompat import shard_map

    def block(qs_l, ts_l, ql_l, tl_l):
        return banded_realign_rows(qs_l, ts_l, ql_l, tl_l, band=band,
                                   params=params, dlo=dlo, kernel=kernel)

    in_specs, out_specs = _shard_specs(mesh)
    fn = shard_map(block, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    return fn(qs, ts, q_lens, t_lens)


@functools.partial(jax.jit, static_argnames=("mesh", "band", "params"))
def _sharded_rows_traced(qs, ts, q_lens, t_lens, dlo, mesh, band: int,
                         params: ScoreParams):
    """Sharded dispatch for the XLA scan path: ``dlo`` stays a traced
    replicated scalar, so re-placing the band between flushes reuses
    the compiled program (same contract as the unsharded XLA path)."""
    from pwasm_tpu.utils.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    def block(qs_l, ts_l, ql_l, tl_l, dlo_l):
        return _rowwalk_batch_jit(qs_l, ts_l, ql_l, tl_l, dlo_l, band,
                                  params)

    in_specs, out_specs = _shard_specs(mesh)
    fn = shard_map(block, mesh=mesh, in_specs=in_specs + (P(),),
                   out_specs=out_specs, check_vma=False)
    return fn(qs, ts, q_lens, t_lens, dlo)


def sharded_realign_rows(mesh, qs, ts, q_lens, t_lens, band: int = 64,
                         params: ScoreParams = ScoreParams(),
                         dlo: int | None = None):
    """``banded_realign_rows`` with the lane axis sharded over every
    mesh axis (the ``pafreport --shard`` realign path): each device runs
    the fused forward+walk kernels on its own lane shard.  Lanes are
    padded to a mesh multiple with empty entries (ok=False) and sliced
    back; results are bit-identical to the unsharded call."""
    if dlo is None:
        dlo = -(band // 2)
    n_mesh = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    T = qs.shape[0]
    pad = -T % n_mesh
    if pad:
        qs = np.pad(qs, ((0, pad), (0, 0)), constant_values=127)
        ts = np.pad(ts, ((0, pad), (0, 0)), constant_values=127)
        q_lens = np.pad(q_lens, (0, pad))
        t_lens = np.pad(t_lens, (0, pad))
    args = (jnp.asarray(qs), jnp.asarray(ts), jnp.asarray(q_lens),
            jnp.asarray(t_lens))
    kernel = _select_kernel(qs.shape[1], ts.shape[1], band)
    if kernel == "xla":
        out = _sharded_rows_traced(*args, jnp.int32(dlo), mesh, band,
                                   params)
    else:
        out = _sharded_rows_static(*args, mesh, band, params, int(dlo),
                                   kernel)
    return tuple(x[:T] for x in out)


# ---------------------------------------------------------------------------
# device-side gap extraction: compressed rows -> fixed-capacity gap slots
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("max_gaps",))
def _gaps_jit(leads, iy_runs, ops_rows, q_lens, max_gaps: int):
    m_max = iy_runs.shape[1]
    G = max_gaps

    def lane(lead, iy, op, q_len):
        rows = jnp.arange(1, m_max + 1, dtype=jnp.int32)
        live = rows <= q_len
        iy = jnp.where(live, iy, 0)
        opl = jnp.where(live, op.astype(jnp.int32), 0)
        diag = (opl == OP_DIAG).astype(jnp.int32)
        consumed = iy + diag
        # target bases consumed before each row's op (exclusive prefix)
        tcons = lead + jnp.cumsum(consumed) - consumed
        # gaps in the query: the lead run at qpos 0, then every row with
        # an Iy run, at qpos = row (the run follows the row's op)
        has_lead = (lead > 0).astype(jnp.int32)
        r_mask = iy > 0
        slot = jnp.where(r_mask, jnp.cumsum(r_mask) - 1 + has_lead, G)
        rg_pos = jnp.zeros(G, jnp.int32).at[slot].set(rows, mode="drop")
        rg_len = jnp.zeros(G, jnp.int32).at[slot].set(iy, mode="drop")
        lead_slot = jnp.where(has_lead == 1, 0, G)
        rg_pos = rg_pos.at[lead_slot].set(0, mode="drop")
        rg_len = rg_len.at[lead_slot].set(lead, mode="drop")
        r_count = jnp.sum(r_mask) + has_lead
        # gaps in the target: maximal runs of op == OP_IX rows, at the
        # target position where the run starts
        is_ix = opl == OP_IX
        prev = jnp.concatenate([jnp.zeros(1, dtype=bool), is_ix[:-1]])
        start = is_ix & ~prev
        idx = jnp.arange(m_max, dtype=jnp.int32)
        nni = jax.lax.associative_scan(          # next non-Ix row index
            jnp.minimum, jnp.where(is_ix, m_max, idx), reverse=True)
        length = nni - idx
        t_slot = jnp.where(start, jnp.cumsum(start) - 1, G)
        tg_pos = jnp.zeros(G, jnp.int32).at[t_slot].set(tcons,
                                                        mode="drop")
        tg_len = jnp.zeros(G, jnp.int32).at[t_slot].set(length,
                                                        mode="drop")
        t_count = jnp.sum(start)
        overflow = (r_count > G) | (t_count > G)
        return (rg_pos, rg_len, r_count.astype(jnp.int32),
                tg_pos, tg_len, t_count.astype(jnp.int32), overflow)

    return jax.vmap(lane)(leads, iy_runs, ops_rows,
                          q_lens.astype(jnp.int32))


def realign_gaps_batch(qs: jax.Array, ts: jax.Array,
                       q_lens: jax.Array, t_lens: jax.Array,
                       band: int = 64,
                       params: ScoreParams = ScoreParams(),
                       dlo: int | None = None, max_gaps: int = 32):
    """Re-align a batch and extract gap records entirely on device.

    Returns ``(scores, ok, (rg_pos, rg_len, r_count, tg_pos, tg_len,
    t_count, overflow))`` — per lane, up to ``max_gaps`` (pos, len)
    slots per side in forward coordinates (rg_pos = qpos of the run,
    tg_pos = tpos where the run starts); ``overflow`` lanes have more
    gaps than slots and must take the expanded-ops path.  Feed slots to
    ``gap_slots_to_gapdata`` for the CIGAR-walk strand conventions."""
    scores, leads, iy_runs, ops_rows, ok = banded_realign_rows(
        qs, ts, q_lens, t_lens, band=band, params=params, dlo=dlo)
    return scores, ok, _gaps_jit(leads, iy_runs, ops_rows, q_lens,
                                 max_gaps)


def gap_slots_to_gapdata(rg_pos, rg_len, r_count, tg_pos, tg_len, t_count,
                         offset: int, r_len: int, eff_t_len: int,
                         reverse: int
                         ) -> tuple[list[GapData], list[GapData]]:
    """One lane's device gap slots -> (rgaps, tgaps) GapData lists with
    the exact conventions of ``ops_to_gaps`` (strand flip included)."""
    rgaps: list[GapData] = []
    for i in range(int(r_count)):
        pos = offset + int(rg_pos[i])
        if reverse:
            pos = r_len - pos
        rgaps.append(GapData(pos, int(rg_len[i])))
    tgaps: list[GapData] = []
    for i in range(int(t_count)):
        pos = int(tg_pos[i])
        tgaps.append(GapData(eff_t_len - pos if reverse else pos,
                             int(tg_len[i])))
    return rgaps, tgaps


# ---------------------------------------------------------------------------
# host-side conversion: op runs -> GapData lists (CIGAR-walk conventions)
# ---------------------------------------------------------------------------
def ops_forward(ops_bwd_row: np.ndarray) -> np.ndarray:
    """Reverse the non-zero prefix of one traceback row into forward
    alignment order."""
    k = int((ops_bwd_row != 0).sum())
    return ops_bwd_row[:k][::-1]


def ops_to_gaps(ops_fwd: np.ndarray, offset: int, r_len: int,
                eff_t_len: int, reverse: int
                ) -> tuple[list[GapData], list[GapData]]:
    """Convert a forward op string to (rgaps, tgaps) with the exact
    conventions of the CIGAR walk (core/events.py:296-314; reference
    pafreport.cpp:680-697): Ix runs are target gaps at the current
    target position (strand-flipped when reverse), Iy runs are query
    gaps at offset+qpos (strand-flipped when reverse)."""
    rgaps: list[GapData] = []
    tgaps: list[GapData] = []
    qpos = tpos = 0
    i = 0
    L = len(ops_fwd)
    while i < L:
        op = ops_fwd[i]
        j = i
        while j < L and ops_fwd[j] == op:
            j += 1
        run = j - i
        if op == OP_DIAG:
            qpos += run
            tpos += run
        elif op == OP_IX:   # gap in the target sequence
            tgaps.append(GapData(eff_t_len - tpos if reverse else tpos,
                                 run))
            qpos += run
        elif op == OP_IY:   # gap in the query
            pos = offset + qpos
            if reverse:
                pos = r_len - pos
            rgaps.append(GapData(pos, run))
            tpos += run
        i = j
    return rgaps, tgaps


def ops_consumed(ops_fwd: np.ndarray) -> tuple[int, int]:
    """(query bases, target bases) consumed by a forward op string."""
    q = int(((ops_fwd == OP_DIAG) | (ops_fwd == OP_IX)).sum())
    t = int(((ops_fwd == OP_DIAG) | (ops_fwd == OP_IY)).sum())
    return q, t


def ops_score(ops_fwd: np.ndarray, q: np.ndarray, t: np.ndarray,
              params: ScoreParams = ScoreParams()) -> int:
    """Score a forward op string (independent check that the traceback
    path actually achieves the DP score)."""
    s = 0
    qpos = tpos = 0
    prev = 0
    for op in ops_fwd:
        if op == OP_DIAG:
            match = q[qpos] == t[tpos] and q[qpos] < 4
            s += params.match if match else -params.mismatch
            qpos += 1
            tpos += 1
        elif op == OP_IX:
            s -= params.go if prev != OP_IX else params.gap_extend
            qpos += 1
        elif op == OP_IY:
            s -= params.go if prev != OP_IY else params.gap_extend
            tpos += 1
        prev = op
    return s


# ---------------------------------------------------------------------------
# numpy oracle: full-matrix Gotoh traceback with the same tie-breaks
# ---------------------------------------------------------------------------
def full_gotoh_traceback(q: np.ndarray, t: np.ndarray,
                         params: ScoreParams = ScoreParams()
                         ) -> tuple[int, np.ndarray]:
    """Unbanded Gotoh with traceback — the independent host oracle.
    Tie-breaks match the device kernel by definition: diag argmax prefers
    M, then Ix, then Iy; gap recurrences prefer open on ties.  Returns
    (score, forward op array)."""
    m, n = len(q), len(t)
    ge, go = params.gap_extend, params.go
    M = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Ix = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Iy = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    DM = np.zeros((m + 1, n + 1), dtype=np.int8)   # diag argmax
    BX = np.zeros((m + 1, n + 1), dtype=np.int8)   # Ix from extend
    BY = np.zeros((m + 1, n + 1), dtype=np.int8)   # Iy from extend
    M[0, 0] = 0
    for j in range(1, n + 1):
        Iy[0, j] = -(go + (j - 1) * ge)
        BY[0, j] = 1 if j > 1 else 0
    for i in range(1, m + 1):
        Ix[i, 0] = -(go + (i - 1) * ge)
        BX[i, 0] = 1 if i > 1 else 0
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            s = params.match if (q[i - 1] == t[j - 1] and q[i - 1] < 4) \
                else -params.mismatch
            a, b, c = M[i - 1, j - 1], Ix[i - 1, j - 1], Iy[i - 1, j - 1]
            if a >= b and a >= c:
                DM[i, j] = 0
                M[i, j] = a + s
            elif b >= c:
                DM[i, j] = 1
                M[i, j] = b + s
            else:
                DM[i, j] = 2
                M[i, j] = c + s
            op_sc, ext_sc = M[i - 1, j] - go, Ix[i - 1, j] - ge
            BX[i, j] = 1 if ext_sc > op_sc else 0
            Ix[i, j] = max(op_sc, ext_sc)
            op_sc, ext_sc = M[i, j - 1] - go, Iy[i, j - 1] - ge
            BY[i, j] = 1 if ext_sc > op_sc else 0
            Iy[i, j] = max(op_sc, ext_sc)
    mv, xv, yv = M[m, n], Ix[m, n], Iy[m, n]
    if mv >= xv and mv >= yv:
        mat = 0
    elif xv >= yv:
        mat = 1
    else:
        mat = 2
    score = int(max(mv, xv, yv))
    ops: list[int] = []
    i, j = m, n
    while i > 0 or j > 0:
        if i == 0:
            ops.append(OP_IY)
            j -= 1
            continue
        if j == 0:
            ops.append(OP_IX)
            i -= 1
            continue
        if mat == 0:
            ops.append(OP_DIAG)
            mat = int(DM[i, j])
            i -= 1
            j -= 1
        elif mat == 1:
            ops.append(OP_IX)
            mat = 1 if BX[i, j] else 0
            i -= 1
        else:
            ops.append(OP_IY)
            mat = 2 if BY[i, j] else 0
            j -= 1
    return score, np.array(ops[::-1], dtype=np.int8)


# ---------------------------------------------------------------------------
# host batch driver: encode, pad, dispatch, convert, oracle fallback
# ---------------------------------------------------------------------------
# the shared variable-length batching policy lives in
# parallel/bucketing.py; the re-aligner's 2-D shape grouping uses its
# group_by_shape (see realign_pairs)


def _pick_dlo(d_ends: np.ndarray, band: int) -> int:
    """Band placement covering diagonal 0 (the origin) and as many of
    the lanes' end diagonals ``t_len - q_len`` as possible: center the
    band on the hull [min(0, d_min), max(0, d_max)] when it fits,
    else default to centering on the main diagonal."""
    lo = min(0, int(d_ends.min()))
    hi = max(0, int(d_ends.max()))
    span = hi - lo + 1
    if span <= band:
        return lo - (band - span) // 2
    return -(band // 2)


# a full-matrix PYTHON traceback beyond this many cells would burn
# minutes of interpreter time — the native oracle below takes over far
# beyond it (bounded by its one pointer byte per cell)
_ORACLE_CELL_LIMIT = 4_000_000
_NATIVE_ORACLE_CELL_LIMIT = 256_000_000   # ~256 MB of pointer bytes
_MAX_BAND = 4096
# ceiling on the device pointer tensor (T_chunk x m_max x band uint8)
# per dispatch; lanes are chunked to stay under it, and a single lane
# whose m_max x band alone exceeds it skips the device path entirely
_PTR_BYTES_LIMIT = 1 << 30


def realign_pairs(pairs: list[tuple[bytes, bytes]], band: int = 64,
                  params: ScoreParams = ScoreParams(), mesh=None,
                  supervisor=None):
    """Re-align a batch of (query_segment, target) byte-string pairs.

    Returns a list of (score, ops_fwd) — or ``None`` for pairs that
    could not be re-aligned within resource bounds (callers keep their
    original gap structure).  Sequences are encoded upper-case.  Lanes
    are grouped by their 128-rounded (query, target) shape bucket
    before dispatch (SURVEY.md §7.3 variable-length batching): one
    50 kb target in a batch of 1.5 kb lanes pads only its own group's
    tensors ~30x, not every lane's, and the per-bucket jitted program
    is reused across flushes.  Lanes whose end diagonal the static band
    cannot cover retry on device with an escalated band (x4 per retry
    up to 4096); tiny leftovers use the host oracle.

    ``mesh``: a jax.sharding.Mesh (``pafreport --shard``) — lanes shard
    over every mesh axis, one fused-kernel launch per device shard.

    ``supervisor``: a resilience.BatchSupervisor — each device dispatch
    is retried/validated under its policy; on give-up the remaining
    lanes degrade to the host oracle (bit-exact tie-break contract)
    within its cell bounds instead of killing the run.
    """
    from pwasm_tpu.core.dna import encode

    if not pairs:
        return []
    enc = [(encode(qb.upper()), encode(tb.upper())) for qb, tb in pairs]
    out: list = [None] * len(pairs)
    from pwasm_tpu.parallel.bucketing import group_by_shape
    groups = group_by_shape(
        ((len(qc), len(tc)) for qc, tc in enc))
    for (mb, nb), idxs in sorted(groups.items()):
        _realign_group(enc, idxs, mb, nb, band, params, out, mesh,
                       supervisor)
    return out


def _realign_group(enc, idxs: list[int], m_max: int, n: int, band: int,
                   params: ScoreParams, out: list, mesh=None,
                   supervisor=None) -> None:
    """Dispatch one shape bucket of ``realign_pairs`` lanes (padded to
    (m_max, n)), writing results into ``out`` at their original
    indices."""
    T = len(idxs)
    qs = np.full((T, m_max), 127, dtype=np.int8)
    ts = np.full((T, n), 127, dtype=np.int8)
    q_lens = np.zeros(T, dtype=np.int32)
    t_lens = np.zeros(T, dtype=np.int32)
    for k, ki in enumerate(idxs):
        qc, tc = enc[ki]
        qs[k, :len(qc)] = qc
        ts[k, :len(tc)] = tc
        q_lens[k] = len(qc)
        t_lens[k] = len(tc)

    todo = np.arange(T)
    cur_band = max(1, band)
    first = True
    device_dead = False
    # always try the caller's own band, even above the escalation
    # ceiling; the ceiling bounds only the automatic retries
    while len(todo) and not device_dead \
            and (first or cur_band <= _MAX_BAND):
        first = False
        lane_bytes = m_max * cur_band
        if lane_bytes > _PTR_BYTES_LIMIT:
            break  # even one lane's pointer plane is too large
        chunk = max(1, _PTR_BYTES_LIMIT // lane_bytes)
        still = []
        for c0 in range(0, len(todo), chunk):
            sub = todo[c0:c0 + chunk]
            dlo = _pick_dlo(t_lens[sub] - q_lens[sub], cur_band)

            def dispatch(sub=sub, dlo=dlo, cur_band=cur_band):
                if mesh is not None:
                    res = sharded_realign_rows(
                        mesh, qs[sub], ts[sub], q_lens[sub],
                        t_lens[sub], band=cur_band, params=params,
                        dlo=dlo)
                else:
                    res = banded_realign_rows(
                        jnp.asarray(qs[sub]), jnp.asarray(ts[sub]),
                        jnp.asarray(q_lens[sub]),
                        jnp.asarray(t_lens[sub]),
                        band=cur_band, params=params, dlo=dlo)
                return tuple(np.asarray(x) for x in res)

            if supervisor is not None:
                from pwasm_tpu.resilience.guardrails import check_realign
                from pwasm_tpu.resilience.supervisor import \
                    DeviceWorkFailed
                try:
                    scores, leads, iy_runs, ops_rows, ok = \
                        supervisor.run(
                            "realign", dispatch,
                            validate=lambda r, sub=sub: check_realign(
                                *r, q_lens=q_lens[sub],
                                t_lens=t_lens[sub],
                                match_score=params.match))
                except DeviceWorkFailed as e:
                    # device given up on: every unresolved lane (this
                    # chunk and everything still queued) degrades to
                    # the bounded host oracle below — counted + warned
                    # like every other degradation
                    supervisor.note_degraded(
                        "realign",
                        f"degrading {len(todo) - c0} lane(s) to the "
                        f"host oracle ({e})")
                    still.extend(todo[c0:])
                    device_dead = True
                    break
            else:
                scores, leads, iy_runs, ops_rows, ok = dispatch()
            for idx, k in enumerate(sub):
                if ok[idx]:
                    out[idxs[k]] = (int(scores[idx]),
                                    rows_to_ops_fwd(int(leads[idx]),
                                                    iy_runs[idx],
                                                    ops_rows[idx],
                                                    int(q_lens[k])))
            still.extend(sub[~ok])
        todo = np.array(still, dtype=np.int64)
        cur_band = max(cur_band * 4, 4)
    for k in todo:
        # beyond the band ceiling: bounded host oracle or give up — the
        # native single-core Gotoh (same tie-breaks) reaches ~64x more
        # cells than the Python oracle before the give-up window opens
        cells = int(q_lens[k]) * int(t_lens[k])
        res = None
        if cells <= _NATIVE_ORACLE_CELL_LIMIT:
            from pwasm_tpu.native import gotoh_traceback
            res = gotoh_traceback(qs[k, :q_lens[k]], ts[k, :t_lens[k]],
                                  params.match, params.mismatch,
                                  params.gap_open, params.gap_extend)
        if res is None and cells <= _ORACLE_CELL_LIMIT:
            res = full_gotoh_traceback(qs[k, :q_lens[k]],
                                       ts[k, :t_lens[k]], params)
        if res is not None:
            out[idxs[k]] = res
