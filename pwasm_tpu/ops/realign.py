"""Banded affine-gap DP **re-alignment**: traceback to gap structures.

The scores-only kernels (``ops/banded_dp.py``) rank candidate targets;
this module turns the same banded Gotoh recurrence into a re-aligner
(SURVEY.md §0 north star: "batched banded affine-gap DP re-alignment ...
gated behind the class boundary"): for every (query segment, target)
pair it emits the optimal alignment *path* and converts it to the exact
gap-record conventions of the CIGAR walk (core/events.py:296-314,
reference pafreport.cpp:680-697), so a re-aligned MSA drops in where the
PAF's own gap structure was used.

Design (TPU-first):

- The forward pass is the shared banded wavefront recurrence with the
  band on the vector axis, vmapped over targets; each row additionally
  emits one packed pointer byte per band cell:
  bits 0-1 = diag argmax (0=M, 1=Ix, 2=Iy), bit 2 = Ix came from extend,
  bit 3 = Iy came from extend.  Pointers live in a (T, m, band) uint8
  tensor on device — O(m x band) per lane, not O(m x n).
- The traceback is a fixed-length ``lax.scan`` walk per lane (vmapped):
  each step reads one pointer byte (dynamic gather) and emits one op
  code, in reverse order.  No host round-trip per alignment; one batched
  fetch of the (T, S) op tensor per flush.
- Tie-breaks are DEFINED (M >= Ix >= Iy on maxima; gap-open wins ties
  against gap-extend) and replicated bit-for-bit by the numpy oracle
  ``full_gotoh_traceback`` so CPU/TPU gap structures are identical —
  the same bit-exactness contract as the consensus kernel.

Op codes (forward order): 1 = diagonal (consumes query+target),
2 = Ix (consumes query => gap in target, the CIGAR-walk 'I' case),
3 = Iy (consumes target => gap in query, the CIGAR-walk 'D' case).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from pwasm_tpu.core.events import GapData
from pwasm_tpu.ops.banded_dp import NEG, ScoreParams

OP_DIAG, OP_IX, OP_IY = 1, 2, 3


# ---------------------------------------------------------------------------
# forward pass with pointers (band coordinates, per lane)
# ---------------------------------------------------------------------------
def _forward_lane(q_seg, t, q_len, n: int, dlo, band: int,
                  params: ScoreParams):
    """Forward DP over one lane; rows past q_len are pass-throughs.
    Returns final wavefront (M, Ix, Iy) at row q_len and the (m_max,
    band) pointer tensor (row i stored at index i-1).  ``dlo`` is a
    traced int32 scalar, so band placement changes between flushes
    reuse the compiled program."""
    from pwasm_tpu.ops.banded_dp import initial_wavefront, make_row_step

    m_max = q_seg.shape[0]
    step = make_row_step(n, dlo, band, params, emit_ptrs=True)
    wf0 = initial_wavefront(n, dlo, band, params)

    def row(carry, xs):
        prev_m, prev_ix, prev_iy, i = carry
        qi, = xs
        i = i + 1
        m_new, ix_new, iy_new, ptr = step(prev_m, prev_ix, prev_iy, i,
                                          qi, t)
        keep = i <= q_len
        m_new = jnp.where(keep, m_new, prev_m)
        ix_new = jnp.where(keep, ix_new, prev_ix)
        iy_new = jnp.where(keep, iy_new, prev_iy)
        return (m_new, ix_new, iy_new, i), ptr

    (m_f, ix_f, iy_f, _), ptrs = jax.lax.scan(
        row, (*wf0, jnp.int32(0)), (q_seg.astype(jnp.int32),),
        length=m_max)
    return m_f, ix_f, iy_f, ptrs


# ---------------------------------------------------------------------------
# traceback walk (per lane)
# ---------------------------------------------------------------------------
def _traceback_lane(ptrs, q_len, t_len, m_f, ix_f, iy_f, n: int, dlo,
                    band: int, steps: int):
    """Walk the pointer tensor from cell (q_len, t_len) back to (0, 0),
    emitting one op per step in REVERSE order (0 = done/padding)."""
    m_max = ptrs.shape[0]
    b_end = t_len - q_len - dlo
    in_band = (b_end >= 0) & (b_end < band)
    b0 = jnp.clip(b_end, 0, band - 1)
    mv, xv, yv = m_f[b0], ix_f[b0], iy_f[b0]
    score = jnp.where(in_band, jnp.maximum(mv, jnp.maximum(xv, yv)), NEG)
    mat0 = jnp.where((mv >= xv) & (mv >= yv), 0,
                     jnp.where(xv >= yv, 1, 2)).astype(jnp.int32)

    def step(state, _):
        i, b, mat, done = state
        j = i + dlo + b
        done = done | ((i == 0) & (j == 0))
        # row 0 can only consume target (the init Iy chain has no stored
        # pointers): force Iy while j > 0
        mat = jnp.where((i == 0) & ~done, 2, mat)
        ptr = ptrs[jnp.clip(i - 1, 0, m_max - 1),
                   jnp.clip(b, 0, band - 1)].astype(jnp.int32)
        dm = ptr & 3
        bx = (ptr >> 2) & 1
        by = (ptr >> 3) & 1
        op = jnp.where(done, 0, mat + 1)
        ni = jnp.where(mat <= 1, i - 1, i)
        nb = jnp.where(mat == 0, b, jnp.where(mat == 1, b + 1, b - 1))
        nmat = jnp.where(mat == 0, dm,
                         jnp.where(mat == 1,
                                   jnp.where(bx == 1, 1, 0),
                                   jnp.where(by == 1, 2, 0)))
        nmat = jnp.where(i == 0, 2, nmat)  # stay on the row-0 Iy chain
        ni = jnp.where(done, i, ni)
        nb = jnp.where(done, b, nb)
        nmat = jnp.where(done, mat, nmat)
        return (ni, nb, nmat, done), op.astype(jnp.int8)

    init = (q_len.astype(jnp.int32), b0.astype(jnp.int32), mat0,
            ~in_band)  # out-of-band lanes never walk
    (fi, fb, _, fdone), ops_bwd = jax.lax.scan(step, init, None,
                                               length=steps)
    fj = fi + dlo + fb
    ok = in_band & (score > NEG // 2) & (fi == 0) & (fj == 0)
    return score.astype(jnp.int32), ops_bwd, ok


@functools.partial(jax.jit, static_argnames=("band", "params"))
def _traceback_batch_jit(qs, ts, q_lens, t_lens, dlo, band, params):
    m_max = qs.shape[1]
    n = ts.shape[1]
    steps = m_max + n

    def lane(q_seg, t, q_len, t_len):
        m_f, ix_f, iy_f, ptrs = _forward_lane(q_seg, t, q_len, n, dlo,
                                              band, params)
        return _traceback_lane(ptrs, q_len, t_len, m_f, ix_f, iy_f, n,
                               dlo, band, steps)

    return jax.vmap(lane)(qs, ts, q_lens.astype(jnp.int32),
                          t_lens.astype(jnp.int32))


def banded_traceback_batch(qs: jax.Array, ts: jax.Array,
                           q_lens: jax.Array, t_lens: jax.Array,
                           band: int = 64,
                           params: ScoreParams = ScoreParams(),
                           dlo: int | None = None):
    """Batched banded re-alignment with traceback.

    qs: (T, m_max) int8 per-lane query segments (codes, pad 127)
    ts: (T, n) int8 per-lane targets (codes, pad 127)
    q_lens / t_lens: (T,) true lengths
    dlo: band placement (diagonals covered are [dlo, dlo+band));
    default centers the band on the main diagonal.  ``dlo`` is traced,
    not static — re-placing the band between flushes reuses the
    compiled program.

    Returns ``(scores, ops_bwd, ok)``:
    scores (T,) int32 global scores at (q_len, t_len);
    ops_bwd (T, m_max + n) int8 alignment ops in reverse order, 0-padded;
    ok (T,) bool — band covered the end cell and the walk closed at the
    origin.  Lanes with ``ok=False`` need a wider band (see
    ``realign_pairs`` escalation) or the host oracle.
    """
    if band < 1:
        raise ValueError(f"band must be >= 1, got {band}")
    if dlo is None:
        dlo = -(band // 2)
    return _traceback_batch_jit(qs, ts, q_lens, t_lens,
                                jnp.int32(dlo), band, params)


# ---------------------------------------------------------------------------
# host-side conversion: op runs -> GapData lists (CIGAR-walk conventions)
# ---------------------------------------------------------------------------
def ops_forward(ops_bwd_row: np.ndarray) -> np.ndarray:
    """Reverse the non-zero prefix of one traceback row into forward
    alignment order."""
    k = int((ops_bwd_row != 0).sum())
    return ops_bwd_row[:k][::-1]


def ops_to_gaps(ops_fwd: np.ndarray, offset: int, r_len: int,
                eff_t_len: int, reverse: int
                ) -> tuple[list[GapData], list[GapData]]:
    """Convert a forward op string to (rgaps, tgaps) with the exact
    conventions of the CIGAR walk (core/events.py:296-314; reference
    pafreport.cpp:680-697): Ix runs are target gaps at the current
    target position (strand-flipped when reverse), Iy runs are query
    gaps at offset+qpos (strand-flipped when reverse)."""
    rgaps: list[GapData] = []
    tgaps: list[GapData] = []
    qpos = tpos = 0
    i = 0
    L = len(ops_fwd)
    while i < L:
        op = ops_fwd[i]
        j = i
        while j < L and ops_fwd[j] == op:
            j += 1
        run = j - i
        if op == OP_DIAG:
            qpos += run
            tpos += run
        elif op == OP_IX:   # gap in the target sequence
            tgaps.append(GapData(eff_t_len - tpos if reverse else tpos,
                                 run))
            qpos += run
        elif op == OP_IY:   # gap in the query
            pos = offset + qpos
            if reverse:
                pos = r_len - pos
            rgaps.append(GapData(pos, run))
            tpos += run
        i = j
    return rgaps, tgaps


def ops_consumed(ops_fwd: np.ndarray) -> tuple[int, int]:
    """(query bases, target bases) consumed by a forward op string."""
    q = int(((ops_fwd == OP_DIAG) | (ops_fwd == OP_IX)).sum())
    t = int(((ops_fwd == OP_DIAG) | (ops_fwd == OP_IY)).sum())
    return q, t


def ops_score(ops_fwd: np.ndarray, q: np.ndarray, t: np.ndarray,
              params: ScoreParams = ScoreParams()) -> int:
    """Score a forward op string (independent check that the traceback
    path actually achieves the DP score)."""
    s = 0
    qpos = tpos = 0
    prev = 0
    for op in ops_fwd:
        if op == OP_DIAG:
            match = q[qpos] == t[tpos] and q[qpos] < 4
            s += params.match if match else -params.mismatch
            qpos += 1
            tpos += 1
        elif op == OP_IX:
            s -= params.go if prev != OP_IX else params.gap_extend
            qpos += 1
        elif op == OP_IY:
            s -= params.go if prev != OP_IY else params.gap_extend
            tpos += 1
        prev = op
    return s


# ---------------------------------------------------------------------------
# numpy oracle: full-matrix Gotoh traceback with the same tie-breaks
# ---------------------------------------------------------------------------
def full_gotoh_traceback(q: np.ndarray, t: np.ndarray,
                         params: ScoreParams = ScoreParams()
                         ) -> tuple[int, np.ndarray]:
    """Unbanded Gotoh with traceback — the independent host oracle.
    Tie-breaks match the device kernel by definition: diag argmax prefers
    M, then Ix, then Iy; gap recurrences prefer open on ties.  Returns
    (score, forward op array)."""
    m, n = len(q), len(t)
    ge, go = params.gap_extend, params.go
    M = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Ix = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Iy = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    DM = np.zeros((m + 1, n + 1), dtype=np.int8)   # diag argmax
    BX = np.zeros((m + 1, n + 1), dtype=np.int8)   # Ix from extend
    BY = np.zeros((m + 1, n + 1), dtype=np.int8)   # Iy from extend
    M[0, 0] = 0
    for j in range(1, n + 1):
        Iy[0, j] = -(go + (j - 1) * ge)
        BY[0, j] = 1 if j > 1 else 0
    for i in range(1, m + 1):
        Ix[i, 0] = -(go + (i - 1) * ge)
        BX[i, 0] = 1 if i > 1 else 0
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            s = params.match if (q[i - 1] == t[j - 1] and q[i - 1] < 4) \
                else -params.mismatch
            a, b, c = M[i - 1, j - 1], Ix[i - 1, j - 1], Iy[i - 1, j - 1]
            if a >= b and a >= c:
                DM[i, j] = 0
                M[i, j] = a + s
            elif b >= c:
                DM[i, j] = 1
                M[i, j] = b + s
            else:
                DM[i, j] = 2
                M[i, j] = c + s
            op_sc, ext_sc = M[i - 1, j] - go, Ix[i - 1, j] - ge
            BX[i, j] = 1 if ext_sc > op_sc else 0
            Ix[i, j] = max(op_sc, ext_sc)
            op_sc, ext_sc = M[i, j - 1] - go, Iy[i, j - 1] - ge
            BY[i, j] = 1 if ext_sc > op_sc else 0
            Iy[i, j] = max(op_sc, ext_sc)
    mv, xv, yv = M[m, n], Ix[m, n], Iy[m, n]
    if mv >= xv and mv >= yv:
        mat = 0
    elif xv >= yv:
        mat = 1
    else:
        mat = 2
    score = int(max(mv, xv, yv))
    ops: list[int] = []
    i, j = m, n
    while i > 0 or j > 0:
        if i == 0:
            ops.append(OP_IY)
            j -= 1
            continue
        if j == 0:
            ops.append(OP_IX)
            i -= 1
            continue
        if mat == 0:
            ops.append(OP_DIAG)
            mat = int(DM[i, j])
            i -= 1
            j -= 1
        elif mat == 1:
            ops.append(OP_IX)
            mat = 1 if BX[i, j] else 0
            i -= 1
        else:
            ops.append(OP_IY)
            mat = 2 if BY[i, j] else 0
            j -= 1
    return score, np.array(ops[::-1], dtype=np.int8)


# ---------------------------------------------------------------------------
# host batch driver: encode, pad, dispatch, convert, oracle fallback
# ---------------------------------------------------------------------------
def _bucket(x: int, step: int = 128) -> int:
    return max(step, (x + step - 1) // step * step)


def _pick_dlo(d_ends: np.ndarray, band: int) -> int:
    """Band placement covering diagonal 0 (the origin) and as many of
    the lanes' end diagonals ``t_len - q_len`` as possible: center the
    band on the hull [min(0, d_min), max(0, d_max)] when it fits,
    else default to centering on the main diagonal."""
    lo = min(0, int(d_ends.min()))
    hi = max(0, int(d_ends.max()))
    span = hi - lo + 1
    if span <= band:
        return lo - (band - span) // 2
    return -(band // 2)


# a full-matrix host traceback beyond this many cells would burn minutes
# of Python time / gigabytes of int64 — escalate the device band instead
_ORACLE_CELL_LIMIT = 4_000_000
_MAX_BAND = 4096
# ceiling on the device pointer tensor (T_chunk x m_max x band uint8)
# per dispatch; lanes are chunked to stay under it, and a single lane
# whose m_max x band alone exceeds it skips the device path entirely
_PTR_BYTES_LIMIT = 1 << 30


def realign_pairs(pairs: list[tuple[bytes, bytes]], band: int = 64,
                  params: ScoreParams = ScoreParams()):
    """Re-align a batch of (query_segment, target) byte-string pairs.

    Returns a list of (score, ops_fwd) — or ``None`` for pairs that
    could not be re-aligned within resource bounds (callers keep their
    original gap structure).  Sequences are encoded upper-case; shapes
    are bucketed to multiples of 128 so the jitted program is reused
    across flushes.  Lanes whose end diagonal the static band cannot
    cover retry on device with an escalated band (x4 per retry up to
    4096); tiny leftovers use the host oracle.
    """
    from pwasm_tpu.core.dna import encode

    if not pairs:
        return []
    T = len(pairs)
    m_max = _bucket(max(len(p[0]) for p in pairs))
    n = _bucket(max(len(p[1]) for p in pairs))
    qs = np.full((T, m_max), 127, dtype=np.int8)
    ts = np.full((T, n), 127, dtype=np.int8)
    q_lens = np.zeros(T, dtype=np.int32)
    t_lens = np.zeros(T, dtype=np.int32)
    for k, (qb, tb) in enumerate(pairs):
        qc = encode(qb.upper())
        tc = encode(tb.upper())
        qs[k, :len(qc)] = qc
        ts[k, :len(tc)] = tc
        q_lens[k] = len(qc)
        t_lens[k] = len(tc)

    out: list = [None] * T
    todo = np.arange(T)
    cur_band = max(1, band)
    first = True
    # always try the caller's own band, even above the escalation
    # ceiling; the ceiling bounds only the automatic retries
    while len(todo) and (first or cur_band <= _MAX_BAND):
        first = False
        lane_bytes = m_max * cur_band
        if lane_bytes > _PTR_BYTES_LIMIT:
            break  # even one lane's pointer plane is too large
        chunk = max(1, _PTR_BYTES_LIMIT // lane_bytes)
        still = []
        for c0 in range(0, len(todo), chunk):
            sub = todo[c0:c0 + chunk]
            dlo = _pick_dlo(t_lens[sub] - q_lens[sub], cur_band)
            scores, ops_bwd, ok = banded_traceback_batch(
                jnp.asarray(qs[sub]), jnp.asarray(ts[sub]),
                jnp.asarray(q_lens[sub]), jnp.asarray(t_lens[sub]),
                band=cur_band, params=params, dlo=dlo)
            scores = np.asarray(scores)
            ops_bwd = np.asarray(ops_bwd)
            ok = np.asarray(ok)
            for idx, k in enumerate(sub):
                if ok[idx]:
                    out[k] = (int(scores[idx]),
                              ops_forward(ops_bwd[idx]))
            still.extend(sub[~ok])
        todo = np.array(still, dtype=np.int64)
        cur_band = max(cur_band * 4, 4)
    for k in todo:
        # beyond the band ceiling: bounded host oracle or give up
        if int(q_lens[k]) * int(t_lens[k]) <= _ORACLE_CELL_LIMIT:
            out[k] = full_gotoh_traceback(qs[k, :q_lens[k]],
                                          ts[k, :t_lens[k]], params)
    return out
