"""Jax-free host consensus helpers.

The plain-CPU CLI must never import jax (a pinned-but-unhealthy TPU
tunnel would hang an otherwise host-only run, and the cold jax import
alone costs ~1.2 s — the dominant term in the Python-CLI-vs-native
bench ratio before it moved here).  The pure-numpy twins of the device
consensus ops live in this module so the host report/MSA/consensus
paths can reach them without touching ``ops/consensus.py``'s jax
imports; the device module re-exports them for compatibility.
"""

from __future__ import annotations

import numpy as np

N_CLASSES = 6
CODE_ZERO_COV = -1
PAD_CODE = 6  # any code >= 6 contributes nothing to the pileup


def host_class_counts(pile: np.ndarray) -> np.ndarray:
    """Pure-numpy per-column class counts over a (depth, cols) int8
    code pileup — the host twin of ``pileup_counts`` (codes outside
    [0, 6) contribute nothing).  Returns (cols, 6) int32.  This is the
    single degradation path the resilience layer falls back to when a
    device consensus launch is given up on (align/msa.py and cli.py
    both route here so the two fallbacks cannot drift)."""
    return np.stack([(pile == k).sum(0, dtype=np.int32)
                     for k in range(N_CLASSES)], axis=1)
