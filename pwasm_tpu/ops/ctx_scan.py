"""Vectorized variant-context scan — north-star kernel #3 (SURVEY.md §7.1).

Device-side batch analysis of diff events against the forward query:

- 9bp reference windows with the reference's edge-clamp semantics,
  including the wrong-sign right-edge quirk (pafreport.cpp:721-733, see
  ``pwasm_tpu.report.diff_report.get_ref_context``);
- homopolymer attribution (4-run overlap rule, pafreport.cpp:735-748);
- methylation-motif scan (first motif in table order wins,
  pafreport.cpp:751-763);
- codon-impact: per-codon before/after amino acids for substitutions, and
  the frameshift/premature-stop scan over the modified suffix for indels
  (pafreport.cpp:801-883), all through the 5^3 amino-acid LUT.

Everything is a fixed-shape gather/compare over an (E, ...) event batch —
one fused XLA program, no per-event host loops.  The host keeps only the
final string assembly (``pwasm_tpu.report.columnar``), which is tested
byte-identical to the scalar path.

The FORMULAS live in ``ops/ctx_scan_impl.py`` (jax-free, namespace-
parameterized) and are shared verbatim with the vectorized numpy host
path — host/device parity is structural, not maintained by hand.  This
module binds them to ``jax.numpy``, jits the fused program, and adds
the dispatch-lean transfer forms:

- ``ctx_scan_packed`` concatenates every output field into ONE int32
  (E, total_width) tensor inside the program, so a flush costs a single
  device->host fetch instead of ~16 per-field round-trips (~1-2 ms each
  through a tunnel — the realistic-scale dispatch budget, VERDICT r5);
- ``pack_events``/``ref_bucket_len`` pad the event axis and the
  reference tensor to power-of-two buckets, so the jitted program is
  served by a small fixed set of compiled shapes across flushes and
  ref lengths instead of recompiling per size.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from pwasm_tpu.ops import ctx_scan_impl as _impl
from pwasm_tpu.ops.ctx_scan_impl import (CTX, EVT_D, EVT_I, EVT_S,  # noqa: F401
                                         MAX_MOTIF, PAD, ctx_scan_layout,
                                         next_pow2, ref_bucket_len,
                                         unpack_ctx_scan)


def _translate(c0, c1, c2):
    """Codes -> amino-acid ASCII (device namespace binding)."""
    return _impl.translate_codes(c0, c1, c2, xp=jnp)


def pack_events(events, max_ev: int = 16, bucket: int = 256) -> dict:
    """SoA-pack a list of DiffEvent into device tensors (see
    ``ctx_scan_impl.pack_events_np`` for the power-of-two event-axis
    bucketing that keeps the jitted program's shape set small).  The
    int32 vectors ship as ONE (4, E) tensor and the int8 code planes as
    ONE (2, E, max_ev) tensor — two host->device transfers per flush
    instead of six."""
    d = _impl.pack_events_np(events, max_ev, bucket)
    import numpy as np

    ints = jnp.asarray(np.stack([d["rloc"], d["evt"], d["evtlen"],
                                 d["nbases"]]))
    codes = jnp.asarray(np.stack([d["evtbases"], d["evtsub"]]))
    return dict(rloc=ints[0], evt=ints[1], evtlen=ints[2],
                nbases=ints[3], evtbases=codes[0], evtsub=codes[1])


def pack_motifs(motifs) -> tuple[jax.Array, jax.Array]:
    """Motif table -> (codes (NM, MAX_MOTIF) int8, lens (NM,) int32)."""
    codes, lens = _impl.pack_motifs_np(motifs)
    return jnp.asarray(codes), jnp.asarray(lens)


def ref_context_windows(ref: jax.Array, ref_len, rloc: jax.Array):
    """(E,) event positions -> (E, 9) windows + (E,) local offsets,
    mirroring get_ref_context exactly (including the right-edge quirk)."""
    return _impl.ref_context_windows(ref, ref_len, rloc, xp=jnp)


def hpoly_flags(evtbases: jax.Array, nbases: jax.Array, rctx: jax.Array,
                rctxloc: jax.Array) -> jax.Array:
    """Vectorized hpolyCheck (see ctx_scan_impl)."""
    return _impl.hpoly_flags(evtbases, nbases, rctx, rctxloc, xp=jnp)


def motif_hits(rctx: jax.Array, mot_codes: jax.Array,
               mot_lens: jax.Array) -> jax.Array:
    """First motif (table order) found anywhere in each window."""
    return _impl.motif_hits(rctx, mot_codes, mot_lens, xp=jnp)


def sub_impact(ref: jax.Array, rloc, nbases, evtbases, evtsub,
               r_trloc, max_codons: int):
    """Substitution codon impact (see ctx_scan_impl)."""
    return _impl.sub_impact(ref, rloc, nbases, evtbases, evtsub,
                            r_trloc, max_codons, xp=jnp)


def indel_stop_scan(ref: jax.Array, ref_len, rloc, evt, evtlen, nbases,
                    evtbases, r_trloc, max_len: int):
    """Frameshift analysis for I/D events (see ctx_scan_impl)."""
    return _impl.indel_stop_scan(ref, ref_len, rloc, evt, evtlen,
                                 nbases, evtbases, r_trloc, max_len,
                                 xp=jnp)


@functools.partial(jax.jit,
                   static_argnames=("max_codons", "max_len", "skip_codan"))
def ctx_scan(ref: jax.Array, ref_len, ev: dict, mot_codes, mot_lens,
             max_codons: int = 8, max_len: int = 4096,
             skip_codan: bool = False) -> dict:
    """The fused event-analysis program.  Returns a dict of device arrays;
    ``pwasm_tpu.report.device_report`` turns them into report rows."""
    return _impl.ctx_scan_calc(ref, ref_len, ev, mot_codes, mot_lens,
                               max_codons=max_codons, max_len=max_len,
                               skip_codan=skip_codan, xp=jnp)


@functools.partial(jax.jit,
                   static_argnames=("max_codons", "max_len", "skip_codan"))
def ctx_scan_packed(ref: jax.Array, ref_len, ev: dict, mot_codes,
                    mot_lens, max_codons: int = 8, max_len: int = 4096,
                    skip_codan: bool = False) -> jax.Array:
    """``ctx_scan`` with every output field cast to int32 and
    concatenated into ONE (E, total_width) tensor in the fixed
    ``ctx_scan_layout`` order — the whole analysis crosses the host
    link in a single fetch (``unpack_ctx_scan`` splits it back into
    the dict form, as numpy views)."""
    out = _impl.ctx_scan_calc(ref, ref_len, ev, mot_codes, mot_lens,
                              max_codons=max_codons, max_len=max_len,
                              skip_codan=skip_codan, xp=jnp)
    E = ev["rloc"].shape[0]
    parts = []
    for name, width in ctx_scan_layout(max_codons, skip_codan):
        parts.append(out[name].astype(jnp.int32).reshape(E, width))
    return jnp.concatenate(parts, axis=1)
