"""Vectorized variant-context scan — north-star kernel #3 (SURVEY.md §7.1).

Device-side batch analysis of diff events against the forward query:

- 9bp reference windows with the reference's edge-clamp semantics,
  including the wrong-sign right-edge quirk (pafreport.cpp:721-733, see
  ``pwasm_tpu.report.diff_report.get_ref_context``);
- homopolymer attribution (4-run overlap rule, pafreport.cpp:735-748);
- methylation-motif scan (first motif in table order wins,
  pafreport.cpp:751-763);
- codon-impact: per-codon before/after amino acids for substitutions, and
  the frameshift/premature-stop scan over the modified suffix for indels
  (pafreport.cpp:801-883), all through the 5^3 amino-acid LUT.

Everything is a fixed-shape gather/compare over an (E, ...) event batch —
one fused XLA program, no per-event host loops.  The host keeps only the
final string assembly (``pwasm_tpu.report.device_report``), which is
tested byte-identical to the scalar path.

Event tensor layout (produced by ``pack_events``):
  rloc (E,) int32; evt (E,) int32 {0=S, 1=I, 2=D}; evtlen (E,) int32
  (the reference's evtlen field — stays 1 for merged substitutions);
  nbases (E,) actual evtbases length; evtbases/evtsub (E, MAXEV) int8
  codes padded with PAD.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from pwasm_tpu.core.dna import AA_LUT, CODE_N, encode

PAD = 6
EVT_S, EVT_I, EVT_D = 0, 1, 2
CTX = 9          # reference-context window size
MAX_MOTIF = 8    # max motif length supported by the device scan

def _translate(c0, c1, c2):
    """Codes (clipped to N) -> amino-acid ASCII via the 5^3 LUT; any code
    outside [0,4) translates through N -> 'X'.

    The LUT is materialized here, not at module level: a module-level
    ``jnp.asarray`` would initialize the jax backend at import time, which
    must never happen on host-only code paths (an unhealthy TPU tunnel
    would hang a plain-CPU CLI run).  Under jit it constant-folds; it may
    not be cached across calls (a first call inside a trace would cache a
    tracer)."""
    lut = jnp.asarray(AA_LUT)
    c0 = jnp.clip(c0, 0, CODE_N)
    c1 = jnp.clip(c1, 0, CODE_N)
    c2 = jnp.clip(c2, 0, CODE_N)
    return lut[(c0 * 25 + c1 * 5 + c2).astype(jnp.int32)]


def pack_events(events, max_ev: int = 16, bucket: int = 256) -> dict:
    """SoA-pack a list of DiffEvent into device tensors.  Events whose
    bases exceed ``max_ev`` must take the host path (caller filters).

    The event axis is padded up to a multiple of ``bucket`` so the jitted
    ctx_scan program is reused across flushes instead of recompiling for
    every distinct event count; padding rows are zeros (a 0-length 'S'
    event at rloc 0) and callers read only the first ``len(events)``
    results."""
    E = len(events)
    E_pad = max(bucket, (E + bucket - 1) // bucket * bucket) if bucket \
        else E
    rloc = np.zeros(E_pad, np.int32)
    evt = np.zeros(E_pad, np.int32)
    evtlen = np.zeros(E_pad, np.int32)
    nbases = np.zeros(E_pad, np.int32)
    evtbases = np.full((E_pad, max_ev), PAD, np.int8)
    evtsub = np.full((E_pad, max_ev), PAD, np.int8)
    for k, ev in enumerate(events):
        rloc[k] = ev.rloc
        evt[k] = {"S": EVT_S, "I": EVT_I, "D": EVT_D}[ev.evt]
        evtlen[k] = ev.evtlen
        b = encode(ev.evtbases.upper())
        nbases[k] = len(b)
        evtbases[k, :len(b)] = b[:max_ev]
        s = encode(ev.evtsub.upper())
        evtsub[k, :len(s)] = s[:max_ev]
    return dict(rloc=jnp.asarray(rloc), evt=jnp.asarray(evt),
                evtlen=jnp.asarray(evtlen), nbases=jnp.asarray(nbases),
                evtbases=jnp.asarray(evtbases),
                evtsub=jnp.asarray(evtsub))


def pack_motifs(motifs) -> tuple[jax.Array, jax.Array]:
    """Motif table -> (codes (NM, MAX_MOTIF) int8, lens (NM,) int32)."""
    nm = len(motifs)
    codes = np.full((nm, MAX_MOTIF), PAD, np.int8)
    lens = np.zeros(nm, np.int32)
    for i, mot in enumerate(motifs):
        b = encode(mot.encode() if isinstance(mot, str) else mot)
        if len(b) > MAX_MOTIF:
            raise ValueError(f"motif longer than {MAX_MOTIF}: {mot}")
        codes[i, :len(b)] = b
        lens[i] = len(b)
    return jnp.asarray(codes), jnp.asarray(lens)


def ref_context_windows(ref: jax.Array, ref_len, rloc: jax.Array):
    """(E,) event positions -> (E, 9) windows + (E,) local offsets,
    mirroring get_ref_context exactly (including the right-edge quirk)."""
    ctxstart = rloc - 4
    evtloc = jnp.full_like(rloc, 4)
    left = ctxstart < 0
    right = ~left & (ctxstart + 8 >= ref_len)
    evtloc = jnp.where(left, evtloc + ctxstart, evtloc)
    # the right-edge branch uses the OLD ctxstart in its (sign-flipped)
    # adjustment — reference behavior preserved
    evtloc = jnp.where(right, evtloc + ref_len - ctxstart - 9, evtloc)
    ctxstart = jnp.where(left, 0, ctxstart)
    ctxstart = jnp.where(right, ref_len - 9, ctxstart)
    degen = right & (ctxstart < 0)
    evtloc = jnp.where(degen, evtloc + ctxstart, evtloc)
    ctxstart = jnp.where(degen, 0, ctxstart)
    idx = ctxstart[:, None] + jnp.arange(CTX)[None, :]
    win = ref[jnp.clip(idx, 0, ref.shape[0] - 1)]
    return win, evtloc


def hpoly_flags(evtbases: jax.Array, nbases: jax.Array, rctx: jax.Array,
                rctxloc: jax.Array) -> jax.Array:
    """Vectorized hpolyCheck: all event bases identical AND a 4-run of the
    base inside the window overlapping the event offset."""
    first = evtbases[:, 0]
    kidx = jnp.arange(evtbases.shape[1])[None, :]
    valid = kidx < nbases[:, None]
    all_same = jnp.all((evtbases == first[:, None]) | ~valid, axis=1)
    # seed positions l in [0, 6): window[l:l+4] all == first
    l = jnp.arange(CTX - 4 + 1)
    runs = jnp.all(
        rctx[:, l[:, None] + jnp.arange(4)[None, :]]
        == first[:, None, None], axis=2)           # (E, 6)
    # reference uses GStr::index -> FIRST run position only
    has_run = jnp.any(runs, axis=1)
    lpos = jnp.argmax(runs, axis=1)
    overlap = (lpos <= rctxloc) & (rctxloc <= lpos + 4)
    return all_same & has_run & overlap & (nbases > 0)


def motif_hits(rctx: jax.Array, mot_codes: jax.Array,
               mot_lens: jax.Array) -> jax.Array:
    """First motif (table order) found anywhere in each window; returns
    (E,) int32 1-based motif index, 0 = none."""
    E = rctx.shape[0]
    nm, mw = mot_codes.shape
    starts = jnp.arange(CTX)                       # candidate start pos
    ks = jnp.arange(mw)
    idx = starts[:, None] + ks[None, :]            # (9, mw)
    win = rctx[:, jnp.clip(idx, 0, CTX - 1)]       # (E, 9, mw)
    cmp = win[:, None] == mot_codes[None, :, None]  # (E, nm, 9, mw)
    klt = ks[None, :] < mot_lens[:, None]           # (nm, mw)
    ok = jnp.all(cmp | ~klt[None, :, None, :], axis=3)  # (E, nm, 9)
    fits = (starts[None, :] + mot_lens[:, None]) <= CTX  # (nm, 9)
    found = jnp.any(ok & fits[None], axis=2)       # (E, nm)
    any_hit = jnp.any(found, axis=1)
    first = jnp.argmax(found, axis=1)
    return jnp.where(any_hit, first + 1, 0).astype(jnp.int32)


def sub_impact(ref: jax.Array, rloc, nbases, evtbases, evtsub,
               r_trloc, max_codons: int):
    """Substitution codon impact: for up to ``max_codons`` affected codons
    return (orig_aa, new_aa, aapos, valid, sub_mismatch)."""
    e_off = rloc - r_trloc                  # event offset in the window
    ao_first = e_off // 3
    ao_last = (e_off + jnp.maximum(nbases, 1) - 1) // 3
    d = jnp.arange(max_codons)[None, :]
    ao = ao_first[:, None] + d              # (E, K) codon window indices
    kvalid = ao <= ao_last[:, None]
    cpos = r_trloc[:, None, None] + ao[..., None] * 3 \
        + jnp.arange(3)[None, None, :]      # (E, K, 3) absolute positions
    Rn = ref.shape[0]
    orig = ref[jnp.clip(cpos, 0, Rn - 1)]
    orig = jnp.where(cpos < Rn, orig, PAD)
    # overlay the substituted bases at [rloc, rloc+nbases)
    rel = cpos - rloc[:, None, None]
    inside = (rel >= 0) & (rel < nbases[:, None, None])
    sub = evtbases[jnp.arange(evtbases.shape[0])[:, None, None],
                   jnp.clip(rel, 0, evtbases.shape[1] - 1)]
    mod = jnp.where(inside, sub, orig)
    orig_aa = _translate(orig[..., 0], orig[..., 1], orig[..., 2])
    new_aa = _translate(mod[..., 0], mod[..., 1], mod[..., 2])
    aapos = ao + (rloc // 3)[:, None]
    # the reference verifies each substituted base against the query
    # (pafreport.cpp:812-813); surface that as a flag the host turns fatal
    kb = jnp.arange(evtbases.shape[1])[None, :]
    bvalid = kb < nbases[:, None]
    refb = ref[jnp.clip(rloc[:, None] + kb, 0, Rn - 1)]
    mism = jnp.any((refb != evtsub) & bvalid, axis=1)
    return orig_aa, new_aa, aapos, kvalid, mism


def indel_stop_scan(ref: jax.Array, ref_len, rloc, evt, evtlen, nbases,
                    evtbases, r_trloc, max_len: int):
    """Frameshift analysis for I/D events: build the modified suffix
    (insert/cut at the event), translate codon-by-codon, find the first
    premature stop, and collect the reference's aa4/maa4 preview codons.

    Returns (stop_aapos (E,) int32 or -1, aa4 (E,4) uint8, maa4 (E,4)
    uint8, aa4_valid, maa4_valid)."""
    E = rloc.shape[0]
    Rn = ref.shape[0]
    e_off = rloc - r_trloc
    is_ins = evt == EVT_I
    nb = jnp.where(is_ins, nbases, evtlen)
    j = jnp.arange(max_len)[None, :]        # (1, W) window positions
    # source index for each modified-sequence position
    ins_src = jnp.where(j < e_off[:, None], r_trloc[:, None] + j,
                        r_trloc[:, None] + j - nb[:, None])
    ins_inside = (j >= e_off[:, None]) & (j < (e_off + nb)[:, None])
    del_src = jnp.where(j < e_off[:, None], r_trloc[:, None] + j,
                        r_trloc[:, None] + j + nb[:, None])
    src = jnp.where(is_ins[:, None], ins_src, del_src)
    base = ref[jnp.clip(src, 0, Rn - 1)]
    base = jnp.where(src < ref_len, base, PAD)
    insb = evtbases[jnp.arange(E)[:, None],
                    jnp.clip(j - e_off[:, None], 0,
                             evtbases.shape[1] - 1)]
    seq = jnp.where(is_ins[:, None] & ins_inside, insb, base)
    modlen = jnp.where(is_ins, ref_len - r_trloc + nb,
                       ref_len - r_trloc - nb)
    n_cod = max_len // 3
    cpos = jnp.arange(n_cod)[None, :] * 3
    c0 = jnp.take_along_axis(seq, cpos, axis=1)
    c1 = jnp.take_along_axis(seq, cpos + 1, axis=1)
    c2 = jnp.take_along_axis(seq, cpos + 2, axis=1)
    aa = _translate(c0, c1, c2)             # (E, n_cod)
    cvalid = (cpos + 2) < modlen[:, None]   # while i+2 < len(modseq)
    stop = (aa == ord(".")) & cvalid
    has_stop = jnp.any(stop, axis=1)
    cstar = jnp.argmax(stop, axis=1)
    stop_aapos = jnp.where(has_stop, 1 + cstar + r_trloc // 3, -1)
    # aa4/maa4: codons c = 1..4, before the stop, valid in each sequence
    c14 = jnp.arange(1, 5)[None, :]
    before_stop = jnp.where(has_stop[:, None], c14 < cstar[:, None], True)
    maa4_valid = before_stop & jnp.take_along_axis(
        cvalid, c14, axis=1)
    maa4 = jnp.take_along_axis(aa, c14, axis=1)
    # aa4 comes from the unmodified suffix (same positions)
    opos = r_trloc[:, None] + c14 * 3
    o0 = ref[jnp.clip(opos, 0, Rn - 1)]
    o1 = ref[jnp.clip(opos + 1, 0, Rn - 1)]
    o2 = ref[jnp.clip(opos + 2, 0, Rn - 1)]
    o0 = jnp.where(opos < ref_len, o0, PAD)
    o1 = jnp.where(opos + 1 < ref_len, o1, PAD)
    o2 = jnp.where(opos + 2 < ref_len, o2, PAD)
    aa4 = _translate(o0, o1, o2)
    # reference guard: i+2 < len(r_trseq)  <=>  opos+2 < ref_len
    aa4_valid = maa4_valid & ((opos + 2) < ref_len)
    return stop_aapos.astype(jnp.int32), aa4, maa4, aa4_valid, maa4_valid


@functools.partial(jax.jit,
                   static_argnames=("max_codons", "max_len", "skip_codan"))
def ctx_scan(ref: jax.Array, ref_len, ev: dict, mot_codes, mot_lens,
             max_codons: int = 8, max_len: int = 4096,
             skip_codan: bool = False) -> dict:
    """The fused event-analysis program.  Returns a dict of device arrays;
    ``pwasm_tpu.report.device_report`` turns them into report rows."""
    rloc = ev["rloc"]
    rctx, rctxloc = ref_context_windows(ref, ref_len, rloc)
    hpoly = hpoly_flags(ev["evtbases"], ev["nbases"], rctx, rctxloc)
    motif = motif_hits(rctx, mot_codes, mot_lens)
    aapos0 = rloc // 3
    ca = aapos0 * 3
    aa = _translate(ref[jnp.clip(ca, 0, ref.shape[0] - 1)],
                    jnp.where(ca + 1 < ref_len,
                              ref[jnp.clip(ca + 1, 0, ref.shape[0] - 1)],
                              PAD),
                    jnp.where(ca + 2 < ref_len,
                              ref[jnp.clip(ca + 2, 0, ref.shape[0] - 1)],
                              PAD))
    out = dict(rctx=rctx, rctxloc=rctxloc, hpoly=hpoly, motif=motif,
               aa=aa, aapos=aapos0 + 1)
    if not skip_codan:
        r_trloc = jnp.maximum(3 * (aapos0 + 1 - 2), 0)
        s_orig, s_new, s_pos, s_valid, s_mism = sub_impact(
            ref, rloc, ev["nbases"], ev["evtbases"], ev["evtsub"],
            r_trloc, max_codons)
        stop_aapos, aa4, maa4, aa4_v, maa4_v = indel_stop_scan(
            ref, ref_len, rloc, ev["evt"], ev["evtlen"], ev["nbases"],
            ev["evtbases"], r_trloc, max_len)
        out.update(s_orig_aa=s_orig, s_new_aa=s_new, s_aapos=s_pos,
                   s_valid=s_valid, s_mismatch=s_mism,
                   stop_aapos=stop_aapos, aa4=aa4, maa4=maa4,
                   aa4_valid=aa4_v, maa4_valid=maa4_v)
    return out
