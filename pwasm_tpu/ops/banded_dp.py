"""Batched banded affine-gap DP (Gotoh) — the north-star re-alignment
kernel (SURVEY.md §0: "batched banded affine-gap DP re-alignment,
anti-diagonal wavefront ... over packed sequences").

The reference has exactly one alignment-scoring DP — the X-drop end
refinement (GapAssem.cpp:182-349).  This kernel generalizes it: a full
banded global aligner with affine gaps, batched over thousands of targets
(vmap lanes), integer scoring end-to-end so CPU/TPU results are bit-exact.

Formulation
-----------
DP matrices M (match/mismatch), Ix (gap in target, consumes query), Iy
(gap in query, consumes target), band of width B in diagonal space:
row ``i`` covers columns ``j = i + dlo + b`` for band index b in [0, B).

Row-wavefront recurrences in band coordinates (time = query row):

- ``M[i][b]  = max(M,Ix,Iy)[i-1][b] + s(q_i, t_j)``       (diagonal stays)
- ``Ix[i][b] = max(M[i-1][b+1] - GO, Ix[i-1][b+1] - GE)`` (up shifts by 1)
- ``Iy[i][b] = max_{k<b}(M[i][k] - GO - (b-1-k) GE)``     (left chain)

The Iy chain is the only intra-row dependency; it collapses to a running
max of ``M[i][k] + k*GE`` (a cumulative max), so every row is fully
vectorized — no scalar inner loop, and the same closed form works inside
the Pallas kernel as a log-step shift-max.

No Ix<->Iy adjacency (a deletion directly followed by an insertion) —
standard Gotoh; the numpy reference in tests uses the identical recurrence.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

NEG = -(2 ** 30)  # -inf surrogate, safe against int32 underflow

# Iy-chain implementation inside the Pallas tile recurrence:
# "log" (default) = flat log2(band) shift-max chain; "two_level" =
# intra-sublane-group scan + group-prefix fold (see _make_tile_recurrence)
# — an on-chip A/B knob for the headline kernel's dominant op block.
import os as _os

_IY_CHAIN = _os.environ.get("PWASM_DP_IYCHAIN", "log")


@dataclass(frozen=True)
class ScoreParams:
    """Integer alignment scores (penalties positive)."""

    match: int = 2
    mismatch: int = 4
    gap_open: int = 4    # charged when a gap opens (in addition to extend)
    gap_extend: int = 2

    @property
    def go(self) -> int:  # total cost of the first gap base
        return self.gap_open + self.gap_extend


def band_dlo(m: int, n: int, band: int) -> int:
    """Static band placement: diagonal offsets j-i in [dlo, dlo+band).
    Centers the band between the start diagonal (0) and the end diagonal
    (n-m); raises if the band can't cover both."""
    dlo = (n - m) // 2 - band // 2
    if not (dlo <= 0 <= dlo + band - 1 and dlo <= n - m <= dlo + band - 1):
        raise ValueError(
            f"band {band} too narrow for sizes m={m}, n={n}"
            f" (needs to cover diagonals 0 and {n - m})")
    return dlo


def initial_wavefront(n: int, dlo: int, band: int,
                      params: ScoreParams) -> tuple:
    """Row-0 wavefront state (M, Ix, Iy) in band coordinates."""
    ge, go = params.gap_extend, params.go
    bidx = jnp.arange(band, dtype=jnp.int32)
    j0 = dlo + bidx
    m0 = jnp.where(j0 == 0, 0, NEG).astype(jnp.int32)
    iy0 = jnp.where((j0 >= 1) & (j0 <= n),
                    -(go + (j0 - 1) * ge), NEG).astype(jnp.int32)
    ix0 = jnp.full((band,), NEG, dtype=jnp.int32)
    return m0, ix0, iy0


def make_row_step(n: int, dlo, band: int, params: ScoreParams,
                  emit_ptrs: bool = False):
    """The shared DP row recurrence in band coordinates.

    Returns ``step(prev_m, prev_ix, prev_iy, i, qi, t) -> (m, ix, iy)``
    where ``i`` is the 1-based absolute query row and ``t`` the (n,)
    padded target.  The single-chip scan, the sequence-parallel
    wavefront pipeline (pwasm_tpu.parallel.wavefront_sp) and the
    traceback re-aligner (pwasm_tpu.ops.realign) all call this exact
    function, so their integer scores agree bit for bit.  ``dlo`` may be
    a Python int or a traced int32 scalar (every use is arithmetic).

    With ``emit_ptrs=True`` the step additionally returns one packed
    uint8 pointer per band cell: bits 0-1 = diag argmax (0=M, 1=Ix,
    2=Iy, tie-break M >= Ix >= Iy), bit 2 = Ix from extend, bit 3 = Iy
    from extend (gap-open wins ties) — the traceback re-aligner's
    inputs.  The j==0 Ix boundary override below equals the generic
    max it replaces (M[i-1][j=0] is NEG for i > 1 and 0 for i = 1), so
    the extend bit stays valid there.
    """
    ge, go = params.gap_extend, params.go
    bidx = jnp.arange(band, dtype=jnp.int32)

    def step(prev_m, prev_ix, prev_iy, i, qi, t):
        j = i + dlo + bidx
        valid = (j >= 1) & (j <= n)
        tj = jnp.where(valid, t[jnp.clip(j - 1, 0, n - 1)], 127)
        s = jnp.where((qi == tj) & (qi < 4),
                      params.match, -params.mismatch)
        diag = jnp.maximum(prev_m, jnp.maximum(prev_ix, prev_iy))
        m_new = jnp.where(valid, diag + s, NEG)
        up_m = jnp.concatenate([prev_m[1:], jnp.array([NEG])])
        up_ix = jnp.concatenate([prev_ix[1:], jnp.array([NEG])])
        ix_new = jnp.maximum(up_m - go, up_ix - ge)
        # boundary column j == 0: only a leading target-gap is alive
        ix_new = jnp.where(j == 0, -(go + (i - 1) * ge), ix_new)
        ix_new = jnp.where((j < 0) | (j > n), NEG, ix_new)
        # left chain: Iy[b] = max_{k<b} (M[b's row][k] - GO - (b-1-k) GE)
        u = m_new + bidx * ge
        run = jax.lax.associative_scan(jnp.maximum, u)
        run_prev = jnp.concatenate([jnp.array([NEG]), run[:-1]])
        iy_new = run_prev - go - (bidx - 1) * ge
        iy_new = jnp.where(valid, iy_new, NEG)
        m_new = m_new.astype(jnp.int32)
        ix_new = ix_new.astype(jnp.int32)
        iy_new = iy_new.astype(jnp.int32)
        if not emit_ptrs:
            return m_new, ix_new, iy_new
        dm = jnp.where((prev_m >= prev_ix) & (prev_m >= prev_iy), 0,
                       jnp.where(prev_ix >= prev_iy, 1, 2))
        bx = (up_ix - ge > up_m - go).astype(jnp.int32)
        # Iy[b] == max(M[b-1] - go, Iy[b-1] - ge) (the closed form is
        # the unrolled chain); recover the sequential-form bit in-row
        negv = jnp.full((1,), NEG, dtype=jnp.int32)
        m_left = jnp.concatenate([negv, m_new[:-1]])
        iy_left = jnp.concatenate([negv, iy_new[:-1]])
        by = (iy_left - ge > m_left - go).astype(jnp.int32)
        ptr = (dm | (bx << 2) | (by << 3)).astype(jnp.uint8)
        return m_new, ix_new, iy_new, ptr

    return step


def final_score(m_f, ix_f, iy_f, t_len, m: int, dlo: int,
                band: int) -> jax.Array:
    """Extract the global score at cell (m, t_len) from the last
    wavefront; NEG if t_len falls outside the band."""
    b_end = t_len - m - dlo
    in_band = (b_end >= 0) & (b_end < band)
    b_end = jnp.clip(b_end, 0, band - 1)
    best = jnp.maximum(m_f[b_end], jnp.maximum(ix_f[b_end], iy_f[b_end]))
    return jnp.where(in_band, best, NEG).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("band", "params"))
def banded_score(q: jax.Array, t: jax.Array, t_len: jax.Array,
                 band: int = 64,
                 params: ScoreParams = ScoreParams()) -> jax.Array:
    """Banded global alignment score of one query vs one (padded) target.

    q: (m,) int8 base codes (0..3 real bases; >=4 never matches)
    t: (n,) int8 padded target; t_len: true target length (<= n)
    Returns the int32 global score at cell (m, t_len), or NEG if t_len
    falls outside the band.
    """
    m = q.shape[0]
    n = t.shape[0]
    dlo = band_dlo(m, n, band)
    step = make_row_step(n, dlo, band, params)
    wf0 = initial_wavefront(n, dlo, band, params)

    def row(carry, qi):
        prev_m, prev_ix, prev_iy, i = carry
        i = i + 1
        m_new, ix_new, iy_new = step(prev_m, prev_ix, prev_iy, i, qi, t)
        return (m_new, ix_new, iy_new, i), None

    (m_f, ix_f, iy_f, _), _ = jax.lax.scan(
        row, (*wf0, jnp.int32(0)), q.astype(jnp.int32))
    return final_score(m_f, ix_f, iy_f, t_len, m, dlo, band)


@functools.partial(jax.jit, static_argnames=("band", "params"))
def banded_scores_batch(q: jax.Array, ts: jax.Array, t_lens: jax.Array,
                        band: int = 64,
                        params: ScoreParams = ScoreParams()) -> jax.Array:
    """vmap over a (T, n) target batch -> (T,) int32 scores."""
    return jax.vmap(lambda t, l: banded_score(q, t, l, band, params))(
        ts, t_lens)


# ---------------------------------------------------------------------------
# Pallas TPU kernel: band on the SUBLANE axis, targets on the LANE axis
# (128 targets per block).  The per-row band window of the target is a
# dynamic-start sublane slice of a padded, transposed target ref — the only
# memory-access pattern in the row loop, and one Mosaic lowers natively
# (no gathers, no value-space dynamic_slice).  The query lives in SMEM and
# is read one scalar per row.
# ---------------------------------------------------------------------------
def _make_tile_recurrence(n, band, dlo, match, mismatch, go, ge, block_t):
    """The DP row recurrence on (band, block_t) int32 tiles, shared by
    BOTH Pallas kernels (resident and HBM-streaming) so their scoring
    stays identical by construction — the tile-space analog of
    ``make_row_step``.  Returns ``(init, row_tile, extract)``:

    - ``init() -> (m, ix, iy)`` row-0 wavefront tiles;
    - ``row_tile(carry, i, qi, tj, interior=False) -> (m, ix, iy)`` one
      query row given the scalar query base ``qi`` and the
      (band, block_t) target window ``tj``; the Iy chain is a log2(band)
      shift-max cumulative scan along the sublane (band) axis.  With
      ``interior=True`` (compile-time) the boundary masks are elided —
      valid only for rows where the whole band lies in 1..n, i.e.
      ``1 - dlo <= i <= n - band - dlo + 1`` (measured ~1.5x on v5e,
      since masks are ~1/4 of the row's vector ops);
    - ``extract(carry, t_len, m) -> (1, block_t)`` the per-lane global
      score at cell (m, t_len) via a masked max (no gather).
    """
    bidx = jax.lax.broadcasted_iota(jnp.int32, (band, block_t), 0)
    neg = jnp.full((band, block_t), NEG, dtype=jnp.int32)

    def init():
        j0 = dlo + bidx
        m_v = jnp.where(j0 == 0, 0, NEG)
        iy_v = jnp.where((j0 >= 1) & (j0 <= n), -(go + (j0 - 1) * ge),
                         NEG)
        return m_v, neg, iy_v

    def row_tile(carry, i, qi, tj, interior=False):
        m_prev, ix_prev, iy_prev = carry
        # qi < 4 (a real base) is a scalar predicate: fold it into the
        # match score instead of a per-element vector mask
        m_sel = jax.lax.select(qi < jnp.int32(4), jnp.int32(match),
                               jnp.int32(-mismatch))
        s = jnp.where(tj == qi, m_sel, jnp.int32(-mismatch))
        diag = jnp.maximum(m_prev, jnp.maximum(ix_prev, iy_prev))
        m_new = diag + s
        up_m = jnp.concatenate([m_prev[1:], neg[:1]], axis=0)
        up_ix = jnp.concatenate([ix_prev[1:], neg[:1]], axis=0)
        ix_new = jnp.maximum(up_m - go, up_ix - ge)
        if not interior:
            j = i + dlo + bidx
            valid = (j >= 1) & (j <= n)
            m_new = jnp.where(valid, m_new, NEG)
            # boundary column j == 0: only a leading target-gap is alive
            ix_new = jnp.where(j == 0, -(go + (i - 1) * ge), ix_new)
            ix_new = jnp.where((j < 0) | (j > n), NEG, ix_new)
        # cumulative max of m_new + b*ge along the band
        run = m_new + bidx * ge
        if _IY_CHAIN == "two_level" and band % 8 == 0 and band >= 16:
            # two-level scan: an intra-group inclusive scan over
            # 8-sublane groups (3 full-tile shift-max steps), then an
            # exclusive scan over the band//8 group totals (log steps on
            # 1/8 of the data) folded back with one max — ~7 full-tile
            # op-equivalents vs 2*log2(band) for the flat chain.  The
            # group axis maps shifts to intra-vreg sublane moves; worth
            # it only if Mosaic relayouts the (g, 8, T) reshape cheaply
            # (an on-chip A/B knob, PWASM_DP_IYCHAIN).
            g = band // 8
            r3 = run.reshape(g, 8, block_t)
            neg3 = jnp.full_like(r3, NEG)
            intra = r3
            for sh in (1, 2, 4):
                shifted = jnp.concatenate(
                    [neg3[:, :sh], intra[:, :-sh]], axis=1)
                intra = jnp.maximum(intra, shifted)
            totals = intra[:, 7:8, :]            # (g, 1, T) group maxes
            pre = jnp.full_like(totals, NEG)     # exclusive group prefix
            acc = totals
            sh = 1
            while sh < g:
                shifted = jnp.concatenate(
                    [jnp.full_like(acc[:sh], NEG), acc[:-sh]], axis=0)
                acc = jnp.maximum(acc, shifted)
                sh *= 2
            pre = jnp.concatenate([pre[:1], acc[:-1]], axis=0)
            run = jnp.maximum(intra, pre).reshape(band, block_t)
        else:
            sh = 1                       # flat log-step shift-max chain
            while sh < band:
                shifted = jnp.concatenate([neg[:sh], run[:-sh]], axis=0)
                run = jnp.maximum(run, shifted)
                sh *= 2
        run_prev = jnp.concatenate([neg[:1], run[:-1]], axis=0)
        iy_new = run_prev - go - (bidx - 1) * ge
        if not interior:
            iy_new = jnp.where(valid, iy_new, NEG)
        return m_new, ix_new, iy_new

    def extract(carry, t_len, m):
        m_f, ix_f, iy_f = carry
        b_end = t_len - m - dlo
        in_band = (b_end >= 0) & (b_end < band)
        best3 = jnp.maximum(m_f, jnp.maximum(ix_f, iy_f))
        best = jnp.max(jnp.where(bidx == b_end, best3, NEG), axis=0,
                       keepdims=True)
        return jnp.where(in_band, best, NEG)

    return init, row_tile, extract


def _banded_kernel(q_ref, t_ref, tlen_ref, out_ref, *, m, n, band, dlo,
                   match, mismatch, go, ge, block_t, unroll=4):
    """One grid step aligns ``block_t`` targets against the shared query.

    State: three (band, block_t) int32 wavefronts updated over m rows.
    ``t_ref`` is (band + n + band + unroll, block_t): the target
    transposed with ``band`` rows of padding in front and
    ``band + unroll`` behind so every window load is in bounds
    (band_dlo guarantees dlo >= 1 - band and m + dlo <= n).

    Three phases: a masked head loop for rows whose band sticks out of
    1..n on the left, an interior loop (boundary masks statically elided,
    ``unroll`` rows per iteration off ONE widened window slice), and a
    masked tail loop.  The split is static — row ``i`` (1-based) is
    interior iff ``1 - dlo <= i <= n - band - dlo + 1``.
    """
    from jax.experimental import pallas as pl

    init, row_tile, extract = _make_tile_recurrence(
        n, band, dlo, match, mismatch, go, ge, block_t)

    def row(ii, carry):
        qi = q_ref[0, ii]  # scalar load from SMEM (dynamic index OK)
        # band window of target bases t[j-1]: rows (i+dlo-1+b) of the
        # unpadded transpose = rows (ii+dlo+band ...) of the padded ref
        tj = t_ref[pl.ds(ii + dlo + band, band), :]
        return row_tile(carry, ii + 1, qi, tj)

    # 0-based row index ranges of the three phases (static Python ints)
    head = min(max(0, -dlo), m)              # rows 0 .. head-1 masked
    int_end = max(head, min(m, n - band - dlo + 1))
    nblk = (int_end - head) // unroll

    carry = jax.lax.fori_loop(0, head, row, init())

    def blk(bb, carry):
        i0 = head + bb * unroll
        win = t_ref[pl.ds(i0 + dlo + band, band + unroll - 1), :]
        for r in range(unroll):
            qi = q_ref[0, i0 + r]
            carry = row_tile(carry, i0 + r + 1, qi, win[r:r + band],
                             interior=True)
        return carry

    carry = jax.lax.fori_loop(0, nblk, blk, carry)
    carry = jax.lax.fori_loop(head + nblk * unroll, m, row, carry)
    out_ref[...] = extract(carry, tlen_ref[...], m)


@functools.partial(jax.jit,
                   static_argnames=("band", "params", "block_t",
                                    "interpret"))
def banded_scores_pallas(q: jax.Array, ts: jax.Array, t_lens: jax.Array,
                         band: int = 128,
                         params: ScoreParams = ScoreParams(),
                         block_t: int = 128,
                         interpret: bool | None = None) -> jax.Array:
    """Pallas banded aligner: (T, n) targets -> (T,) int32 scores.

    Targets ride the lane axis in blocks of ``block_t`` (use multiples of
    128); the band rides the sublane axis (multiples of 8).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        from pwasm_tpu.ops import default_interpret
        interpret = default_interpret()
    m = q.shape[0]
    T, n = ts.shape
    dlo = band_dlo(m, n, band)
    pad_t = (T + block_t - 1) // block_t * block_t
    if pad_t != T:
        ts = jnp.pad(ts, ((0, pad_t - T), (0, 0)), constant_values=127)
        t_lens = jnp.pad(t_lens, (0, pad_t - T), constant_values=0)
    # transpose to (n, T) and pad the sequence axis with `band` sentinel
    # rows in front and `band + unroll` behind so every row-window slice
    # (including the widened interior-block window) is in bounds
    unroll = 4
    ts_T = jnp.pad(ts.astype(jnp.int32).T, ((band, band + unroll), (0, 0)),
                   constant_values=127)
    kernel = functools.partial(
        _banded_kernel, m=m, n=n, band=band, dlo=dlo,
        match=params.match, mismatch=params.mismatch,
        go=params.go, ge=params.gap_extend, block_t=block_t,
        unroll=unroll)
    out = pl.pallas_call(
        kernel,
        grid=(pad_t // block_t,),
        in_specs=[
            pl.BlockSpec((1, m), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((n + 2 * band + unroll, block_t),
                         lambda i: (0, i)),
            pl.BlockSpec((1, block_t), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_t), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, pad_t), jnp.int32),
        interpret=interpret,
    )(q.astype(jnp.int32)[None, :], ts_T,
      t_lens.astype(jnp.int32)[None, :])
    return out[0, :T]


# ---------------------------------------------------------------------------
# Long-read variant (BASELINE.md config #5): same wavefront recurrence, but
# the target stays in HBM and the per-chunk band windows stream into a
# double-buffered VMEM scratch with explicit async DMA — VMEM holds only
# O(chunk x block_t), not O(n x block_t), so 50 kb+ sequences fit.
# ---------------------------------------------------------------------------
def _banded_kernel_long(q_ref, t_hbm, tlen_ref, out_ref, t_buf0, t_buf1,
                        sems, *, m, n, band, dlo, match, mismatch, go, ge,
                        block_t, chunk):
    """One grid step aligns ``block_t`` targets, streaming the target in
    row chunks.

    ``t_hbm`` is the padded transposed target batch in HBM/ANY:
    (band + n + band + 2*chunk, T_pad) int32.  Rows
    [ci*chunk + dlo + band, +chunk+band) cover every band window of query
    rows [ci*chunk, (ci+1)*chunk).  Chunks are processed in pairs with two
    statically-addressed VMEM buffers (Mosaic cannot dynamically index a
    buffer-slot axis, and int8 refs don't support dynamic sublane slices —
    hence 2 x 2-D int32 buffers): while chunk 2c computes out of buf0, the
    DMA for 2c+1 fills buf1, and vice versa (double buffering).  Chunks at
    or past n_chunks read only sentinel padding and their rows are masked
    pass-throughs, so the pair round-up needs no control flow.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    tb = pl.program_id(0)
    n_chunks = (m + chunk - 1) // chunk
    n_pairs = (n_chunks + 1) // 2
    window = chunk + band
    init, row_tile, extract = _make_tile_recurrence(
        n, band, dlo, match, mismatch, go, ge, block_t)

    def get_dma(buf, slot, ci):
        return pltpu.make_async_copy(
            t_hbm.at[pl.ds(ci * chunk + dlo + band, window),
                     pl.ds(tb * block_t, block_t)],
            buf, sems.at[slot])

    get_dma(t_buf0, 0, 0).start()

    def rows_masked(buf, ci, carry):
        def row(rr, carry2):
            ii = ci * chunk + rr
            qi = q_ref[0, jnp.minimum(ii, m - 1)]
            tj = buf[pl.ds(rr, band), :]
            new = row_tile(carry2, ii + 1, qi, tj)
            # rows past the true query length are pass-through
            keep = ii < m
            return tuple(jnp.where(keep, nv, ov)
                         for nv, ov in zip(new, carry2))

        return jax.lax.fori_loop(0, chunk, row, carry)

    def rows_interior(buf, ci, carry):
        # every row of this chunk lies strictly inside 1..n and < m, so
        # the boundary masks and the past-m pass-through are statically
        # elided — the same ~1.5x interior elision the resident kernel
        # applies (see _banded_kernel's phase split)
        def row(rr, carry2):
            ii = ci * chunk + rr
            qi = q_ref[0, ii]
            tj = buf[pl.ds(rr, band), :]
            return row_tile(carry2, ii + 1, qi, tj, interior=True)

        return jax.lax.fori_loop(0, chunk, row, carry)

    def pair_body(rows0, rows1):
        def body(cc, carry):
            ci0 = 2 * cc
            get_dma(t_buf1, 1, ci0 + 1).start()
            get_dma(t_buf0, 0, ci0).wait()
            carry = rows0(t_buf0, ci0, carry)

            @pl.when(cc + 1 < n_pairs)
            def _():
                get_dma(t_buf0, 0, ci0 + 2).start()

            get_dma(t_buf1, 1, ci0 + 1).wait()
            return rows1(t_buf1, ci0 + 1, carry)

        return body

    # static phase split at PAIR granularity: a chunk is interior iff
    # all its rows are (0-based ii in [head, int_end), the same bounds
    # as the resident kernel's phases); pairs with both chunks interior
    # run the unmasked bodies
    head = min(max(0, -dlo), m)
    int_end = max(head, min(m, n - band - dlo + 1))

    def chunk_interior(ci):
        return ci * chunk >= head and (ci + 1) * chunk <= int_end

    pair_ok = [chunk_interior(2 * c) and chunk_interior(2 * c + 1)
               for c in range(n_pairs)]
    p_lo = next((c for c, ok in enumerate(pair_ok) if ok), n_pairs)
    p_hi = next((c for c in range(n_pairs - 1, -1, -1)
                 if pair_ok[c]), p_lo - 1) + 1

    carry = jax.lax.fori_loop(0, p_lo,
                              pair_body(rows_masked, rows_masked), init())
    carry = jax.lax.fori_loop(p_lo, p_hi,
                              pair_body(rows_interior, rows_interior),
                              carry)
    carry = jax.lax.fori_loop(p_hi, n_pairs,
                              pair_body(rows_masked, rows_masked), carry)
    out_ref[...] = extract(carry, tlen_ref[...], m)


@functools.partial(jax.jit,
                   static_argnames=("band", "params", "block_t", "chunk",
                                    "interpret"))
def banded_scores_long(q: jax.Array, ts: jax.Array, t_lens: jax.Array,
                       band: int = 128,
                       params: ScoreParams = ScoreParams(),
                       block_t: int = 128, chunk: int = 1024,
                       interpret: bool | None = None) -> jax.Array:
    """HBM-streaming banded aligner for long sequences: (T, n) targets ->
    (T,) int32 scores, bit-exact with ``banded_scores_batch``.

    Unlike ``banded_scores_pallas`` (whole target resident in VMEM), only
    a (chunk + band, block_t) double-buffered window lives on-chip, so n
    is bounded by HBM, not VMEM; DMA of chunk ci+1 overlaps compute of
    chunk ci.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        from pwasm_tpu.ops import default_interpret
        interpret = default_interpret()
    m = q.shape[0]
    T, n = ts.shape
    dlo = band_dlo(m, n, band)
    pad_t = (T + block_t - 1) // block_t * block_t
    if pad_t != T:
        ts = jnp.pad(ts, ((0, pad_t - T), (0, 0)), constant_values=127)
        t_lens = jnp.pad(t_lens, (0, pad_t - T), constant_values=0)
    # sentinel padding: band rows in front (windows may start at negative
    # diagonals), band + 2*chunk behind (the pair round-up may issue one
    # dead chunk's DMA past the last real window).  int32 because Mosaic
    # can't dynamically sublane-slice int8 VMEM refs.
    ts_T = jnp.pad(ts.astype(jnp.int32).T, ((band, band + 2 * chunk),
                                            (0, 0)),
                   constant_values=127)
    kernel = functools.partial(
        _banded_kernel_long, m=m, n=n, band=band, dlo=dlo,
        match=params.match, mismatch=params.mismatch,
        go=params.go, ge=params.gap_extend, block_t=block_t, chunk=chunk)
    out = pl.pallas_call(
        kernel,
        grid=(pad_t // block_t,),
        in_specs=[
            pl.BlockSpec((1, m), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec((1, block_t), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_t), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, pad_t), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((chunk + band, block_t), jnp.int32),
            pltpu.VMEM((chunk + band, block_t), jnp.int32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(q.astype(jnp.int32)[None, :], ts_T,
      t_lens.astype(jnp.int32)[None, :])
    return out[0, :T]


# ---------------------------------------------------------------------------
# numpy reference (full-matrix Gotoh) for cross-checking — O(mn), exact
# ---------------------------------------------------------------------------
def full_gotoh_score(q: np.ndarray, t: np.ndarray,
                     params: ScoreParams = ScoreParams()) -> int:
    """Unbanded full-matrix Gotoh global score, identical recurrence
    (no Ix<->Iy adjacency).  Integer math; the oracle for the band tests."""
    m, n = len(q), len(t)
    ge, go = params.gap_extend, params.go
    M = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Ix = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Iy = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    M[0, 0] = 0
    for j in range(1, n + 1):
        Iy[0, j] = -(go + (j - 1) * ge)
    for i in range(1, m + 1):
        Ix[i, 0] = -(go + (i - 1) * ge)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            s = params.match if (q[i - 1] == t[j - 1] and q[i - 1] < 4) \
                else -params.mismatch
            M[i, j] = max(M[i - 1, j - 1], Ix[i - 1, j - 1],
                          Iy[i - 1, j - 1]) + s
            Ix[i, j] = max(M[i - 1, j] - go, Ix[i - 1, j] - ge)
            Iy[i, j] = max(M[i, j - 1] - go, Iy[i, j - 1] - ge)
    return int(max(M[m, n], Ix[m, n], Iy[m, n]))
