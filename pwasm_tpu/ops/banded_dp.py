"""Batched banded affine-gap DP (Gotoh) — the north-star re-alignment
kernel (SURVEY.md §0: "batched banded affine-gap DP re-alignment,
anti-diagonal wavefront ... over packed sequences").

The reference has exactly one alignment-scoring DP — the X-drop end
refinement (GapAssem.cpp:182-349).  This kernel generalizes it: a full
banded global aligner with affine gaps, batched over thousands of targets
(vmap lanes), integer scoring end-to-end so CPU/TPU results are bit-exact.

Formulation
-----------
DP matrices M (match/mismatch), Ix (gap in target, consumes query), Iy
(gap in query, consumes target), band of width B in diagonal space:
row ``i`` covers columns ``j = i + dlo + b`` for band index b in [0, B).

Row-wavefront recurrences in band coordinates (time = query row):

- ``M[i][b]  = max(M,Ix,Iy)[i-1][b] + s(q_i, t_j)``       (diagonal stays)
- ``Ix[i][b] = max(M[i-1][b+1] - GO, Ix[i-1][b+1] - GE)`` (up shifts by 1)
- ``Iy[i][b] = max_{k<b}(M[i][k] - GO - (b-1-k) GE)``     (left chain)

The Iy chain is the only intra-row dependency; it collapses to a running
max of ``M[i][k] + k*GE`` (a cumulative max), so every row is fully
vectorized — no scalar inner loop, and the same closed form works inside
the Pallas kernel as a log-step shift-max.

No Ix<->Iy adjacency (a deletion directly followed by an insertion) —
standard Gotoh; the numpy reference in tests uses the identical recurrence.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

NEG = -(2 ** 30)  # -inf surrogate, safe against int32 underflow


@dataclass(frozen=True)
class ScoreParams:
    """Integer alignment scores (penalties positive)."""

    match: int = 2
    mismatch: int = 4
    gap_open: int = 4    # charged when a gap opens (in addition to extend)
    gap_extend: int = 2

    @property
    def go(self) -> int:  # total cost of the first gap base
        return self.gap_open + self.gap_extend


def band_dlo(m: int, n: int, band: int) -> int:
    """Static band placement: diagonal offsets j-i in [dlo, dlo+band).
    Centers the band between the start diagonal (0) and the end diagonal
    (n-m); raises if the band can't cover both."""
    dlo = (n - m) // 2 - band // 2
    if not (dlo <= 0 <= dlo + band - 1 and dlo <= n - m <= dlo + band - 1):
        raise ValueError(
            f"band {band} too narrow for sizes m={m}, n={n}"
            f" (needs to cover diagonals 0 and {n - m})")
    return dlo


@functools.partial(jax.jit, static_argnames=("band", "params"))
def banded_score(q: jax.Array, t: jax.Array, t_len: jax.Array,
                 band: int = 64,
                 params: ScoreParams = ScoreParams()) -> jax.Array:
    """Banded global alignment score of one query vs one (padded) target.

    q: (m,) int8 base codes (0..3 real bases; >=4 never matches)
    t: (n,) int8 padded target; t_len: true target length (<= n)
    Returns the int32 global score at cell (m, t_len), or NEG if t_len
    falls outside the band.
    """
    m = q.shape[0]
    n = t.shape[0]
    dlo = band_dlo(m, n, band)
    ge = params.gap_extend
    go = params.go
    bidx = jnp.arange(band, dtype=jnp.int32)

    # ---- row 0
    j0 = dlo + bidx
    m0 = jnp.where(j0 == 0, 0, NEG)
    iy0 = jnp.where((j0 >= 1) & (j0 <= n), -(go + (j0 - 1) * ge), NEG)
    ix0 = jnp.full((band,), NEG, dtype=jnp.int32)

    def row(carry, qi):
        prev_m, prev_ix, prev_iy, i = carry
        i = i + 1
        j = i + dlo + bidx
        valid = (j >= 1) & (j <= n)
        tj = jnp.where(valid, t[jnp.clip(j - 1, 0, n - 1)], 127)
        s = jnp.where((qi == tj) & (qi < 4),
                      params.match, -params.mismatch)
        diag = jnp.maximum(prev_m, jnp.maximum(prev_ix, prev_iy))
        m_new = jnp.where(valid, diag + s, NEG)
        up_m = jnp.concatenate([prev_m[1:], jnp.array([NEG])])
        up_ix = jnp.concatenate([prev_ix[1:], jnp.array([NEG])])
        ix_new = jnp.maximum(up_m - go, up_ix - ge)
        # boundary column j == 0: only a leading target-gap is alive
        ix_new = jnp.where(j == 0, -(go + (i - 1) * ge), ix_new)
        ix_new = jnp.where((j < 0) | (j > n), NEG, ix_new)
        # left chain: Iy[b] = max_{k<b} (M[b's row][k] - GO - (b-1-k) GE)
        u = m_new + bidx * ge
        run = jax.lax.associative_scan(jnp.maximum, u)
        run_prev = jnp.concatenate([jnp.array([NEG]), run[:-1]])
        iy_new = run_prev - go - (bidx - 1) * ge
        iy_new = jnp.where(valid, iy_new, NEG)
        return (m_new.astype(jnp.int32), ix_new.astype(jnp.int32),
                iy_new.astype(jnp.int32), i), None

    (m_f, ix_f, iy_f, _), _ = jax.lax.scan(
        row, (m0.astype(jnp.int32), ix0, iy0.astype(jnp.int32),
              jnp.int32(0)),
        q.astype(jnp.int32))
    b_end = t_len - m - dlo
    in_band = (b_end >= 0) & (b_end < band)
    b_end = jnp.clip(b_end, 0, band - 1)
    best = jnp.maximum(m_f[b_end], jnp.maximum(ix_f[b_end], iy_f[b_end]))
    return jnp.where(in_band, best, NEG).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("band", "params"))
def banded_scores_batch(q: jax.Array, ts: jax.Array, t_lens: jax.Array,
                        band: int = 64,
                        params: ScoreParams = ScoreParams()) -> jax.Array:
    """vmap over a (T, n) target batch -> (T,) int32 scores."""
    return jax.vmap(lambda t, l: banded_score(q, t, l, band, params))(
        ts, t_lens)


# ---------------------------------------------------------------------------
# Pallas TPU kernel: whole batch in one kernel, band on the lane axis,
# targets on the sublane axis.
# ---------------------------------------------------------------------------
def _banded_kernel(q_ref, t_ref, tlen_ref, out_ref, *, m, n, band, dlo,
                   match, mismatch, go, ge, block_t):
    """One grid step aligns ``block_t`` targets against the shared query.

    State: three (block_t, band) int32 wavefronts updated over m rows with
    a fori_loop; the Iy chain is a log2(band) shift-max cumulative scan.
    """
    bidx = jax.lax.broadcasted_iota(jnp.int32, (block_t, band), 1)
    q = q_ref[...]        # (1, m) int32
    t = t_ref[...]        # (block_t, n) int32
    neg = jnp.full((block_t, band), NEG, dtype=jnp.int32)

    j0 = dlo + bidx
    m_v = jnp.where(j0 == 0, 0, NEG)
    iy_v = jnp.where((j0 >= 1) & (j0 <= n), -(go + (j0 - 1) * ge), NEG)
    ix_v = neg

    def row(ii, carry):
        m_prev, ix_prev, iy_prev = carry
        i = ii + 1
        j = i + dlo + bidx
        valid = (j >= 1) & (j <= n)
        qi = jax.lax.dynamic_slice(q, (0, ii), (1, 1))[0, 0]
        jc = jnp.clip(j - 1, 0, n - 1)
        tj = jnp.take_along_axis(t, jc, axis=1)
        s = jnp.where((qi == tj) & (qi < 4), match, -mismatch)
        diag = jnp.maximum(m_prev, jnp.maximum(ix_prev, iy_prev))
        m_new = jnp.where(valid, diag + s, NEG)
        up_m = jnp.concatenate([m_prev[:, 1:], neg[:, :1]], axis=1)
        up_ix = jnp.concatenate([ix_prev[:, 1:], neg[:, :1]], axis=1)
        ix_new = jnp.maximum(up_m - go, up_ix - ge)
        ix_new = jnp.where(j == 0, -(go + (i - 1) * ge), ix_new)
        ix_new = jnp.where((j < 0) | (j > n), NEG, ix_new)
        # cumulative max of m_new + b*ge along the band (log-step scan)
        run = m_new + bidx * ge
        sh = 1
        while sh < band:
            shifted = jnp.concatenate(
                [neg[:, :sh], run[:, :-sh]], axis=1)
            run = jnp.maximum(run, shifted)
            sh *= 2
        run_prev = jnp.concatenate([neg[:, :1], run[:, :-1]], axis=1)
        iy_new = run_prev - go - (bidx - 1) * ge
        iy_new = jnp.where(valid, iy_new, NEG)
        return m_new, ix_new, iy_new

    m_f, ix_f, iy_f = jax.lax.fori_loop(0, m, row, (m_v, ix_v, iy_v))
    t_len = tlen_ref[...]  # (block_t, 1)
    b_end = t_len - m - dlo
    in_band = (b_end >= 0) & (b_end < band)
    b_clip = jnp.clip(b_end, 0, band - 1)
    best3 = jnp.maximum(m_f, jnp.maximum(ix_f, iy_f))
    best = jnp.take_along_axis(best3, b_clip, axis=1)
    out_ref[...] = jnp.where(in_band, best, NEG)


@functools.partial(jax.jit,
                   static_argnames=("band", "params", "block_t",
                                    "interpret"))
def banded_scores_pallas(q: jax.Array, ts: jax.Array, t_lens: jax.Array,
                         band: int = 128,
                         params: ScoreParams = ScoreParams(),
                         block_t: int = 8,
                         interpret: bool | None = None) -> jax.Array:
    """Pallas banded aligner: (T, n) targets -> (T,) int32 scores.

    band rides the lane axis (use multiples of 128); targets ride the
    sublane axis in blocks of ``block_t`` per grid step.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    m = q.shape[0]
    T, n = ts.shape
    dlo = band_dlo(m, n, band)
    pad_t = (T + block_t - 1) // block_t * block_t
    if pad_t != T:
        ts = jnp.pad(ts, ((0, pad_t - T), (0, 0)), constant_values=127)
        t_lens = jnp.pad(t_lens, (0, pad_t - T), constant_values=0)
    kernel = functools.partial(
        _banded_kernel, m=m, n=n, band=band, dlo=dlo,
        match=params.match, mismatch=params.mismatch,
        go=params.go, ge=params.gap_extend, block_t=block_t)
    out = pl.pallas_call(
        kernel,
        grid=(pad_t // block_t,),
        in_specs=[
            pl.BlockSpec((1, m), lambda i: (0, 0)),
            pl.BlockSpec((block_t, n), lambda i: (i, 0)),
            pl.BlockSpec((block_t, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_t, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((pad_t, 1), jnp.int32),
        interpret=interpret,
    )(q.astype(jnp.int32)[None, :], ts.astype(jnp.int32),
      t_lens.astype(jnp.int32)[:, None])
    return out[:T, 0]


# ---------------------------------------------------------------------------
# numpy reference (full-matrix Gotoh) for cross-checking — O(mn), exact
# ---------------------------------------------------------------------------
def full_gotoh_score(q: np.ndarray, t: np.ndarray,
                     params: ScoreParams = ScoreParams()) -> int:
    """Unbanded full-matrix Gotoh global score, identical recurrence
    (no Ix<->Iy adjacency).  Integer math; the oracle for the band tests."""
    m, n = len(q), len(t)
    ge, go = params.gap_extend, params.go
    M = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Ix = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    Iy = np.full((m + 1, n + 1), NEG, dtype=np.int64)
    M[0, 0] = 0
    for j in range(1, n + 1):
        Iy[0, j] = -(go + (j - 1) * ge)
    for i in range(1, m + 1):
        Ix[i, 0] = -(go + (i - 1) * ge)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            s = params.match if (q[i - 1] == t[j - 1] and q[i - 1] < 4) \
                else -params.mismatch
            M[i, j] = max(M[i - 1, j - 1], Ix[i - 1, j - 1],
                          Iy[i - 1, j - 1]) + s
            Ix[i, j] = max(M[i - 1, j] - go, Ix[i - 1, j] - ge)
            Iy[i, j] = max(M[i, j - 1] - go, Iy[i, j - 1] - ge)
    return int(max(M[m, n], Ix[m, n], Iy[m, n]))
