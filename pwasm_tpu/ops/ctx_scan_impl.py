"""Shared host/device implementation of the variant-context scan.

The SAME formulas run under two array namespaces: ``jax.numpy`` inside
the jitted device program (``ops/ctx_scan.py``) and plain ``numpy`` for
the vectorized host path (``report/columnar.py``).  Every function takes
the namespace as ``xp`` — host/device parity is therefore structural
(one formula, two executors), not a pair of implementations kept in sync
by tests alone.

This module must stay FREE OF JAX IMPORTS: the plain-CPU CLI loads it
on its hot path, and importing jax there would both pay the ~seconds
import cost the CPU pin exists to avoid and risk touching an unhealthy
tunnel backend.  (``ops/ctx_scan.py`` holds the jit wrappers.)

Semantics ported bit-for-bit from the reference — see the docstrings in
``ops/ctx_scan.py`` for the pafreport.cpp line citations (context
windows with the right-edge quirk, homopolymer 4-run overlap rule,
first-motif-wins scan, codon impact through the 5^3 LUT, frameshift
stop scan over the whole modified suffix).

Event tensor layout (produced by ``pack_events_np``):
  rloc (E,) int32; evt (E,) int32 {0=S, 1=I, 2=D}; evtlen (E,) int32
  (the reference's evtlen field — stays 1 for merged substitutions);
  nbases (E,) actual evtbases length; evtbases/evtsub (E, MAXEV) int8
  codes padded with PAD.
"""

from __future__ import annotations

import numpy as np

from pwasm_tpu.core.dna import AA_LUT, CODE_N, encode

PAD = 6
EVT_S, EVT_I, EVT_D = 0, 1, 2
CTX = 9          # reference-context window size
MAX_MOTIF = 8    # max motif length supported by the device scan


def next_pow2(n: int, floor: int = 256) -> int:
    """Smallest power of two >= max(n, floor) — the shape-bucket rule
    shared by the event axis, the reference tensor, and the stop-scan
    window, so the jitted programs key on a SMALL FIXED SET of shapes
    instead of recompiling (and re-dispatching) per exact size."""
    return max(floor, 1 << (max(int(n), 1) - 1).bit_length())


def ref_bucket_len(ref_len: int, max_ev: int) -> int:
    """Power-of-two padded length for the reference tensor.  Must cover
    ``ref_len + max_ev + 3`` (the frameshift stop-scan window reads the
    whole modified suffix, which an insertion lengthens by up to
    ``max_ev`` bases, plus one codon of slack)."""
    return next_pow2(ref_len + max_ev + 3)


def translate_codes(c0, c1, c2, xp=np):
    """Codes (clipped to N) -> amino-acid ASCII via the 5^3 LUT; any code
    outside [0,4) translates through N -> 'X'.

    The LUT is materialized per call, not at module level as a device
    array: under jit it constant-folds, and the numpy path pays one
    cheap asarray (it is already a numpy array there)."""
    lut = xp.asarray(AA_LUT)
    c0 = xp.clip(c0, 0, CODE_N)
    c1 = xp.clip(c1, 0, CODE_N)
    c2 = xp.clip(c2, 0, CODE_N)
    return lut[(c0 * 25 + c1 * 5 + c2).astype(xp.int32)]


def pack_events_np(events, max_ev: int = 16, bucket: int = 256) -> dict:
    """SoA-pack a list of DiffEvent into numpy tensors.  Events whose
    bases exceed ``max_ev`` must take the scalar path (caller filters).

    The event axis is padded to ``next_pow2`` of a multiple of
    ``bucket`` so the jitted ctx_scan program is reused across flushes
    from a small fixed set of compiled shapes (256, 512, 1024, ...)
    instead of recompiling for every distinct event count; padding rows
    are zeros (a 0-length 'S' event at rloc 0) and callers read only
    the first ``len(events)`` results.  ``bucket=0`` skips padding (the
    host path — no compile cache to key)."""
    from pwasm_tpu.core.dna import ENCODE_TABLE

    E = len(events)
    E_pad = next_pow2(E, bucket) if bucket else E
    if E == 0:
        return dict(rloc=np.zeros(E_pad, np.int32),
                    evt=np.zeros(E_pad, np.int32),
                    evtlen=np.zeros(E_pad, np.int32),
                    nbases=np.zeros(E_pad, np.int32),
                    evtbases=np.full((E_pad, max_ev), PAD, np.int8),
                    evtsub=np.full((E_pad, max_ev), PAD, np.int8))
    evt_code = {"S": EVT_S, "I": EVT_I, "D": EVT_D}
    rloc = np.zeros(E_pad, np.int32)
    evt = np.zeros(E_pad, np.int32)
    evtlen = np.zeros(E_pad, np.int32)
    rloc[:E] = np.fromiter((ev.rloc for ev in events), np.int32, E)
    evt[:E] = np.fromiter((evt_code[ev.evt] for ev in events),
                          np.int32, E)
    evtlen[:E] = np.fromiter((ev.evtlen for ev in events), np.int32, E)

    def code_plane(raw: list[bytes]):
        # one concatenated encode + a single scatter instead of one
        # numpy round-trip per event (the realistic-scale report packs
        # tens of thousands of events per flush)
        lens = np.fromiter(map(len, raw), np.int64, E)
        cat = np.frombuffer(b"".join(raw), dtype=np.uint8)
        codes = ENCODE_TABLE[cat]
        keep_lens = np.minimum(lens, max_ev)   # callers filter; clip
        #                                        is belt-and-suspenders
        starts = np.concatenate(([0], np.cumsum(lens)[:-1]))
        idx_row = np.repeat(np.arange(E), lens)
        idx_col = np.arange(len(cat)) - np.repeat(starts, lens)
        plane = np.full((E_pad, max_ev), PAD, np.int8)
        if (lens > max_ev).any():
            sel = idx_col < max_ev
            plane[idx_row[sel], idx_col[sel]] = codes[sel]
        else:
            plane[idx_row, idx_col] = codes
        return plane, keep_lens.astype(np.int32)

    evtbases, nb = code_plane([ev.evtbases.upper() for ev in events])
    evtsub, _ = code_plane([ev.evtsub.upper() for ev in events])
    nbases = np.zeros(E_pad, np.int32)
    nbases[:E] = nb
    return dict(rloc=rloc, evt=evt, evtlen=evtlen, nbases=nbases,
                evtbases=evtbases, evtsub=evtsub)


def pack_motifs_np(motifs) -> tuple[np.ndarray, np.ndarray]:
    """Motif table -> (codes (NM, MAX_MOTIF) int8, lens (NM,) int32)."""
    nm = len(motifs)
    codes = np.full((nm, MAX_MOTIF), PAD, np.int8)
    lens = np.zeros(nm, np.int32)
    for i, mot in enumerate(motifs):
        b = encode(mot.encode() if isinstance(mot, str) else mot)
        if len(b) > MAX_MOTIF:
            raise ValueError(f"motif longer than {MAX_MOTIF}: {mot}")
        codes[i, :len(b)] = b
        lens[i] = len(b)
    return codes, lens


def ref_context_windows(ref, ref_len, rloc, xp=np):
    """(E,) event positions -> (E, 9) windows + (E,) local offsets,
    mirroring get_ref_context exactly (including the right-edge quirk)."""
    ctxstart = rloc - 4
    evtloc = xp.full_like(rloc, 4)
    left = ctxstart < 0
    right = ~left & (ctxstart + 8 >= ref_len)
    evtloc = xp.where(left, evtloc + ctxstart, evtloc)
    # the right-edge branch uses the OLD ctxstart in its (sign-flipped)
    # adjustment — reference behavior preserved
    evtloc = xp.where(right, evtloc + ref_len - ctxstart - 9, evtloc)
    ctxstart = xp.where(left, 0, ctxstart)
    ctxstart = xp.where(right, ref_len - 9, ctxstart)
    degen = right & (ctxstart < 0)
    evtloc = xp.where(degen, evtloc + ctxstart, evtloc)
    ctxstart = xp.where(degen, 0, ctxstart)
    idx = ctxstart[:, None] + xp.arange(CTX)[None, :]
    win = ref[xp.clip(idx, 0, ref.shape[0] - 1)]
    return win, evtloc


def hpoly_flags(evtbases, nbases, rctx, rctxloc, xp=np):
    """Vectorized hpolyCheck: all event bases identical AND a 4-run of the
    base inside the window overlapping the event offset."""
    first = evtbases[:, 0]
    kidx = xp.arange(evtbases.shape[1])[None, :]
    valid = kidx < nbases[:, None]
    all_same = xp.all((evtbases == first[:, None]) | ~valid, axis=1)
    # seed positions l in [0, 6): window[l:l+4] all == first
    l = xp.arange(CTX - 4 + 1)
    runs = xp.all(
        rctx[:, l[:, None] + xp.arange(4)[None, :]]
        == first[:, None, None], axis=2)           # (E, 6)
    # reference uses GStr::index -> FIRST run position only
    has_run = xp.any(runs, axis=1)
    lpos = xp.argmax(runs, axis=1)
    overlap = (lpos <= rctxloc) & (rctxloc <= lpos + 4)
    return all_same & has_run & overlap & (nbases > 0)


def motif_hits(rctx, mot_codes, mot_lens, xp=np):
    """First motif (table order) found anywhere in each window; returns
    (E,) int32 1-based motif index, 0 = none."""
    nm, mw = mot_codes.shape
    starts = xp.arange(CTX)                        # candidate start pos
    ks = xp.arange(mw)
    idx = starts[:, None] + ks[None, :]            # (9, mw)
    win = rctx[:, xp.clip(idx, 0, CTX - 1)]        # (E, 9, mw)
    cmp = win[:, None] == mot_codes[None, :, None]  # (E, nm, 9, mw)
    klt = ks[None, :] < mot_lens[:, None]           # (nm, mw)
    ok = xp.all(cmp | ~klt[None, :, None, :], axis=3)  # (E, nm, 9)
    fits = (starts[None, :] + mot_lens[:, None]) <= CTX  # (nm, 9)
    found = xp.any(ok & fits[None], axis=2)        # (E, nm)
    any_hit = xp.any(found, axis=1)
    first = xp.argmax(found, axis=1)
    return xp.where(any_hit, first + 1, 0).astype(xp.int32)


def sub_impact(ref, rloc, nbases, evtbases, evtsub, r_trloc,
               max_codons: int, xp=np):
    """Substitution codon impact: for up to ``max_codons`` affected codons
    return (orig_aa, new_aa, aapos, valid, sub_mismatch)."""
    e_off = rloc - r_trloc                  # event offset in the window
    ao_first = e_off // 3
    ao_last = (e_off + xp.maximum(nbases, 1) - 1) // 3
    d = xp.arange(max_codons, dtype=xp.int32)[None, :]
    ao = ao_first[:, None] + d              # (E, K) codon window indices
    kvalid = ao <= ao_last[:, None]
    cpos = r_trloc[:, None, None] + ao[..., None] * 3 \
        + xp.arange(3, dtype=xp.int32)[None, None, :]  # (E, K, 3) abs pos
    Rn = ref.shape[0]
    orig = ref[xp.clip(cpos, 0, Rn - 1)]
    orig = xp.where(cpos < Rn, orig, PAD)
    # overlay the substituted bases at [rloc, rloc+nbases)
    rel = cpos - rloc[:, None, None]
    inside = (rel >= 0) & (rel < nbases[:, None, None])
    sub = evtbases[xp.arange(evtbases.shape[0])[:, None, None],
                   xp.clip(rel, 0, evtbases.shape[1] - 1)]
    mod = xp.where(inside, sub, orig)
    orig_aa = translate_codes(orig[..., 0], orig[..., 1], orig[..., 2],
                              xp=xp)
    new_aa = translate_codes(mod[..., 0], mod[..., 1], mod[..., 2],
                             xp=xp)
    aapos = ao + (rloc // 3)[:, None]
    # the reference verifies each substituted base against the query
    # (pafreport.cpp:812-813); surface that as a flag the host turns fatal
    kb = xp.arange(evtbases.shape[1])[None, :]
    bvalid = kb < nbases[:, None]
    refb = ref[xp.clip(rloc[:, None] + kb, 0, Rn - 1)]
    mism = xp.any((refb != evtsub) & bvalid, axis=1)
    return orig_aa, new_aa, aapos, kvalid, mism


def indel_stop_scan(ref, ref_len, rloc, evt, evtlen, nbases, evtbases,
                    r_trloc, max_len: int, xp=np):
    """Frameshift analysis for I/D events: build the modified suffix
    (insert/cut at the event), translate codon-by-codon, find the first
    premature stop, and collect the reference's aa4/maa4 preview codons.

    Returns (stop_aapos (E,) int32 or -1, aa4 (E,4) uint8, maa4 (E,4)
    uint8, aa4_valid, maa4_valid).  ``max_len`` bounds the scanned
    window; a stop past it is reported as -1 (the host driver rescans
    unresolved lanes with a larger window — see report/columnar.py)."""
    E = rloc.shape[0]
    Rn = ref.shape[0]
    e_off = rloc - r_trloc
    is_ins = evt == EVT_I
    nb = xp.where(is_ins, nbases, evtlen)
    j = xp.arange(max_len, dtype=xp.int32)[None, :]  # (1, W) positions
    # source index for each modified-sequence position
    ins_src = xp.where(j < e_off[:, None], r_trloc[:, None] + j,
                       r_trloc[:, None] + j - nb[:, None])
    ins_inside = (j >= e_off[:, None]) & (j < (e_off + nb)[:, None])
    del_src = xp.where(j < e_off[:, None], r_trloc[:, None] + j,
                       r_trloc[:, None] + j + nb[:, None])
    src = xp.where(is_ins[:, None], ins_src, del_src)
    base = ref[xp.clip(src, 0, Rn - 1)]
    base = xp.where(src < ref_len, base, PAD)
    insb = evtbases[xp.arange(E)[:, None],
                    xp.clip(j - e_off[:, None], 0,
                            evtbases.shape[1] - 1)]
    seq = xp.where(is_ins[:, None] & ins_inside, insb, base)
    modlen = xp.where(is_ins, ref_len - r_trloc + nb,
                      ref_len - r_trloc - nb)
    n_cod = max_len // 3
    cpos = xp.arange(n_cod, dtype=xp.int32)[None, :] * 3
    cpos_b = xp.broadcast_to(cpos, (E, n_cod))
    c0 = xp.take_along_axis(seq, cpos_b, axis=1)
    c1 = xp.take_along_axis(seq, cpos_b + 1, axis=1)
    c2 = xp.take_along_axis(seq, cpos_b + 2, axis=1)
    aa = translate_codes(c0, c1, c2, xp=xp)  # (E, n_cod)
    cvalid = (cpos + 2) < modlen[:, None]   # while i+2 < len(modseq)
    stop = (aa == ord(".")) & cvalid
    has_stop = xp.any(stop, axis=1)
    cstar = xp.argmax(stop, axis=1)
    stop_aapos = xp.where(has_stop, 1 + cstar + r_trloc // 3, -1)
    # aa4/maa4: codons c = 1..4, before the stop, valid in each sequence
    c14 = xp.arange(1, 5)[None, :]
    before_stop = xp.where(has_stop[:, None], c14 < cstar[:, None], True)
    c14_b = xp.broadcast_to(c14, (E, 4))
    maa4_valid = before_stop & xp.take_along_axis(cvalid, c14_b, axis=1)
    maa4 = xp.take_along_axis(aa, c14_b, axis=1)
    # aa4 comes from the unmodified suffix (same positions)
    opos = r_trloc[:, None] + c14 * 3
    o0 = ref[xp.clip(opos, 0, Rn - 1)]
    o1 = ref[xp.clip(opos + 1, 0, Rn - 1)]
    o2 = ref[xp.clip(opos + 2, 0, Rn - 1)]
    o0 = xp.where(opos < ref_len, o0, PAD)
    o1 = xp.where(opos + 1 < ref_len, o1, PAD)
    o2 = xp.where(opos + 2 < ref_len, o2, PAD)
    aa4 = translate_codes(o0, o1, o2, xp=xp)
    # reference guard: i+2 < len(r_trseq)  <=>  opos+2 < ref_len
    aa4_valid = maa4_valid & ((opos + 2) < ref_len)
    return stop_aapos.astype(xp.int32), aa4, maa4, aa4_valid, maa4_valid


def ctx_scan_prologue(ref, ref_len, ev: dict, mot_codes, mot_lens,
                      xp=np) -> tuple[dict, object]:
    """The codan-independent half of the scan — context windows,
    homopolymer/motif attribution, the event codon's amino acid — plus
    the translation-window start ``r_trloc``.  ONE implementation
    shared by the fused device program (``ctx_scan_calc``) and the
    lane-filtered host driver (``report/columnar.host_ctx_scan``):
    parity between them is structural, not hand-synced."""
    rloc = ev["rloc"]
    rctx, rctxloc = ref_context_windows(ref, ref_len, rloc, xp=xp)
    hpoly = hpoly_flags(ev["evtbases"], ev["nbases"], rctx, rctxloc,
                        xp=xp)
    motif = motif_hits(rctx, mot_codes, mot_lens, xp=xp)
    aapos0 = rloc // 3
    ca = aapos0 * 3
    aa = translate_codes(
        ref[xp.clip(ca, 0, ref.shape[0] - 1)],
        xp.where(ca + 1 < ref_len,
                 ref[xp.clip(ca + 1, 0, ref.shape[0] - 1)], PAD),
        xp.where(ca + 2 < ref_len,
                 ref[xp.clip(ca + 2, 0, ref.shape[0] - 1)], PAD),
        xp=xp)
    out = dict(rctx=rctx, rctxloc=rctxloc, hpoly=hpoly, motif=motif,
               aa=aa, aapos=aapos0 + 1)
    r_trloc = xp.maximum(3 * (aapos0 + 1 - 2), 0)
    return out, r_trloc


def ctx_scan_calc(ref, ref_len, ev: dict, mot_codes, mot_lens,
                  max_codons: int = 8, max_len: int = 4096,
                  skip_codan: bool = False, xp=np) -> dict:
    """The fused event-analysis program (host or device namespace).
    Returns a dict of arrays; ``report/columnar.py`` turns them into
    report rows."""
    rloc = ev["rloc"]
    out, r_trloc = ctx_scan_prologue(ref, ref_len, ev, mot_codes,
                                     mot_lens, xp=xp)
    if not skip_codan:
        s_orig, s_new, s_pos, s_valid, s_mism = sub_impact(
            ref, rloc, ev["nbases"], ev["evtbases"], ev["evtsub"],
            r_trloc, max_codons, xp=xp)
        stop_aapos, aa4, maa4, aa4_v, maa4_v = indel_stop_scan(
            ref, ref_len, rloc, ev["evt"], ev["evtlen"], ev["nbases"],
            ev["evtbases"], r_trloc, max_len, xp=xp)
        out.update(s_orig_aa=s_orig, s_new_aa=s_new, s_aapos=s_pos,
                   s_valid=s_valid, s_mismatch=s_mism,
                   stop_aapos=stop_aapos, aa4=aa4, maa4=maa4,
                   aa4_valid=aa4_v, maa4_valid=maa4_v)
    return out


def ctx_scan_layout(max_codons: int, skip_codan: bool) -> list:
    """(field, per-event width) pairs of the scan output, in the fixed
    order the packed single-tensor transfer uses (see
    ``ops/ctx_scan.py ctx_scan_packed`` / ``unpack_ctx_scan``)."""
    fields = [("rctx", CTX), ("rctxloc", 1), ("hpoly", 1), ("motif", 1),
              ("aa", 1), ("aapos", 1)]
    if not skip_codan:
        K = max_codons
        fields += [("s_orig_aa", K), ("s_new_aa", K), ("s_aapos", K),
                   ("s_valid", K), ("s_mismatch", 1), ("stop_aapos", 1),
                   ("aa4", 4), ("maa4", 4), ("aa4_valid", 4),
                   ("maa4_valid", 4)]
    return fields


def unpack_ctx_scan(flat: np.ndarray, max_codons: int,
                    skip_codan: bool) -> dict:
    """Split the packed (E, total_width) int32 fetch back into the
    per-field dict (numpy views — no copies).  Width-1 fields come back
    as (E,) and the rest as (E, width), exactly the shapes the dict
    form has."""
    out = {}
    col = 0
    for name, width in ctx_scan_layout(max_codons, skip_codan):
        if width == 1:
            out[name] = flat[:, col]
        else:
            out[name] = flat[:, col:col + width]
        col += width
    return out
