"""JAX/Pallas device kernels.

The three north-star kernels (SURVEY.md §7.1):

- ``consensus``  — per-column ACGT/N/gap pileup counting + the reference's
  consensus vote, as a pure-XLA path (works everywhere, vmap/pjit friendly)
  and a Pallas TPU kernel.
- ``banded_dp``  — batched banded affine-gap DP, anti-diagonal wavefront.
- ``ctx_scan``   — vectorized variant-context scan: 9bp windows,
  homopolymer/motif attribution, codon-impact LUT.

All integer math end-to-end: the parity contract with the CPU engine is
bit-exactness, not tolerance (SURVEY.md §7.3).
"""

from pwasm_tpu.ops.consensus import (  # noqa: F401
    pileup_counts,
    consensus_vote_counts,
    consensus_votes,
    consensus_pallas,
    votes_to_chars,
    CODE_ZERO_COV,
)


def on_tpu_backend() -> bool:
    """True when the default backend is a TPU — directly ('tpu') or via
    a tunnel plugin whose platform name differs (e.g. 'axon') but whose
    devices are real TPU chips (device_kind says so)."""
    import jax

    backend = jax.default_backend()
    if backend == "tpu":
        return True
    try:
        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or "").lower()
        return "tpu" in kind or "tpu" in backend.lower()
    except Exception:
        return False


def default_interpret() -> bool:
    """Pallas interpreter-mode default: on for non-TPU backends, and
    forced on everywhere by ``PWASM_DEVICE_INTERPRET=1`` — the JAX-side
    debugging analog of the reference's sanitizer builds (SURVEY.md §5:
    Makefile:30-47 memcheck): interpreter mode evaluates kernels
    op-by-op with real bounds semantics, so out-of-window slices and
    masking bugs surface as Python errors instead of silent garbage.
    ``PWASM_DEVICE_INTERPRET=0`` forces compiled (Mosaic) lowering even
    off-TPU — the smoke path that keeps interpreter-mode tests from
    masking a lowering break."""
    import os

    forced = os.environ.get("PWASM_DEVICE_INTERPRET", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    return not on_tpu_backend()
