"""JAX/Pallas device kernels.

The three north-star kernels (SURVEY.md §7.1):

- ``consensus``  — per-column ACGT/N/gap pileup counting + the reference's
  consensus vote, as a pure-XLA path (works everywhere, vmap/pjit friendly)
  and a Pallas TPU kernel.
- ``banded_dp``  — batched banded affine-gap DP, anti-diagonal wavefront.
- ``ctx_scan``   — vectorized variant-context scan: 9bp windows,
  homopolymer/motif attribution, codon-impact LUT.

All integer math end-to-end: the parity contract with the CPU engine is
bit-exactness, not tolerance (SURVEY.md §7.3).
"""

# Consensus re-exports are LAZY (PEP 562): `pwasm_tpu.ops.consensus`
# imports jax at module top, and eager re-exporting here made ANY
# submodule import — including the jax-free `ctx_scan_impl` the host
# columnar engine runs on — pay the full ~1.2 s jax import.  That was
# the single largest term in the plain-CPU CLI's cold wall (the
# realistic_pycli_vs_native_ratio bench leg); the host path must not
# import jax at all (tests/test_rowbytes.py gates it).
_CONSENSUS_EXPORTS = ("pileup_counts", "consensus_vote_counts",
                      "consensus_votes", "consensus_pallas",
                      "votes_to_chars", "CODE_ZERO_COV")


def __getattr__(name: str):
    if name in _CONSENSUS_EXPORTS:
        from pwasm_tpu.ops import consensus
        return getattr(consensus, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


_cache_armed = False


def enable_compilation_cache(cache_dir: str | None = None) -> None:
    """Arm JAX's persistent compilation cache (idempotent — the first
    caller's directory wins for the process).

    The CLI's device path compiles a handful of programs per run
    (ctx-scan per ref-length bucket, consensus, refine phases); a cold
    TPU compile costs tens of seconds, and the reference's workflow is
    MANY pafreport invocations over assembly batches — without a disk
    cache every invocation pays the compiles again.  Cache dir:
    explicit ``cache_dir`` (the ``--compile-cache-dir`` /
    ``serve --compile-cache-dir`` knob) > ``PWASM_JAX_CACHE_DIR`` >
    ``~/.cache/pwasm_tpu/jax``; opt out with ``PWASM_JAX_CACHE=0``.
    The ``jax.config`` surface itself is touched only through the
    jaxcompat shim (the config keys moved across jax pins before).
    Failures are non-fatal (the cache is an optimization, never a
    correctness dependency)."""
    global _cache_armed
    import os

    if _cache_armed or os.environ.get("PWASM_JAX_CACHE", "1") == "0":
        return
    _cache_armed = True
    d = cache_dir or os.environ.get("PWASM_JAX_CACHE_DIR") \
        or os.path.join(os.path.expanduser("~"), ".cache",
                        "pwasm_tpu", "jax")
    from pwasm_tpu.utils.jaxcompat import \
        enable_compilation_cache as _shim
    _shim(d)


def on_tpu_backend() -> bool:
    """True when the default backend is a TPU — directly ('tpu') or via
    a tunnel plugin whose platform name differs (e.g. 'axon') but whose
    devices are real TPU chips (device_kind says so)."""
    import jax

    backend = jax.default_backend()
    if backend == "tpu":
        return True
    try:
        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or "").lower()
        return "tpu" in kind or "tpu" in backend.lower()
    except Exception:
        return False


def default_interpret() -> bool:
    """Pallas interpreter-mode default: on for non-TPU backends, and
    forced on everywhere by ``PWASM_DEVICE_INTERPRET=1`` — the JAX-side
    debugging analog of the reference's sanitizer builds (SURVEY.md §5:
    Makefile:30-47 memcheck): interpreter mode evaluates kernels
    op-by-op with real bounds semantics, so out-of-window slices and
    masking bugs surface as Python errors instead of silent garbage.
    ``PWASM_DEVICE_INTERPRET=0`` forces compiled (Mosaic) lowering even
    off-TPU — the smoke path that keeps interpreter-mode tests from
    masking a lowering break."""
    import os

    forced = os.environ.get("PWASM_DEVICE_INTERPRET", "")
    if forced == "1":
        return True
    if forced == "0":
        return False
    return not on_tpu_backend()
