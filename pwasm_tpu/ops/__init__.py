"""JAX/Pallas device kernels.

The three north-star kernels (SURVEY.md §7.1):

- ``consensus``  — per-column ACGT/N/gap pileup counting + the reference's
  consensus vote, as a pure-XLA path (works everywhere, vmap/pjit friendly)
  and a Pallas TPU kernel.
- ``banded_dp``  — batched banded affine-gap DP, anti-diagonal wavefront.
- ``ctx_scan``   — vectorized variant-context scan: 9bp windows,
  homopolymer/motif attribution, codon-impact LUT.

All integer math end-to-end: the parity contract with the CPU engine is
bit-exactness, not tolerance (SURVEY.md §7.3).
"""

from pwasm_tpu.ops.consensus import (  # noqa: F401
    pileup_counts,
    consensus_vote_counts,
    consensus_votes,
    consensus_pallas,
    votes_to_chars,
    CODE_ZERO_COV,
)
