"""JAX/Pallas device kernels.

The three north-star kernels (SURVEY.md §7.1):

- ``consensus``  — per-column ACGT/N/gap pileup counting + the reference's
  consensus vote, as a pure-XLA path (works everywhere, vmap/pjit friendly)
  and a Pallas TPU kernel.
- ``banded_dp``  — batched banded affine-gap DP, anti-diagonal wavefront.
- ``ctx_scan``   — vectorized variant-context scan: 9bp windows,
  homopolymer/motif attribution, codon-impact LUT.

All integer math end-to-end: the parity contract with the CPU engine is
bit-exactness, not tolerance (SURVEY.md §7.3).
"""

from pwasm_tpu.ops.consensus import (  # noqa: F401
    pileup_counts,
    consensus_vote_counts,
    consensus_votes,
    consensus_pallas,
    votes_to_chars,
    CODE_ZERO_COV,
)


def default_interpret() -> bool:
    """Pallas interpreter-mode default: on for non-TPU backends, and
    forced on everywhere by ``PWASM_DEVICE_INTERPRET=1`` — the JAX-side
    debugging analog of the reference's sanitizer builds (SURVEY.md §5:
    Makefile:30-47 memcheck): interpreter mode evaluates kernels
    op-by-op with real bounds semantics, so out-of-window slices and
    masking bugs surface as Python errors instead of silent garbage."""
    import os

    import jax

    if os.environ.get("PWASM_DEVICE_INTERPRET", "0") == "1":
        return True
    return jax.default_backend() != "tpu"
