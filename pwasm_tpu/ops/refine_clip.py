"""Device X-drop clip-refinement phases (VERDICT r3 item 3).

The consensus path's only DP-style hot loop that still ran on host
(GASeq::refineClipping, /root/reference/GapAssem.cpp:182-349) moves to
the device: the per-member seek-initial-match and X-drop-extension
walks, already flattened to (members, layout) tensors by the host batch
pass (align/gapseq.py refine_clipping_batch), run here as ONE jitted
dense integer program — every member is a lane, every candidate walk
step a vector column, early exits become masks.  Bit-exact with the
host pass (and therefore with the scalar reference transliteration) by
construction: same integer scores, same first-occurrence tie-breaks
(argmax), same bounds masks.

The host keeps the ragged→padded layout build and the clp5/clp3
write-back; only the two phase computations ship to the device.  Shapes
are padded to power-of-two buckets so jit caches a handful of programs.
"""

from __future__ import annotations

import functools

import numpy as np

STAR = ord("*")


def _pow2(n: int, floor: int) -> int:
    return max(floor, 1 << max(0, int(n) - 1).bit_length())


@functools.lru_cache(maxsize=None)
def _phases_fn(xdrop: int, match_sc: int, mismatch_sc: int):
    """The raw (unjitted) phase program for one scoring constant set —
    jitted by ``_compiled`` for the single-device path and wrapped in
    ``shard_map`` by ``parallel.mesh.sharded_refine_phases`` for the
    member-sharded multi-chip path (members are independent lanes, so
    the sharding is pure data parallelism; no collectives)."""
    import jax
    import jax.numpy as jnp

    def take(arr2, pos, valid):
        safe = jnp.clip(pos, 0, arr2.shape[1] - 1)
        vals = jnp.take_along_axis(arr2, safe, axis=1)
        return jnp.where(valid, vals, 0)

    def phases(gseq, gxpos, cons, cpos, glen, totals, gclipL, gclipR,
               clipL0, clipR0, seqlens, cons_len):
        M, L = gseq.shape
        cons2 = jnp.broadcast_to(cons[None, :], (M, cons.shape[0]))
        d = jnp.arange(L, dtype=jnp.int32)[None, :]

        def seek(active, sp0, n_cand, direction):
            # batched initial-match seek (gapseq.py seek2, dense)
            sp = sp0[:, None] + direction * d
            cmask = active[:, None] & (d < n_cand[:, None])
            valid_s = cmask & (sp >= 0) & (sp < totals[:, None])
            gs = take(gseq, sp, valid_s)
            cp = cpos[:, None] + sp
            valid_c = cmask & (cp >= 0) & (cp < cons_len)
            cs = take(cons2, cp, valid_c)
            hit = valid_s & valid_c & (gs == cs) & (gs != STAR)
            bump = valid_s & (gs != STAR)
            hh = hit.any(axis=1)
            kk = jnp.argmax(hit, axis=1).astype(jnp.int32)
            bc = jnp.cumsum(bump, axis=1, dtype=jnp.int32)
            bump_at = jnp.take_along_axis(
                bump, kk[:, None], axis=1)[:, 0].astype(jnp.int32)
            bc_at = jnp.take_along_axis(bc, kk[:, None], axis=1)[:, 0]
            # hit rows: non-star candidates strictly before the hit;
            # miss rows: over ALL candidates (the scalar abort
            # semantics)
            bumps = jnp.where(hh, bc_at - bump_at, bc[:, -1])
            return active & hh, kk, jnp.where(active, bumps, 0)

        def extend(active, sp_m, direction):
            # batched X-drop extension (gapseq.py extend2, dense)
            cp_m = cpos + sp_m
            if direction > 0:
                K = jnp.minimum(glen - 1 - sp_m, cons_len - 1 - cp_m)
            else:
                K = jnp.minimum(sp_m, cp_m)
            K = jnp.where(active, jnp.maximum(K, 0), 0)
            ks = 1 + d
            within = active[:, None] & (ks <= K[:, None])
            pos = sp_m[:, None] + direction * ks
            gs = take(gseq, pos, within)
            cp2 = cp_m[:, None] + direction * ks
            cs = take(cons2, cp2, within)
            nonstar = within & (gs != STAR)
            eq = gs == cs
            delta = jnp.where(nonstar,
                              jnp.where(eq, match_sc, mismatch_sc), 0)
            scores = match_sc + jnp.cumsum(delta, axis=1,
                                           dtype=jnp.int32)
            stop = within & (scores <= xdrop)
            first_stop = jnp.where(stop.any(axis=1),
                                   jnp.argmax(stop, axis=1),
                                   L).astype(jnp.int32)
            in_limit = within & (d <= first_stop[:, None])
            cand = jnp.where(eq & nonstar & in_limit, scores, xdrop)
            best = cand.max(axis=1, initial=xdrop)
            bestk = 1 + jnp.argmax(cand, axis=1).astype(jnp.int32)
            improved = active & (best > match_sc)
            return jnp.where(improved, sp_m + direction * bestk, sp_m)

        clipL = clipL0
        clipR = clipR0

        # --- clipR phase (gapseq.py lines tagged 'clipR phase') --------
        actR = clipR0 > 0
        sp0R = glen - gclipR - 1
        n_candR = jnp.where(sp0R >= gclipL, sp0R - gclipL + 1, 1)
        hasR, kR, bumpsR = seek(actR, sp0R, n_candR, -1)
        missR = actR & ~hasR
        clipR = jnp.where(actR, clipR + bumpsR, clipR)
        sp_mR = sp0R - kR
        bestR = extend(hasR, sp_mR, +1)
        updR = hasR & (bestR > sp_mR)
        xposR = jnp.take_along_axis(gxpos, jnp.clip(bestR, 0, L - 1)
                                    [:, None], axis=1)[:, 0]
        clipR = jnp.where(updR, seqlens - xposR - 1, clipR)

        # --- clipL phase ----------------------------------------------
        actL = (clipL0 > 0) & ~missR
        sp0L = gclipL
        hi = glen - gclipR - 1
        n_candL = jnp.where(hi >= sp0L, hi - sp0L + 1, 1)
        hasL, kL, bumpsL = seek(actL, sp0L, n_candL, +1)
        missL = actL & ~hasL
        clipL = jnp.where(actL, clipL + bumpsL, clipL)
        sp_mL = sp0L + kL
        bestL = extend(hasL, sp_mL, -1)
        updL = hasL & (bestL < sp_mL)
        xposL = jnp.take_along_axis(gxpos, jnp.clip(bestL, 0, L - 1)
                                    [:, None], axis=1)[:, 0]
        clipL = jnp.where(updL, xposL, clipL)

        return clipL, clipR, missR, missL

    return phases


@functools.lru_cache(maxsize=None)
def _compiled(xdrop: int, match_sc: int, mismatch_sc: int):
    """The jitted phase program for one scoring constant set (the
    reference's XDROP/MATCH_SC/MISMATCH_SC — effectively a singleton)."""
    import jax

    return jax.jit(_phases_fn(xdrop, match_sc, mismatch_sc))


def refine_phases_device(gseq2, gxpos2, cons_arr, cpos, glen, totals,
                         gclipL, gclipR, clipL0, clipR0, seqlens,
                         xdrop: int, match_sc: int, mismatch_sc: int,
                         mesh=None):
    """Run both refinement phases on the device over the padded layout
    tensors built by refine_clipping_batch.  With ``mesh`` the member
    axis shards over every mesh axis (pure data parallelism).  Returns
    numpy (clipL, clipR, missR, missL) for the M real members."""
    import jax.numpy as jnp

    M, L = gseq2.shape
    Mp = _pow2(M, 8)
    if mesh is not None:
        tot = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
        Mp = -(-Mp // tot) * tot  # member axis must divide the mesh
    Lp = _pow2(L, 128)
    C = len(cons_arr)
    Cp = _pow2(C, 128)

    gseq = np.full((Mp, Lp), STAR, dtype=np.int32)
    gseq[:M, :L] = gseq2
    gxpos = np.zeros((Mp, Lp), dtype=np.int32)
    gxpos[:M, :L] = gxpos2
    cons = np.zeros(Cp, dtype=np.int32)
    cons[:C] = cons_arr

    def padv(v):
        out = np.zeros(Mp, dtype=np.int32)
        out[:M] = v
        return jnp.asarray(out)

    if mesh is not None:
        from pwasm_tpu.parallel.mesh import sharded_refine_phases

        fn = sharded_refine_phases(mesh, int(xdrop), int(match_sc),
                                   int(mismatch_sc))
    else:
        fn = _compiled(int(xdrop), int(match_sc), int(mismatch_sc))
    clipL, clipR, missR, missL = fn(
        jnp.asarray(gseq), jnp.asarray(gxpos), jnp.asarray(cons),
        padv(cpos), padv(glen), padv(totals), padv(gclipL),
        padv(gclipR), padv(clipL0), padv(clipR0), padv(seqlens),
        jnp.int32(C))
    return (np.asarray(clipL)[:M].astype(np.int64),
            np.asarray(clipR)[:M].astype(np.int64),
            np.asarray(missR)[:M], np.asarray(missL)[:M])
