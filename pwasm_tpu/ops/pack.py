"""2-bit sequence packing for host->device transfer.

The north-star kernel operates on "packed 2-bit sequences" (SURVEY.md §0):
A/C/G/T fit in 2 bits, so a target batch ships to the device at a quarter
of the int8 size — which matters when the link to the chip is thin (PCIe,
or the tunneled transport in this environment).  Packing runs in the
native C++ core (pwasm_tpu/native/fastparse.cpp pw_pack_2bit, numpy
fallback here), unpacking runs on device as a fused shift/mask that XLA
folds into the kernel's own preprocessing.

Padding note: packed batches carry no sentinel — padding columns decode
to base 0 ('A').  That is safe for the banded DP score: cell (i, j)
depends only on columns <= j (diag j-1, up j, left-chain < j), so cells
beyond a target's true length can never reach the score extracted at
(m, t_len).  The unpacked-path sentinel (127) is therefore unnecessary
for scoring; tests assert bit-exactness between the two paths.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def pack_targets(ts_codes: np.ndarray) -> np.ndarray:
    """Pack a (T, n) int8 base-code batch into (T, ceil(n/4)) uint8.

    Accepted codes are 0..3 (A/C/G/T) and the padding sentinel 127,
    which packs as base 0 ('A').  For padding (beyond each row's t_len)
    that cannot change scores (module docstring); any OTHER code (N=4,
    gap codes, negatives) is rejected — 2-bit packing would silently
    alias it to a real base, so N-bearing targets must use the int8
    path.  This is the ACGT-only fast transfer format.
    """
    from pwasm_tpu.native import pack_2bit

    ts = np.ascontiguousarray(ts_codes, dtype=np.int8)
    T, n = ts.shape
    bad = (ts < 0) | ((ts > 3) & (ts != 127))
    if bad.any():
        raise ValueError(
            "pack_targets: batch contains codes outside {0..3, 127 pad}; "
            "2-bit packing would alias them to real bases — use the int8 "
            "path")
    ts = np.where(ts == 127, np.int8(0), ts)
    nb = (n + 3) // 4
    if n % 4:
        ts = np.pad(ts, ((0, 0), (0, 4 * nb - n)))
    packed = pack_2bit(ts.reshape(-1))  # rows stay byte-aligned: 4 | row
    if packed is None:  # numpy fallback
        flat = (ts.reshape(-1).astype(np.uint8) & 3).reshape(-1, 4)
        packed = (flat[:, 0] | (flat[:, 1] << 2) | (flat[:, 2] << 4)
                  | (flat[:, 3] << 6)).astype(np.uint8)
    return packed.reshape(T, nb)


def unpack_targets_device(packed: jax.Array, n: int) -> jax.Array:
    """Device-side inverse: (T, nb) uint8 -> (T, n) int8 codes in 0..3.
    Pure shift/mask ops — XLA fuses this into downstream preprocessing."""
    shifts = jnp.array([0, 2, 4, 6], dtype=jnp.uint8)
    c = (packed[:, :, None] >> shifts[None, None, :]) & jnp.uint8(3)
    T, nb = packed.shape
    return c.reshape(T, nb * 4)[:, :n].astype(jnp.int8)


@functools.partial(jax.jit,
                   static_argnames=("n", "band", "params", "block_t"))
def banded_scores_packed(q: jax.Array, ts_packed: jax.Array, n: int,
                         t_lens: jax.Array, band: int = 64,
                         params=None, block_t: int = 128) -> jax.Array:
    """Banded DP scores from a 2-bit-packed target batch: unpack on
    device, then the Pallas wavefront kernel.  Bit-exact with
    ``banded_scores_pallas`` on the unpacked codes."""
    from pwasm_tpu.ops.banded_dp import ScoreParams, banded_scores_pallas

    params = params or ScoreParams()
    ts = unpack_targets_device(ts_packed, n)
    return banded_scores_pallas(q, ts, t_lens, band=band, params=params,
                                block_t=block_t)
