"""Per-column consensus: pileup counting + the reference vote rule.

Device equivalent of GAlnColumn counting (GapAssem.h:295-337) and bestChar
(GapAssem.cpp:1048-1069).  The vote is the closed form of the reference's
stable-sort + '-'/'N'-yield rule (see
``pwasm_tpu.align.msa.best_char_from_counts``):

- if any of A/C/G/T reaches the max count, the first of them (A<C<G<T) wins;
- else if N and '-' tie at the max, '-' wins;
- else whichever of N/'-' holds the max;
- a zero-coverage column votes ``CODE_ZERO_COV`` (the CPU engine raises
  exit-5 on those, GapAssem.cpp:1121-1131).

Everything is integer: int8 base codes in, int32 counts, int8 votes out —
bit-exact by construction against the CPU path.

Base codes: A=0 C=1 G=2 T=3 N=4 gap=5; code >=6 (or negative) = no
contribution (outside a member's span / clipped), used when pileups are
padded to rectangular tensors.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# the host twin lives in the jax-free ops/consensus_host.py (the CPU
# CLI must not import this module); re-exported here for compatibility
from pwasm_tpu.ops.consensus_host import (  # noqa: F401
    CODE_ZERO_COV, N_CLASSES, PAD_CODE, host_class_counts)


def pileup_counts(bases: jax.Array) -> jax.Array:
    """Count base classes per column.

    bases: (..., depth, cols) integer codes; codes outside [0, 6) are
    ignored (padding / clipped positions).
    Returns (..., cols, 6) int32 counts.

    Implemented as a one-hot contraction over the depth axis so XLA lowers
    it onto the MXU for large pileups; float32 accumulation of 0/1 values
    is exact below 2^24 layers, far beyond any real pileup depth.
    """
    oh = jax.nn.one_hot(bases, N_CLASSES, dtype=jnp.float32,
                        axis=-1)  # (..., depth, cols, 6); invalid -> all 0
    counts = jnp.sum(oh, axis=-3)
    return counts.astype(jnp.int32)


def consensus_vote_counts(counts: jax.Array) -> jax.Array:
    """Vote per column from (..., cols, 6) counts -> (..., cols) int8 codes
    (0..3 ACGT, 4 N, 5 gap, CODE_ZERO_COV for empty columns)."""
    counts = counts.astype(jnp.int32)
    acgt = counts[..., :4]
    n = counts[..., 4]
    gap = counts[..., 5]
    m_acgt = jnp.max(acgt, axis=-1)
    m_all = jnp.maximum(m_acgt, jnp.maximum(n, gap))
    first_acgt = jnp.argmax(acgt == m_all[..., None], axis=-1)
    acgt_wins = m_acgt == m_all
    both_tie = (n == m_all) & (gap == m_all)
    n_wins = (n == m_all) & ~both_tie
    code = jnp.where(acgt_wins, first_acgt,
                     jnp.where(n_wins, 4, 5))
    layers = jnp.sum(counts, axis=-1)
    return jnp.where(layers == 0, CODE_ZERO_COV, code).astype(jnp.int8)


@jax.jit
def consensus_votes(bases: jax.Array) -> jax.Array:
    """Fused pileup + vote: (..., depth, cols) codes -> (..., cols) votes."""
    return consensus_vote_counts(pileup_counts(bases))


def votes_to_chars(votes: np.ndarray, star_gap: bool = True) -> bytes:
    """Map vote codes to consensus characters ('*' for gap columns when
    ``star_gap``, matching refineMSA's consensus string)."""
    table = np.frombuffer(b"ACGTN" + (b"*" if star_gap else b"-"),
                          dtype=np.uint8)
    v = np.asarray(votes)
    if (v < 0).any():
        raise ValueError("zero-coverage column in votes")
    return table[v.astype(np.int64)].tobytes()


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------
def _consensus_kernel(bases_ref, counts_ref, votes_ref,
                      assume_valid=False):
    """One grid step: a (depth, COL_TILE) int8 block -> per-column counts
    and votes.  Pure VPU work; the counting packs all six class counters
    into one int32 per element (5 bits each, bits 0-29) and accumulates
    ``1 << 5*code`` over row chunks of 31 (the 5-bit carry limit), then
    unpacks — ~4 VPU ops/base instead of the naive 6x compare+select+add
    (~18 ops/base), measured 1.7x faster on a v5e.  Codes outside [0, 6)
    are remapped to the no-contribution shift (bit 30, never extracted;
    31 such rows overflow harmlessly past bit 31).

    ``assume_valid`` (static) declares every code already in [0, 6] —
    true for every in-product pileup (``Msa.pileup_matrix`` emits only
    0..6, and PAD_CODE 6 shifts into the inert bit 30 with no remap) —
    and elides the 2-op out-of-range remap, leaving ~4 VPU ops/base.
    """
    depth, c_tile = bases_ref.shape
    if depth <= 1024:
        # packed path: the 31-row chunk loop unrolls depth/31 bodies at
        # trace time, so cap it — beyond ~1024 rows the naive path below
        # keeps compile time flat (its 6 sums are depth-constant ops).
        # The int8->int32 widening and the out-of-range handling happen
        # PER CHUNK (31 rows), never materializing a (depth, C) int32
        # tensor: peak VMEM stays ~chunk-sized, which is what lets the
        # column tile grow (tile 4096 previously regressed on the
        # block-wide int32 temporaries).  Out-of-range codes remap to 6,
        # which shifts into bit 30, the never-extracted no-contribution
        # lane (31 such rows overflow harmlessly past bit 31): `& 255`
        # sends negative int8 codes to 128..255, then `min(, 6)` folds
        # them and every code > 6 onto 6 — 2 VPU ops vs 3 for the old
        # where-remap.
        cnts = [jnp.zeros((c_tile,), jnp.int32) for _ in range(N_CLASSES)]
        for r0 in range(0, depth, 31):
            chunk = bases_ref[r0:r0 + 31, :].astype(jnp.int32)
            if not assume_valid:
                chunk = jnp.minimum(chunk & 255, N_CLASSES)
            packed = jnp.sum(jnp.left_shift(jnp.int32(1), 5 * chunk),
                             axis=0)
            for k in range(N_CLASSES):
                cnts[k] = cnts[k] + (jnp.right_shift(packed, 5 * k) & 31)
        cnt = jnp.stack(cnts, axis=0)  # (6, C)
    else:
        b = bases_ref[...].astype(jnp.int32)
        cnt = jnp.stack([jnp.sum((b == k).astype(jnp.int32), axis=0)
                         for k in range(N_CLASSES)], axis=0)
    counts_ref[...] = cnt
    acgt = cnt[:4]
    n = cnt[4]
    gap = cnt[5]
    m_acgt = jnp.max(acgt, axis=0)
    m_all = jnp.maximum(m_acgt, jnp.maximum(n, gap))
    # first ACGT index hitting the max — masked min over the class axis
    # (Mosaic has no integer argmax; min of a where-masked iota is
    # equivalent and lowers to a plain int reduction)
    kidx = jax.lax.broadcasted_iota(jnp.int32, acgt.shape, 0)
    first_acgt = jnp.min(jnp.where(acgt == m_all[None, :], kidx,
                                   N_CLASSES), axis=0)
    acgt_wins = m_acgt == m_all
    both_tie = (n == m_all) & (gap == m_all)
    n_wins = (n == m_all) & ~both_tie
    code = jnp.where(acgt_wins, first_acgt, jnp.where(n_wins, 4, 5))
    layers = jnp.sum(cnt, axis=0)
    votes_ref[...] = jnp.where(layers == 0, CODE_ZERO_COV,
                               code)[None, :].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("col_tile", "interpret",
                                             "assume_valid"))
def consensus_pallas(bases: jax.Array, col_tile: int | None = None,
                     interpret: bool | None = None,
                     assume_valid: bool = False):
    """Pallas consensus over a (depth, cols) pileup.

    Returns (votes int8 (cols,), counts int32 (cols, 6)).  Pads columns to
    the tile size with PAD_CODE (those columns vote CODE_ZERO_COV and are
    sliced off).  On non-TPU backends runs in interpreter mode.
    ``assume_valid`` declares codes already in [0, 6] and elides the
    out-of-range remap (see _consensus_kernel) — safe for every pileup
    the engine itself builds.

    The default column tile is depth-aware: 2048 measured fastest on a
    v5e at 256-deep pileups (512: 192 G bases/s, 2048: ~300 G, 4096:
    regresses on VMEM pressure, 8192: fails to compile), but the block
    is (depth, col_tile) in VMEM, so the tile shrinks with depth to hold
    depth * col_tile at the measured-good 512K elements (floor 128): a
    1024-deep pileup gets tile 512, a 4096-deep one tile 128 — always
    at or below the VMEM footprint the old fixed 512 tile had at depth
    1024, where it was known to compile.
    """
    from jax.experimental import pallas as pl

    if interpret is None:
        from pwasm_tpu.ops import default_interpret
        interpret = default_interpret()
    depth, cols = bases.shape
    if col_tile is None:
        col_tile = max(128, min(2048, (1 << 19) // max(depth, 1)))
        col_tile = 1 << (col_tile.bit_length() - 1)  # power of two
    padded = (cols + col_tile - 1) // col_tile * col_tile
    if padded != cols:
        bases = jnp.pad(bases, ((0, 0), (0, padded - cols)),
                        constant_values=PAD_CODE)
    grid = (padded // col_tile,)
    counts, votes = pl.pallas_call(
        lambda b, c, v: _consensus_kernel(b, c, v,
                                          assume_valid=assume_valid),
        grid=grid,
        in_specs=[pl.BlockSpec((depth, col_tile), lambda i: (0, i))],
        out_specs=[
            pl.BlockSpec((N_CLASSES, col_tile), lambda i: (0, i)),
            pl.BlockSpec((1, col_tile), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N_CLASSES, padded), jnp.int32),
            jax.ShapeDtypeStruct((1, padded), jnp.int32),
        ],
        interpret=interpret,
    )(bases.astype(jnp.int8))
    return (votes[0, :cols].astype(jnp.int8),
            counts[:, :cols].T)
