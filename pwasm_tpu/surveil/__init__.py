"""Continuous fleet-wide many2many — the surveillance pipeline
(ROADMAP item 3, docs/SURVEIL.md).

jax-free coordination layer (``qa/check_supervision.py::
find_surveil_violations``): target FASTAs arrive incrementally over
the stream verbs, are scored against a resident query set with
incremental per-CDS section emission (``session.py``), and — behind
the fleet router — are partitioned across members and merged back
into one byte-identical report (``partition.py``).  All device work
stays behind ``stream/multicds.py`` and ``parallel/many2many.py``.
"""

from pwasm_tpu.surveil.records import FastaAssembler, parse_record
from pwasm_tpu.surveil.partition import (ScatterState, merge_fragments,
                                         rewrite_out_args)

__all__ = ["FastaAssembler", "parse_record", "ScatterState",
           "merge_fragments", "rewrite_out_args"]
