"""Incremental FASTA record assembly for streamed target sets.

Targets arrive over the stream verbs as raw FASTA text chunks split at
arbitrary byte boundaries.  ``FastaAssembler`` reassembles them into
*complete records* — a record is complete once the next ``>`` header
arrives (or the stream ends) — so both the router scatter path (which
forwards whole-record texts to members) and the session (which parses
them into ``(name, seq)`` pairs) agree on record boundaries.

Canonical record text is ``>header\\n`` followed by the sequence lines
exactly as received (minus blank lines), so re-concatenating the
records of a stream reproduces a parseable FASTA with identical
record digests.
"""

from __future__ import annotations


class FastaAssembler:
    """Reassemble FASTA records from arbitrarily-chunked text."""

    def __init__(self):
        self._tail = ""        # partial last line
        self._lines: list[str] = []  # complete lines of the open record
        self.records_out = 0

    @property
    def pending_lines(self) -> int:
        return len(self._lines) + (1 if self._tail else 0)

    def feed(self, data: str) -> list[str]:
        """Feed a chunk; return the record texts completed by it."""
        out: list[str] = []
        buf = self._tail + data.replace("\r\n", "\n").replace("\r", "\n")
        self._tail = ""
        lines = buf.split("\n")
        self._tail = lines.pop()  # "" when data ended on a newline
        for ln in lines:
            ln = ln.strip()
            if not ln:
                continue
            if ln.startswith(">") and self._lines:
                out.append(self._emit())
            self._lines.append(ln)
        return out

    def finish(self) -> list[str]:
        """Flush the trailing record (stream ended)."""
        if self._tail.strip():
            self._lines.append(self._tail.strip())
        self._tail = ""
        return [self._emit()] if self._lines else []

    def _emit(self) -> str:
        rec = "\n".join(self._lines) + "\n"
        self._lines = []
        self.records_out += 1
        return rec


def parse_record(text: str) -> tuple[str, str]:
    """Parse one canonical record text into ``(name, seq)``.

    The name is the first whitespace-delimited token of the header,
    matching ``stream/multicds.load_fasta``.
    """
    lines = [ln for ln in text.split("\n") if ln.strip()]
    if not lines or not lines[0].startswith(">"):
        raise ValueError(f"not a FASTA record: {text[:40]!r}")
    name = lines[0][1:].split()[0] if lines[0][1:].split() else ""
    seq = "".join(ln.strip() for ln in lines[1:])
    if not name:
        raise ValueError("FASTA record with empty name")
    return name, seq
