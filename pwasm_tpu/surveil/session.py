"""The ``--m2m-stream`` job type: continuous many2many.

The deployment shape the paper actually describes (PAPER.md §0,
ROADMAP item 3): a resident CDS query set (``-r``) stays loaded while
target assemblies arrive *incrementally* — over the daemon's stream
verbs when served (``input_stream`` is the job's
:class:`~pwasm_tpu.stream.pafstream.StreamFeed`), or from a target
FASTA replayed as one arrival when run cold.  Every arriving target is
scored against every resident query through the same supervised
``many2many`` site the one-shot driver uses, with *incremental per-CDS
emission*: each arrival batch dispatches only the (query, target)
pairs the section cache has never seen — any cached
``(query record, target record, band)`` score from the family pool
splices in verbatim, because banded-DP scores are pure in the pair —
and the final report is byte-identical to one ``--many2many`` run over
the accumulated targets in arrival order (the parity gate).

Deadlines follow the report-batch contract: ``--deadline-s`` is
checked at every per-CDS dispatch boundary; on expiry the session
cache-inserts every fully-scored partial section (the cache IS the
resume mechanism), requests the warm drain with a
``deadline_exceeded`` reason, and exits 75.

jax-free at module level (the ``find_surveil_violations`` gate): the
device stack loads lazily at the first dispatch, exactly like
``stream/multicds.py``.
"""

from __future__ import annotations

from pwasm_tpu.core.errors import EXIT_PREEMPTED, PwasmError
from pwasm_tpu.stream.multicds import (_usage_err, format_sections,
                                       format_summary, lane_span_mesh,
                                       load_fasta, open_section_store,
                                       parse_m2m_opts)
from pwasm_tpu.surveil.records import FastaAssembler, parse_record

# targets per dispatch when the stream runs hot (arrivals outpace the
# device): bounds per-batch latency without giving up batching when
# the feed has drained
MAX_ARRIVAL_BATCH = 64


def m2m_stream_main(opts: dict, positional: list, stdout, stderr,
                    warm=None, input_stream=None) -> int:
    import contextlib
    import time
    from types import SimpleNamespace

    from pwasm_tpu.utils import RunStats

    cfg = parse_m2m_opts(opts)
    if opts.get("many2many"):
        raise _usage_err("Error: --m2m-stream and --many2many are "
                         "mutually exclusive job types")
    if input_stream is None and len(positional) != 1:
        raise _usage_err("Error: --m2m-stream takes exactly one "
                         "<targets.fa> argument when not served over "
                         "the stream verbs")
    if input_stream is not None and positional:
        raise _usage_err("Error: a served --m2m-stream job takes its "
                         "targets from the stream, not a positional")
    t0_mono = time.monotonic()

    qnames, qs = load_fasta(cfg.rpath, "-r query")
    stats = RunStats()

    store = open_section_store(cfg.rc_dir, cfg.rc_max, warm, stderr)
    q_digs: list = []
    # the resident family pool: every cached (query record, target
    # record, band) score in the store is a valid splice — the banded
    # DP score is pure in the pair — so an arriving target re-scores
    # only what the store has never seen
    known: list[dict] = [dict() for _ in qs]
    if store is not None:
        from pwasm_tpu.service.cache import (m2m_family_key,
                                             record_digest)
        q_digs = [record_digest(qn, q)
                  for qn, q in zip(qnames, qs)]
        fams = {m2m_family_key(q_digs[qi], cfg.band): qi
                for qi in range(len(qs))}
        for _key, man in store.m2m_scan():
            fam = man["m2m"].get("family")
            qi = fams.get(fam) if isinstance(fam, str) else None
            rows_man = man["m2m"].get("targets")
            if qi is None or not isinstance(rows_man, list):
                continue
            try:
                for d, s in rows_man:
                    known[qi].setdefault(str(d), int(s))
            except (TypeError, ValueError):
                continue

    from pwasm_tpu.resilience import BatchSupervisor, ResiliencePolicy
    supervisor = BatchSupervisor(
        ResiliencePolicy(max_retries=cfg.max_retries,
                         fallback=cfg.fallback),
        stats=stats, stderr=stderr)
    if warm is not None and getattr(warm, "supervisor_state", None):
        supervisor.restore_state(warm.supervisor_state)

    # ---- arrival state: targets indexed by GLOBAL arrival order
    tnames: list[str] = []
    ts: list[str] = []
    tlens: list[int] = []
    t_digs: list = []
    rows: list[dict] = [dict() for _ in qs]  # qi -> {gidx: score}
    pending: list[int] = []
    prog = {"resident_queries": len(qs), "targets_in": 0,
            "targets_scored": 0, "targets_reused": 0,
            "pairs_dispatched": 0, "pairs_reused": 0,
            "sections_emitted": 0, "batches": 0, "done": False}

    def publish():
        # live progress for the svc-stats `m2m` block / top pane; the
        # feed carries no __slots__, so the attribute rides along
        if input_stream is not None:
            try:
                input_stream.m2m_progress = dict(prog)
            except Exception:
                pass

    state = SimpleNamespace(ready=False,
                            use_device=cfg.device == "tpu",
                            mesh=None, preempted=False)
    stack = contextlib.ExitStack()

    def ensure_engine():
        # one probe / one pin / one lane scope for the whole session,
        # deferred to the FIRST dispatch: an all-reused stream never
        # touches the device stack at all
        if state.ready:
            return
        state.ready = True
        if state.use_device:
            from pwasm_tpu.utils import backend as _backend
            from pwasm_tpu.utils.backend import \
                device_backend_reachable
            _p0 = _backend.probe_counters["probes"]
            _w0 = _backend.probe_counters["warm_hits"]
            ok, why = device_backend_reachable()
            stats.backend_probes += \
                _backend.probe_counters["probes"] - _p0
            stats.backend_warm_hits += \
                _backend.probe_counters["warm_hits"] - _w0
            if not ok:
                print(f"Warning: jax backend unreachable "
                      f"({why.strip()}); running with --device=cpu",
                      file=stderr)
                state.use_device = False
                stats.engine_fallbacks += 1
        if not state.use_device:
            from pwasm_tpu.utils.jaxcompat import pin_cpu_platform
            pin_cpu_platform()
        else:
            from pwasm_tpu.ops import enable_compilation_cache
            cache_dir = opts.get("compile-cache-dir")
            if not isinstance(cache_dir, str) or not cache_dir:
                cache_dir = getattr(warm, "compile_cache_dir", None) \
                    if warm is not None else None
            enable_compilation_cache(cache_dir)
        from pwasm_tpu.cli import _lane_device_scope
        stack.enter_context(_lane_device_scope(
            SimpleNamespace(device="tpu" if state.use_device
                            else "cpu"), warm, stderr))
        state.mesh = lane_span_mesh(state.use_device, warm, stderr,
                                    cfg.verbose)

    def admit(rec_text: str):
        try:
            name, seq = parse_record(rec_text)
        except ValueError as e:
            raise PwasmError(f"Error: {e} (streamed target)!\n")
        seq = seq.upper()
        if not seq:
            raise PwasmError(
                f"Error: could not retrieve sequence for {name} "
                "(target)!\n")
        tnames.append(name)
        ts.append(seq)
        tlens.append(len(seq))
        if store is not None:
            t_digs.append(record_digest(name, seq))
        pending.append(len(tnames) - 1)
        prog["targets_in"] += 1

    def score_batch(batch: list) -> bool:
        """Score one arrival batch; False when the deadline preempts
        mid-batch (whole per-CDS groups stay atomic either way)."""
        need: dict[int, tuple] = {}
        for qi in range(len(qs)):
            if store is not None:
                owed = []
                for i, g in enumerate(batch):
                    got = known[qi].get(t_digs[g])
                    if got is None:
                        owed.append(i)
                    else:
                        rows[qi][g] = got
                owed = tuple(owed)
            else:
                owed = tuple(range(len(batch)))
            if owed:
                need[qi] = owed
        owed_sets = [need.get(qi, ()) for qi in range(len(qs))]
        prog["targets_reused"] += sum(
            1 for i in range(len(batch))
            if all(i not in o for o in owed_sets))
        prog["pairs_reused"] += len(batch) * len(qs) \
            - sum(len(o) for o in owed_sets)
        groups: dict[tuple, list] = {}
        for qi, owed in need.items():
            groups.setdefault(owed, []).append(qi)
        from pwasm_tpu.parallel.many2many import \
            many2many_scores_ragged
        for idxs, qis in groups.items():
            if cfg.deadline_s is not None and \
                    time.monotonic() - t0_mono >= cfg.deadline_s:
                state.preempted = True
                return False
            ensure_engine()
            scores = many2many_scores_ragged(
                [qs[qi] for qi in qis],
                [ts[batch[i]] for i in idxs], band=cfg.band,
                mesh=state.mesh, supervisor=supervisor)
            for k, qi in enumerate(qis):
                for j, i in enumerate(idxs):
                    g = batch[i]
                    sc = int(scores[k][j])
                    rows[qi][g] = sc
                    if store is not None:
                        known[qi][t_digs[g]] = sc
            prog["pairs_dispatched"] += len(qis) * len(idxs)
            stats.aligned_bases += sum(
                tlens[batch[i]] for i in idxs) * len(qis)
        prog["targets_scored"] += len(batch)
        prog["batches"] += 1
        publish()
        return True

    def flush_pending() -> bool:
        if not pending:
            return True
        batch = list(pending)
        del pending[:]
        return score_batch(batch)

    asm = FastaAssembler()
    drained_early = False
    try:
        if input_stream is not None:
            publish()
            for line in input_stream:
                for rec in asm.feed(line + "\n"):
                    admit(rec)
                # dispatch boundary: feed drained (the arrival batch
                # is whatever accumulated) or the hot-stream cap hit
                if pending and (
                        getattr(input_stream, "buffered", 0) == 0
                        or len(pending) >= MAX_ARRIVAL_BATCH):
                    if not flush_pending():
                        break
                publish()
            drain = getattr(input_stream, "_drain", None)
            if drain is not None and drain.requested \
                    and not getattr(input_stream, "ended", True):
                drained_early = True    # idle/drain preemption: the
                #   stream path's resumable-75 contract
        else:
            try:
                with open(str(positional[0]), "r",
                          encoding="utf-8",
                          errors="replace") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        for rec in asm.feed(chunk):
                            admit(rec)
                        if len(pending) >= MAX_ARRIVAL_BATCH:
                            if not flush_pending():
                                break
            except OSError:
                raise PwasmError(
                    f"Error: invalid FASTA file {positional[0]} !\n")
        if not state.preempted and not drained_early:
            for rec in asm.finish():
                admit(rec)
            flush_pending()
        if input_stream is None and not tnames:
            raise PwasmError(
                f"Error: invalid FASTA file {positional[0]} !\n")
    finally:
        stack.close()

    # honest accounting: only dispatched pairs count as alignments;
    # family-pool splices ride in as bytes
    stats.lines = prog["pairs_dispatched"]
    stats.alignments = prog["pairs_dispatched"]
    stats.device_batches = 0

    def insert_sections(final: bool) -> None:
        # cache insert at per-CDS granularity over whatever subset of
        # targets each query has fully scored: the entry's key is
        # EXACTLY the one-shot section key for that target (sub)set,
        # and the m2m family extras donate every (digest, score) pair
        # to future sessions — this is both the incremental skip pool
        # and the deadline resume mechanism
        if store is None:
            return
        import hashlib

        from pwasm_tpu.service.cache import (m2m_family_key,
                                             section_key)
        for qi in range(len(qs)):
            gs = sorted(rows[qi])
            if not gs or (final and len(gs) != len(tnames)):
                continue
            th = hashlib.sha256()
            for g in gs:
                th.update(t_digs[g].encode())
            skey = section_key(q_digs[qi], th.hexdigest(), cfg.band)
            row = [rows[qi][g] for g in gs]
            sec = format_sections(
                [qnames[qi]], [len(qs[qi])],
                [tnames[g] for g in gs], [tlens[g] for g in gs],
                [row], NEG).encode("utf-8")
            sm = format_summary(
                [qnames[qi]], [tnames[g] for g in gs], [row],
                NEG).encode("utf-8")
            extra = {"m2m": {
                "family": m2m_family_key(q_digs[qi], cfg.band),
                "targets": [[t_digs[g], rows[qi][g]] for g in gs]}}
            store.insert(skey, {"o": sec, "s": sm}, extra=extra)
        if prog["pairs_reused"]:
            store.note_delta(
                prog["pairs_reused"],
                prog["pairs_reused"] + prog["pairs_dispatched"])

    from pwasm_tpu.ops.banded_dp import NEG

    if state.preempted or drained_early:
        stats.preempted = True
        insert_sections(final=False)
        if state.preempted:
            reason = (f"deadline_exceeded: --deadline-s="
                      f"{cfg.deadline_s:g} budget spent")
            drain = getattr(warm, "drain", None) \
                if warm is not None else None
            if drain is not None and not drain.requested:
                drain.request(reason)
        else:
            reason = "stream drained before stream-end"
        print(f"Warning: m2m-stream preempted ({reason}); "
              f"{prog['targets_scored']} of {prog['targets_in']} "
              "target(s) scored"
              + (" and cached — resubmit to continue"
                 if store is not None else ""), file=stderr)
        supervisor.finalize_stats()
        if warm is not None:
            warm.supervisor_state = {
                k: v for k, v in supervisor.export_state().items()
                if k != "fault_calls"}
        publish()
        _write_stats(opts, stats, prog)
        return EXIT_PREEMPTED

    if cfg.verbose:
        print(f"m2m-stream: {prog['targets_in']} target(s) in "
              f"{prog['batches']} arrival batch(es), "
              f"{prog['pairs_dispatched']} pair(s) dispatched, "
              f"{prog['pairs_reused']} spliced from the family pool",
              file=stderr)

    sections: list = []
    sums: list = []
    for qi in range(len(qs)):
        row = [rows[qi][g] for g in range(len(tnames))]
        sections.append(format_sections(
            [qnames[qi]], [len(qs[qi])], tnames, tlens, [row],
            NEG).encode("utf-8"))
        sums.append(format_summary([qnames[qi]], tnames, [row],
                                   NEG).encode("utf-8"))
        prog["sections_emitted"] += 1
    insert_sections(final=True)

    body = b"".join(sections)
    if "o" in opts:
        try:
            with open(str(opts["o"]), "wb") as f:
                f.write(body)
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['o']} for writing!\n")
    else:
        stdout.write(body.decode("utf-8"))
    if "s" in opts:
        try:
            with open(str(opts["s"]), "wb") as f:
                f.write(b"".join(sums))
        except OSError:
            raise PwasmError(
                f"Cannot open file {opts['s']} for writing!\n")
    supervisor.finalize_stats()
    if warm is not None:
        warm.supervisor_state = {
            k: v for k, v in supervisor.export_state().items()
            if k != "fault_calls"}
    prog["done"] = True
    publish()
    _write_stats(opts, stats, prog)
    if cfg.verbose:
        print(stats.brief(), file=stderr)
    return 0


def _write_stats(opts: dict, stats, prog: dict) -> None:
    """The versioned ``--stats`` JSON plus an additive ``m2m`` block
    (`fold_run_stats` ignores unknown keys by contract) — the bench
    incremental-ratio leg and the scatter merge read it."""
    if "stats" not in opts:
        return
    import json
    d = stats.as_dict()
    d["m2m"] = {k: v for k, v in prog.items() if k != "done"}
    try:
        with open(str(opts["stats"]), "w") as f:
            json.dump(d, f)
            f.write("\n")
    except OSError:
        raise PwasmError(
            f"Cannot open file {opts['stats']} for writing!\n")
