"""Router-side scatter/merge bookkeeping for fleet-wide m2m streams.

Pure data structures (no sockets, no jax): the router partitions the
arriving target-record stream across member sub-streams round-robin
(:class:`ScatterState` — the affinity-ordered member list decides who
sub 0 is), remembers each record's global arrival index, and at the
end splices the per-member section FRAGMENTS back into one report in
global arrival order (:func:`merge_fragments`) — byte-identical to one
un-scattered run over the same stream, because every fragment row is
spliced verbatim and only headers/summary (which depend on the total
target count) are re-rendered.

Member death re-partitions wholesale: the dead sub's records are
replayed — in their original relative order — into a fresh sub-stream
on a survivor (``kill``/``adopt``), so the positional row↔record
mapping survives failover unchanged.
"""

from __future__ import annotations


class ScatterState:
    """Record→sub assignment with arrival-order bookkeeping."""

    def __init__(self):
        self.orders: list[list[int]] = []  # per sub: global record
        self.live: list[bool] = []         # indices in send order
        self.nrec = 0
        self._rr = 0

    def add_sub(self) -> int:
        self.orders.append([])
        self.live.append(True)
        return len(self.orders) - 1

    def live_subs(self) -> list[int]:
        return [k for k, ok in enumerate(self.live) if ok]

    def assign(self) -> tuple[int, int]:
        """Admit the next arriving record; return ``(gidx, sub)``.

        Round-robin over the CURRENTLY live subs in index order —
        deterministic given the arrival order and the death history.
        """
        alive = self.live_subs()
        if not alive:
            raise ValueError("no live subs to assign to")
        gidx = self.nrec
        self.nrec += 1
        sub = alive[self._rr % len(alive)]
        self._rr += 1
        self.orders[sub].append(gidx)
        return gidx, sub

    def kill(self, sub: int) -> list[int]:
        """Mark ``sub`` dead; return the records it owned (in send
        order) for wholesale replay into a replacement sub."""
        self.live[sub] = False
        return list(self.orders[sub])

    def adopt(self, sub: int, order: list[int]) -> None:
        """A fresh replacement sub inherits a dead sub's records."""
        if self.orders[sub]:
            raise ValueError("adopting sub already owns records")
        self.orders[sub] = list(order)


def rewrite_out_args(args: list, o=None, s=None,
                     strip=("stats",)) -> list:
    """Rewrite a stream job's argv for one member sub-stream: fragment
    ``-o``/``-s`` paths in, per-client ``--stats`` out (each member
    writes its own; the router merges)."""
    out: list = []
    i = 0
    repl = {"-o": o, "-s": s}
    strip_eq = tuple(f"--{name}=" for name in strip)
    strip_lone = tuple(f"--{name}" for name in strip)
    while i < len(args):
        a = args[i]
        if a in repl and repl[a] is not None and i + 1 < len(args):
            out.extend([a, repl[a]])
            i += 2
            continue
        if isinstance(a, str) and (a.startswith(strip_eq)
                                   or a in strip_lone):
            i += 2 if a in strip_lone and i + 1 < len(args) \
                and not str(args[i + 1]).startswith("-") else 1
            continue
        out.append(a)
        i += 1
    return out


def _parse_fragment(data: bytes):
    """Parse one member's section-report fragment into
    ``[(header_fields, [row_bytes, ...]), ...]`` per query — rows kept
    as raw bytes so the merge splices them verbatim."""
    secs: list = []
    for ln in data.split(b"\n"):
        if not ln:
            continue
        if ln.startswith(b">"):
            fields = ln[1:].split(b"\t")
            if len(fields) != 3:
                raise ValueError(
                    f"malformed section header: {ln[:60]!r}")
            secs.append((fields, []))
        else:
            if not secs:
                raise ValueError("fragment row before any header")
            secs[-1][1].append(ln)
    return secs


def merge_fragments(fragments: list, orders: list, total: int,
                    summary: bool = False):
    """Splice per-member section fragments into ONE report in global
    arrival order.

    ``fragments[k]`` is the raw ``-o`` bytes member ``k`` emitted for
    the records in ``orders[k]`` (same index space, live subs only);
    ``total`` is the stream's total record count.  Returns the merged
    report bytes, or ``(report, summary)`` when ``summary`` is true —
    the summary is re-derived from the spliced rows with exactly the
    ``format_summary`` rendering, since best/sum depend on the whole
    row, not any one fragment.
    """
    if len(fragments) != len(orders):
        raise ValueError("fragments/orders length mismatch")
    parsed = [_parse_fragment(f) for f in fragments]
    nq = {len(p) for p in parsed}
    if len(nq) > 1:
        raise ValueError(f"fragments disagree on query count: {nq}")
    out: list = []
    sums: list = []
    for qi in range(nq.pop() if nq else 0):
        name = qlen = None
        rows: dict[int, bytes] = {}
        for k, p in enumerate(parsed):
            fields, frag_rows = p[qi]
            if name is None:
                name, qlen = fields[0], fields[1]
            elif (name, qlen) != (fields[0], fields[1]):
                raise ValueError(
                    f"fragments disagree on query {qi}: "
                    f"{name!r} vs {fields[0]!r}")
            if len(frag_rows) != len(orders[k]):
                raise ValueError(
                    f"fragment {k} query {qi}: {len(frag_rows)} "
                    f"row(s) for {len(orders[k])} record(s)")
            for gidx, row in zip(orders[k], frag_rows):
                rows[gidx] = row
        if len(rows) != total:
            raise ValueError(
                f"query {qi}: merged {len(rows)} of {total} row(s)")
        out.append(b">%s\t%s\t%d\n" % (name, qlen, total))
        merged = [rows[g] for g in range(total)]
        for row in merged:
            out.append(row + b"\n")
        if summary:
            sums.append(_summarize(name, merged, total))
    report = b"".join(out)
    return (report, b"".join(sums)) if summary else report


def _summarize(qname: bytes, rows: list, total: int) -> bytes:
    """Re-render one query's summary line from its merged rows —
    byte-for-byte the ``stream/multicds.format_summary`` contract
    (ties break to arrival order; all-``.`` reports ``.  .  0``)."""
    live: list = []
    for ti, row in enumerate(rows):
        fields = row.split(b"\t")
        if len(fields) != 3:
            raise ValueError(f"malformed section row: {row[:60]!r}")
        if fields[2] != b".":
            live.append((int(fields[2]), ti, fields[0]))
    if live:
        best, bi, bname = max(live, key=lambda p: (p[0], -p[1]))
        tot = sum(v for v, _t, _n in live)
        return b"%s\t%d\t%s\t%d\t%d\n" % (qname, total, bname,
                                          best, tot)
    return b"%s\t%d\t.\t.\t0\n" % (qname, total)
