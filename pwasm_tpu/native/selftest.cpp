// Sanitizer self-test for the native host core — the new framework's
// analog of the reference's memcheck build (SURVEY.md §4/§5:
// Makefile:30-47 compiles with -fsanitize=address,undefined).  Links
// fastparse.cpp directly and exercises every exported entry point with
// known inputs + asserts, so `make memcheck` in this directory gives the
// same "run under ASan/UBSan and see nothing" signal the reference's
// sanitizer targets give.  Build/run: make -C pwasm_tpu/native memcheck

#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
int pw_extract(const char*, const char*, const uint8_t*, int32_t, int32_t,
               int32_t, int32_t, int32_t, int32_t, int32_t, int32_t,
               uint8_t*, int32_t, int32_t*, int32_t, uint8_t*, int32_t,
               int32_t*, int32_t, int32_t*, int32_t*);
int32_t pw_banded_gotoh(const int8_t*, int32_t, const int8_t*, int32_t,
                        int32_t, int32_t, int32_t, int32_t, int32_t,
                        int32_t);
void pw_banded_gotoh_batch(const int8_t*, int32_t, const int8_t*,
                           const int32_t*, int32_t, int32_t, int32_t,
                           int32_t, int32_t, int32_t, int32_t, int32_t,
                           int32_t*);
void pw_consensus_vote(const int8_t*, int32_t, int32_t, uint8_t*);
void pw_consensus_vote_counts(const int32_t*, const int32_t*, int32_t,
                              uint8_t*);
int64_t pw_fasta_index(const char*, int64_t*, int64_t, uint8_t*, int64_t);
int64_t pw_fasta_fetch(const char*, int64_t, int64_t, uint8_t*);
void pw_encode_codes(const uint8_t*, int64_t, int8_t*);
void pw_pack_2bit(const int8_t*, int64_t, uint8_t*);
void pw_unpack_2bit(const uint8_t*, int64_t, int8_t*);
int64_t pw_gotoh_traceback(const int8_t*, int64_t, const int8_t*, int64_t,
                           int32_t, int32_t, int32_t, int32_t, int8_t*,
                           int64_t*);
}

static void test_extract() {
  // ref ACGTACGTAC, one substitution at ref pos 3 (t->a over ref T)
  const char* cs = ":3*at:6";
  const char* cigar = "10M";
  const uint8_t* ref = (const uint8_t*)"ACGTACGTAC";
  uint8_t tseq[64];
  int32_t ev[200];
  uint8_t arena[256];
  int32_t gaps[64];
  int32_t sizes[5], err[2];
  int rc = pw_extract(cs, cigar, ref, 10, 0, 0, 10, 0, 10, 0, 10, tseq,
                      64, ev, 200, arena, 256, gaps, 64, sizes, err);
  assert(rc == 0);
  assert(sizes[0] == 10);            // reconstructed target length
  assert(sizes[1] == 1);             // one S event
  assert(memcmp(tseq, "ACGaACGTAC", 10) == 0);  // sub stays lowercase
  // base-mismatch error path (cs says ref base is g, ref has T)
  rc = pw_extract(":3*ga:6", cigar, ref, 10, 0, 0, 10, 0, 10, 0, 10,
                  tseq, 64, ev, 200, arena, 256, gaps, 64, sizes, err);
  assert(rc == 2);
}

static void test_gotoh() {
  int8_t q[8] = {0, 1, 2, 3, 0, 1, 2, 3};
  int8_t t[12] = {0, 1, 2, 3, 0, 1, 2, 3, 0, 0, 0, 0};
  int32_t sc = pw_banded_gotoh(q, 8, t, 8, 8, -4, 2, 4, 4, 2);
  assert(sc == 16);  // 8 matches x 2
  int32_t out[2];
  int32_t t_lens[2] = {8, 8};
  int8_t ts[2 * 12];
  memcpy(ts, t, 12);
  memcpy(ts + 12, t, 12);
  pw_banded_gotoh_batch(q, 8, ts, t_lens, 2, 12, 8, -4, 2, 4, 4, 2, out);
  assert(out[0] == 16 && out[1] == 16);
}

static void test_gotoh_traceback() {
  // q ACGTACGT vs t with one inserted base: 8 diagonals + 1 Iy
  int8_t q[8] = {0, 1, 2, 3, 0, 1, 2, 3};
  int8_t t[9] = {0, 1, 2, 2, 3, 0, 1, 2, 3};
  int8_t ops[17];
  int64_t score = 0;
  int64_t k = pw_gotoh_traceback(q, 8, t, 9, 2, 4, 4, 2, ops, &score);
  assert(k == 9);
  assert(score == 8 * 2 - (4 + 2));  // 8 matches - one 1-base gap
  int diag = 0, iy = 0;
  for (int64_t i = 0; i < k; ++i) {
    if (ops[i] == 1) ++diag;
    if (ops[i] == 3) ++iy;
  }
  assert(diag == 8 && iy == 1);
  // degenerate: empty query -> all Iy
  k = pw_gotoh_traceback(q, 0, t, 3, 2, 4, 4, 2, ops, &score);
  assert(k == 3 && ops[0] == 3 && score == -(4 + 2) - 2 * 2);
}

static void test_consensus() {
  // 3-deep pileup over 4 columns; col 2 ties A with '-' -> A wins;
  // col 3 ties N with '-' -> '-' wins
  int8_t p[3 * 4] = {0, 1, 0, 4,
                     0, 1, 5, 5,
                     1, 1, 7, 7};  // 7 = pad, contributes nothing
  uint8_t out[4];
  pw_consensus_vote(p, 3, 4, out);
  assert(out[0] == 'A' && out[1] == 'C' && out[2] == 'A' &&
         out[3] == '-');
  int32_t counts[2 * 6] = {0, 0, 0, 0, 0, 0,
                           1, 1, 0, 0, 0, 0};
  int32_t layers[2] = {0, 2};
  pw_consensus_vote_counts(counts, layers, 2, out);
  assert(out[0] == 0 && out[1] == 'A');  // zero coverage -> 0
}

static void test_fasta() {
  char path[] = "/tmp/pwasm_selftest_XXXXXX";
  int fd = mkstemp(path);
  assert(fd >= 0);
  FILE* f = fdopen(fd, "w");
  fputs(">one desc\nACGT\nAC\n>two\r\nGG\r\n", f);
  fclose(f);
  int64_t entries[2 * 8];
  uint8_t arena[64];
  int64_t n = pw_fasta_index(path, entries, 2, arena, 64);
  assert(n == 2);
  assert(entries[1] == 3 && memcmp(arena, "one", 3) == 0);
  assert(entries[2] == 6);  // seqlen of record one
  // line geometry: record one wraps 4 then 2 bases at width 5 — a
  // short non-final... no: 'AC' IS final, so uniform with lb=4, lw=5
  assert(entries[5] == 4 && entries[6] == 5 && entries[7] == 1);
  // record two: one CRLF line, GG: lb=2, lw=4, uniform
  assert(entries[8 + 5] == 2 && entries[8 + 6] == 4
         && entries[8 + 7] == 1);
  uint8_t buf[32];
  int64_t got = pw_fasta_fetch(path, entries[3], entries[4], buf);
  assert(got == 6 && memcmp(buf, "ACGTAC", 6) == 0);
  remove(path);
}

static void test_pack() {
  const uint8_t* seq = (const uint8_t*)"ACGTacgtNn-*";
  int8_t codes[12];
  pw_encode_codes(seq, 12, codes);
  const int8_t expect[12] = {0, 1, 2, 3, 0, 1, 2, 3, 4, 4, 5, 5};
  assert(memcmp(codes, expect, 12) == 0);
  int8_t pure[9] = {0, 1, 2, 3, 3, 2, 1, 0, 2};
  uint8_t packed[3];
  pw_pack_2bit(pure, 9, packed);
  int8_t back[9];
  pw_unpack_2bit(packed, 9, back);
  assert(memcmp(pure, back, 9) == 0);
}

int main() {
  test_extract();
  test_gotoh();
  test_gotoh_traceback();
  test_consensus();
  test_fasta();
  test_pack();
  puts("native selftest OK");
  return 0;
}
