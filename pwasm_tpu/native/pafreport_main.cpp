// pwasm-tpu native CLI: the pure-C++ `--device=cpu` pafreport binary.
//
// This is the SURVEY.md §2.4.7-8 / §7.3 deliverable: a standalone native
// program with the reference CLI's surface (pafreport.cpp:175-460) whose
// report (-o), summary (-s) and warning output are byte-identical to the
// Python CLI's CPU path (pwasm_tpu/cli.py + report/diff_report.py), which
// is itself golden-locked against the reference's behavior spec.  The
// parsing/extraction core is shared with the ctypes library (fastparse.cpp
// linked into this binary); the analysis layer below is the C++ twin of
// report/diff_report.py: getRefContext / hpolyCheck / mmotifCheck /
// predictImpact / printDiffInfo (reference: pafreport.cpp:721-955).
//
// Parity is enforced by tests/test_native_cli.py: report/summary bytes,
// stderr warnings and exit codes against the Python CLI over the shared
// synthesizer fixtures.  The --device=tpu path stays in the Python CLI
// (this binary rejects it with a pointer there); MSA outputs are handled
// by pafreport_msa.cpp (phase 2) when linked, else rejected.
//
// Parity scope: ASCII inputs (the DNA/PAF domain).  On non-UTF-8 input
// bytes the Python CLI's text-mode reader raises UnicodeDecodeError
// while this binary passes raw bytes through — byte-for-byte parity is
// defined over valid ASCII FASTA/PAF only.

#include <sys/stat.h>
#include <unistd.h>

#include <array>
#include <cctype>
#include <cmath>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fstream>
#include <iostream>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

#include "pafreport_msa.h"
#include "pafreport_util.h"

// ---- shared native core (fastparse.cpp, linked into this binary)
extern "C" {
int pw_extract(const char* cs, const char* cigar, const uint8_t* ref,
               int32_t ref_len, int32_t offset, int32_t reverse,
               int32_t r_len, int32_t t_alnstart, int32_t t_alnend,
               int32_t r_alnstart, int32_t r_alnend, uint8_t* tseq_out,
               int32_t tseq_cap, int32_t* ev_out, int32_t ev_cap,
               uint8_t* arena, int32_t arena_cap, int32_t* gaps_out,
               int32_t gap_cap, int32_t* out_sizes, int32_t* err_info);
int64_t pw_fasta_index(const char* path, int64_t* entries, int64_t ent_cap,
                       uint8_t* name_arena, int64_t arena_cap);
int64_t pw_fasta_fetch(const char* path, int64_t seq_start, int64_t end,
                       uint8_t* out);
}

namespace {

constexpr int EV_FIELDS = 10;
constexpr int MAX_EVLEN = 12;  // display truncation (pafreport.cpp:919)
constexpr long AUTO_FULLGENOME_FASTA_BYTES = 120000;  // quirk §2.5.7

const char* USAGE =
    "Usage:\n"
    " pafreport <paf_with_cg_cs> -r <refseq.fa> [-s <summary.txt>]\n"
    "    [-o <diff_report.dfa>][-w <outfile.mfa>] [-G|-F|-C|-N]\n"
    "    [--device=cpu] [--motifs=FILE]\n"
    "\n"
    "   Native (pure C++) pafreport binary: the --device=cpu path of the\n"
    "   pwasm-tpu framework, byte-identical to `python -m pwasm_tpu.cli`\n"
    "   on the report/summary outputs.  Device execution (--device=tpu,\n"
    "   --shard, --realign, --profile) lives in the Python CLI.\n"
    "\n"
    "   <paf_with_cg_cs> is the input PAF file with high quality query\n"
    "      sequence(s) aligned to many target sequences using minimap2 --cs\n"
    "   -r provide the fasta file with query sequence(s) (required)\n"
    "   -o write difference data for each alignment into <diff_report.dfa>\n"
    "   -s write event summary counts into <summary.txt>\n"
    "   -w write MSA as multifasta into <outfile.mfa>\n"
    "   -G gene CDS analysis mode (default for query<100K; assumes -C)\n"
    "   -F full genome alignment mode (default for query>100Kb; assumes -N)\n"
    "   -C perform codon impact analysis\n"
    "   -N skip codon impact analysis\n"
    "   --ace=FILE  write the refined MSA as an ACE contig (consensus)\n"
    "   --info=FILE write the refined MSA as a contig-info table\n"
    "   --cons=FILE write the consensus sequence as FASTA\n"
    "   --remove-cons-gaps  drop all-gap consensus columns during\n"
    "               refinement\n"
    "   --no-refine-clip    skip the X-drop clipping refinement pass\n"
    "   --motifs=FILE       load the methylation-motif table from FILE\n"
    "   --skip-bad-lines    warn and continue on malformed PAF lines\n"
    "   --resume    append to an existing -o report, skipping alignments\n"
    "               already emitted (a -s summary then covers only the\n"
    "               resumed portion)\n"
    "   --stats=FILE        write run statistics as one JSON object\n";

using pwnative::GapSeq;
using pwnative::LineReader;
using pwnative::Msa;
using pwnative::PwErr;
using pwnative::revcomp;
using pwnative::sformat;
using pwnative::upper_inplace;

// Standard genetic code, index 16*c0 + 4*c1 + c2 with A0 C1 G2 T3
// (stop='.', anything ambiguous/short='X') — same table as core/dna.py.
const char kCodonLut[65] =
    "KNKNTTTTRSRSIIMIQHQHPPPPRRRRLLLLEDEDAAAAGGGGVVVV.Y.YSSSS.CWCLFLF";

inline int base_code4(char c) {
  switch (toupper((unsigned char)c)) {
    case 'A': return 0;
    case 'C': return 1;
    case 'G': return 2;
    case 'T': case 'U': return 3;
    default:  return -1;
  }
}

// translate_codon(seq, pos): 'X' if fewer than 3 bytes remain or any
// base is ambiguous (matches core/dna.py translate_codon).
char translate_codon(const std::string& seq, long pos) {
  if (pos < 0 || pos + 3 > (long)seq.size()) return 'X';
  int c0 = base_code4(seq[pos]), c1 = base_code4(seq[pos + 1]),
      c2 = base_code4(seq[pos + 2]);
  if (c0 < 0 || c1 < 0 || c2 < 0) return 'X';
  return kCodonLut[16 * c0 + 4 * c1 + c2];
}

// ---------------------------------------------------------------------------
// PAF record model — native twin of core/paf.py (reference: AlnInfo +
// tag scan, pafreport.cpp:54-88,492-521).
// ---------------------------------------------------------------------------
struct AlnInfo {
  int reverse = 2;
  std::string r_id;
  long r_len = 0, r_alnstart = 0, r_alnend = 0;
  std::string t_id;
  long t_len = 0, t_alnstart = 0, t_alnend = 0;
};

struct PafRecord {
  AlnInfo al;
  std::string line;
  long edist = -1;    // NM:i:
  long alnscore = 0;  // AS:i:
  bool has_cigar = false, has_cs = false;
  std::string cigar, cs;
};

long c_atoi(const std::string& s) { return atol(s.c_str()); }

std::vector<std::string> split_tabs(const std::string& line) {
  std::vector<std::string> out;
  size_t start = 0;
  for (;;) {
    size_t tab = line.find('\t', start);
    if (tab == std::string::npos) {
      out.push_back(line.substr(start));
      return out;
    }
    out.push_back(line.substr(start, tab - start));
    start = tab + 1;
  }
}

bool starts_with(const std::string& s, const char* pfx) {
  return s.compare(0, strlen(pfx), pfx) == 0;
}

PafRecord parse_paf_line(const std::string& line) {
  std::vector<std::string> f = split_tabs(line);
  if (f.size() < 15)
    throw PwErr(sformat("Error: invalid PAF fline (num. fields=%zu):\n%s\n",
                        f.size(), line.c_str()));
  PafRecord rec;
  rec.line = line;
  rec.al.reverse = f[4] == "-" ? 1 : 0;
  rec.al.r_id = f[0];
  rec.al.r_len = c_atoi(f[1]);
  rec.al.r_alnstart = c_atoi(f[2]);
  rec.al.r_alnend = c_atoi(f[3]);
  rec.al.t_id = f[5];
  rec.al.t_len = c_atoi(f[6]);
  rec.al.t_alnstart = c_atoi(f[7]);
  rec.al.t_alnend = c_atoi(f[8]);
  int got = 0;
  const int gotall = 1 + 2 + 4 + 8;
  for (size_t k = 12; k < f.size(); ++k) {
    const std::string& s = f[k];
    if (starts_with(s, "NM:i:")) {
      rec.edist = c_atoi(s.substr(5));
      got |= 1;
    } else if (starts_with(s, "AS:i:")) {
      rec.alnscore = c_atoi(s.substr(5));
      got |= 2;
    } else if (starts_with(s, "cg:Z:")) {
      rec.cigar = s.substr(5);
      rec.has_cigar = true;
      got |= 4;
    } else if (starts_with(s, "cs:Z:")) {
      rec.cs = s.substr(5);
      rec.has_cs = true;
      got |= 8;
    }
    if (got == gotall) break;
  }
  return rec;
}

// ---------------------------------------------------------------------------
// Extraction via the shared pw_extract core; error messages identical to
// core/events.py constants (the Python wrapper formats the same codes,
// native/__init__.py:_raise_native_error).
// ---------------------------------------------------------------------------
struct DiffEvent {
  char evt;  // 'S' | 'I' | 'D'
  long evtlen, rloc, tloc;
  std::string bases, sub, tctx;
};

struct Extraction {
  std::string tseq;
  std::vector<DiffEvent> evs;
  std::vector<std::array<int32_t, 3>> gaps;  // (which, pos, len)
  long offset = 0;
  int n_softclip = 0;
};

void replay_softclip(int n, const std::string& line) {
  for (int k = 0; k < n; ++k)
    fprintf(stderr,
            "Warning: soft clipping shouldn't be found in this "
            "application!\n%s\n",
            line.c_str());
}

void validate_coords(const AlnInfo& al, const std::string& line) {
  if (!(0 <= al.r_alnstart && al.r_alnstart <= al.r_alnend &&
        al.r_alnend <= al.r_len && 0 <= al.t_alnstart &&
        al.t_alnstart <= al.t_alnend))
    throw PwErr(sformat(
        "Error: invalid alignment coordinates (q %ld-%ld/%ld, t %ld-%ld) "
        "at line:\n%s\n",
        al.r_alnstart, al.r_alnend, al.r_len, al.t_alnstart, al.t_alnend,
        line.c_str()));
}

[[noreturn]] void raise_extract_error(int rc, const int32_t* info,
                                      const PafRecord& rec,
                                      const std::string& refseq_aln) {
  const std::string& line = rec.line;
  const AlnInfo& al = rec.al;
  long a = info[0], b = info[1];
  switch (rc) {
    case 1:
      throw PwErr(sformat(
          "Error parsing cs string from line: %s (cs position: %s)\n",
          line.c_str(), rec.cs.substr((size_t)a).c_str()));
    case 2: {
      char refc =
          (a >= 0 && a < (long)refseq_aln.size()) ? refseq_aln[a] : '?';
      throw PwErr(sformat(
          "Error: base mismatch %c != qstr[%ld] (%c) at line\n%s\n",
          (char)b, a, refc, line.c_str()));
    }
    case 3:
      throw PwErr(sformat(
          "Error: spliced alignments not supported! at line:\n%s\n",
          line.c_str()));
    case 4:
      throw PwErr(sformat("Error: unhandled event at %s in cs, line:\n%s\n",
                          rec.cs.substr((size_t)a).c_str(), line.c_str()));
    case 5:
      throw PwErr(sformat(
          "Error parsing cigar string from line: %s (cigar position: %s)\n",
          line.c_str(), rec.cigar.substr((size_t)a).c_str()));
    case 6:
      throw PwErr(sformat("Error: unhandled cigar_op %c (len %ld) in %s\n",
                          (char)a, b, line.c_str()));
    case 7:
      throw PwErr(sformat(
          "Error: tseq alignment length mismatch (%ld vs %ld(%ld-%ld)) at "
          "line:%s\n",
          a, al.t_alnend - al.t_alnstart, al.t_alnend, al.t_alnstart,
          line.c_str()));
    case 8:
      throw PwErr(sformat(
          "Error: ref alignment length mismatch (%ld vs %ld-%ld) at "
          "line:%s\n",
          a, al.r_alnend, al.r_alnstart, line.c_str()));
    case 9:
      validate_coords(al, line);  // formats the exact message
      [[fallthrough]];
    default:
      throw PwErr(sformat("native extraction failed (code %d)\n", rc));
  }
}

Extraction extract_alignment(const PafRecord& rec,
                             const std::string& refseq_aln) {
  const AlnInfo& al = rec.al;
  validate_coords(al, rec.line);
  if (!rec.has_cigar || rec.cigar.empty())
    throw PwErr(sformat(
        "Error parsing cigar string from line: %s (cigar position: 0)\n",
        rec.line.c_str()));
  if (!rec.has_cs)
    throw PwErr(sformat(
        "Error parsing cs string from line: %s (cs position: 0)\n",
        rec.line.c_str()));
  long offset = al.r_alnstart;
  if (al.reverse) offset = al.r_len - al.r_alnend;
  long eff = al.t_alnend - al.t_alnstart;
  int32_t tseq_cap = (int32_t)(eff + 16);
  int32_t ev_cap = (int32_t)(EV_FIELDS * (rec.cs.size() + 4));
  int32_t arena_cap = (int32_t)(4 * (rec.cs.size() + 64));
  int32_t gap_cap = (int32_t)(3 * (rec.cigar.size() + 4));
  for (int attempt = 0; attempt < 3; ++attempt) {
    std::vector<uint8_t> tseq_buf(tseq_cap);
    std::vector<int32_t> ev_buf(ev_cap);
    std::vector<uint8_t> arena(arena_cap);
    std::vector<int32_t> gaps_buf(gap_cap);
    int32_t sizes[5] = {0, 0, 0, 0, 0};
    int32_t err_info[2] = {0, 0};
    int rc = pw_extract(
        rec.cs.c_str(), rec.cigar.c_str(),
        (const uint8_t*)refseq_aln.data(), (int32_t)refseq_aln.size(),
        (int32_t)offset, al.reverse, (int32_t)al.r_len,
        (int32_t)al.t_alnstart, (int32_t)al.t_alnend,
        (int32_t)al.r_alnstart, (int32_t)al.r_alnend, tseq_buf.data(),
        tseq_cap, ev_buf.data(), ev_cap, arena.data(), arena_cap,
        gaps_buf.data(), gap_cap, sizes, err_info);
    if (rc == 100) {  // grow buffers and retry
      tseq_cap *= 4;
      ev_cap *= 4;
      arena_cap *= 4;
      gap_cap *= 4;
      continue;
    }
    replay_softclip(sizes[4], rec.line);
    if (rc != 0) raise_extract_error(rc, err_info, rec, refseq_aln);
    Extraction ex;
    ex.offset = offset;
    ex.n_softclip = sizes[4];
    ex.tseq.assign((const char*)tseq_buf.data(), (size_t)sizes[0]);
    const char* evmap = "SID";
    for (int32_t k = 0; k < sizes[1]; ++k) {
      const int32_t* f = &ev_buf[(size_t)k * EV_FIELDS];
      DiffEvent e;
      e.evt = evmap[f[0]];
      e.rloc = f[1];
      e.tloc = f[2];
      e.evtlen = f[3];
      e.bases.assign((const char*)arena.data() + f[4], (size_t)f[5]);
      e.sub.assign((const char*)arena.data() + f[6], (size_t)f[7]);
      e.tctx.assign((const char*)arena.data() + f[8], (size_t)f[9]);
      ex.evs.push_back(std::move(e));
    }
    for (int32_t k = 0; k < sizes[3]; ++k)
      ex.gaps.push_back({gaps_buf[k * 3], gaps_buf[k * 3 + 1],
                         gaps_buf[k * 3 + 2]});
    return ex;
  }
  throw PwErr("native extraction buffers exhausted\n");
}

// ---------------------------------------------------------------------------
// FASTA access via the shared pw_fasta_index/pw_fasta_fetch core — the
// capability of gclib GFastaDb/GFaSeqGet (pafreport.cpp:255,346).
// Duplicate ids keep the first record, like core/fasta.py FastaFile.
// ---------------------------------------------------------------------------
class FastaDb {
 public:
  explicit FastaDb(const std::string& path) : path_(path) {
    int64_t ent_cap = 1024, arena_cap = 1 << 16;
    for (;;) {
      std::vector<int64_t> ents((size_t)ent_cap * 8);
      std::vector<uint8_t> arena((size_t)arena_cap);
      int64_t n = pw_fasta_index(path_.c_str(), ents.data(), ent_cap,
                                 arena.data(), arena_cap);
      if (n == -1)
        throw PwErr("Error: invalid FASTA file " + path_ + " !\n");
      if (n < -1) {  // buffers too small: grow to the reported need
        ent_cap = -(n + 2) + 16;
        arena_cap *= 8;
        continue;
      }
      for (int64_t k = 0; k < n; ++k) {
        const int64_t* e = &ents[(size_t)k * 8];
        std::string name((const char*)arena.data() + e[0], (size_t)e[1]);
        if (!byname_.count(name)) {
          byname_[name] = order_.size();
          order_.push_back({name, e[2], e[3], e[4]});
        }
      }
      break;
    }
    if (order_.empty())
      // parity: FastaFile._full_scan raises without a trailing newline
      throw PwErr("Error: invalid FASTA file " + path_ + " !");
  }

  bool fetch(const std::string& name, std::string& out) const {
    auto it = byname_.find(name);
    if (it == byname_.end()) return false;
    const Rec& r = order_[it->second];
    std::vector<uint8_t> buf((size_t)(r.end - r.start) + 1);
    int64_t w = pw_fasta_fetch(path_.c_str(), r.start, r.end, buf.data());
    if (w < 0) return false;
    out.assign((const char*)buf.data(), (size_t)w);
    return true;
  }

  size_t size() const { return order_.size(); }

  long file_size() const {
    struct stat st;
    if (stat(path_.c_str(), &st) != 0) return -1;
    return (long)st.st_size;
  }

 private:
  struct Rec {
    std::string name;
    int64_t seqlen, start, end;
  };
  std::string path_;
  std::vector<Rec> order_;
  std::unordered_map<std::string, size_t> byname_;
};

// ---------------------------------------------------------------------------
// Biology analysis — C++ twin of report/diff_report.py (reference:
// pafreport.cpp:721-955), byte-identical formatting.
// ---------------------------------------------------------------------------
struct RefCtx {
  std::string win;  // 9-base window, upper-case
  long loc;         // event offset within the window
};

// getRefContext (pafreport.cpp:721-733) including the wrong-sign
// right-edge quirk and the degenerate <9bp clamp (diff_report.py:27-48).
RefCtx get_ref_context(const std::string& refseq, long rloc) {
  long ctxstart = rloc - 4;
  long evtloc = 4;
  long n = (long)refseq.size();
  if (ctxstart < 0) {
    evtloc += ctxstart;
    ctxstart = 0;
  } else if (ctxstart + 8 >= n) {
    evtloc += n - ctxstart - 9;
    ctxstart = n - 9;
    if (ctxstart < 0) {
      evtloc += ctxstart;
      ctxstart = 0;
    }
  }
  long end = ctxstart + 9;
  if (end > n) end = n;
  std::string win =
      ctxstart < n ? refseq.substr((size_t)ctxstart, (size_t)(end - ctxstart))
                   : std::string();
  upper_inplace(win);
  return {win, evtloc};
}

// hpolyCheck (pafreport.cpp:735-748)
bool hpoly_check(const std::string& evtbases, const std::string& rctx,
                 long rctxloc) {
  if (evtbases.empty()) return false;
  for (size_t i = 1; i < evtbases.size(); ++i)
    if (evtbases[i] != evtbases[0]) return false;
  std::string cseed(4, evtbases[0]);
  size_t pos = rctx.find(cseed);
  if (pos == std::string::npos) return false;
  long l = (long)pos;
  return 0 <= l && l <= rctxloc && rctxloc <= l + 4;
}

// mmotifCheck (pafreport.cpp:751-763): first motif found anywhere wins.
std::string mmotif_check(const std::string& rctx,
                         const std::vector<std::string>& motifs) {
  for (const auto& m : motifs)
    if (rctx.find(m) != std::string::npos) return "motif " + m;
  return "";
}

// predictImpact (pafreport.cpp:801-883) with the GStr-capacity quirk:
// both sequences are the entire reference suffix from r_trloc
// (SURVEY.md §2.5.9; diff_report.py:74-136).
std::string predict_impact(const DiffEvent& di, const std::string& refseq,
                           long r_trloc) {
  std::string r_trseq = refseq.substr((size_t)r_trloc);
  std::string modseq = r_trseq;
  if (di.evt == 'S') {
    long aaofs = -1;
    std::vector<long> aamods;
    for (size_t i = 0; i < di.bases.size(); ++i) {
      long p = di.rloc - r_trloc + (long)i;
      char have = (p >= 0 && p < (long)modseq.size())
                      ? (char)toupper((unsigned char)modseq[(size_t)p])
                      : '\0';
      char want = (char)toupper((unsigned char)di.sub[i]);
      if (have != want)
        throw PwErr(sformat(
            "Error: modseq[%ld] not matching di.evtsub[%zu] !\n", p, i));
      modseq[(size_t)p] = di.bases[i];
      long ao = p / 3;
      if (ao != aaofs) {
        aaofs = ao;
        aamods.push_back(ao);
      }
    }
    std::string out;
    for (long ao : aamods) {
      char aa = translate_codon(r_trseq, ao * 3);
      char maa = translate_codon(modseq, ao * 3);
      if (aa != maa) {  // not a synonymous codon
        long aapos = ao + di.rloc / 3;
        std::string s = sformat("AA%ld|%c:%c", aapos, aa, maa);
        if (maa == '.') s += sformat("|premature stop at AA%ld", aapos);
        if (!out.empty()) out += ", ";
        out += s;
      }
    }
    return out.empty() ? "synonymous" : out;
  }
  long pos = di.rloc - r_trloc;
  if (di.evt == 'I') {
    size_t at = pos < 0 ? 0
                        : (pos > (long)modseq.size() ? modseq.size()
                                                     : (size_t)pos);
    modseq.insert(at, di.bases);
  } else if (di.evt == 'D') {
    size_t at = pos < 0 ? 0
                        : (pos > (long)modseq.size() ? modseq.size()
                                                     : (size_t)pos);
    size_t cnt = (size_t)di.evtlen;
    if (at + cnt > modseq.size()) cnt = modseq.size() - at;
    modseq.erase(at, cnt);
  } else {
    throw PwErr(sformat("Error: unrecognized editing event (%c)!\n",
                        di.evt));
  }
  // for I/D, look for a premature stop codon down the road
  int aamodc = 0;
  std::string aa4, maa4, txt;
  for (long i = 0; i + 2 < (long)modseq.size(); i += 3) {
    char aamod = translate_codon(modseq, i);
    if (aamod == '.') {
      txt = sformat("premature stop at AA%ld", 1 + (i + r_trloc) / 3);
      break;
    }
    if (i > 0 && aamodc < 4) {
      ++aamodc;
      if (i + 2 < (long)r_trseq.size()) aa4 += translate_codon(r_trseq, i);
      maa4 += aamod;
    }
  }
  if (txt.empty() && !aa4.empty() && !maa4.empty())
    txt = "frame shift " + aa4 + "+:" + maa4 + "+";
  return txt;
}

// Event summary counters — the reference's documented-but-unwritten -s
// output (quirk §2.5.1), implemented like diff_report.py Summary.
struct Summary {
  long alignments = 0, aligned_bases = 0;
  long ev_n[3] = {0, 0, 0};   // S, I, D counts
  long ev_b[3] = {0, 0, 0};   // S, I, D bases
  long cause_hpoly = 0, cause_motif = 0, cause_unknown = 0;
  long imp_syn = 0, imp_nonsyn = 0, imp_stop = 0, imp_frame = 0;

  void add_alignment(const AlnInfo& al) {
    ++alignments;
    aligned_bases += al.r_alnend - al.r_alnstart;
  }
  void add_event(const DiffEvent& di, const std::string& status,
                 const std::string& impact) {
    int k = di.evt == 'S' ? 0 : di.evt == 'I' ? 1 : 2;
    ++ev_n[k];
    ev_b[k] += di.evt != 'D' ? (long)di.bases.size() : di.evtlen;
    if (status == "homopolymer")
      ++cause_hpoly;
    else if (starts_with(status, "motif"))
      ++cause_motif;
    else
      ++cause_unknown;
    if (!impact.empty()) {
      if (impact.find("premature stop") != std::string::npos)
        ++imp_stop;
      else if (impact == "synonymous")
        ++imp_syn;
      else if (starts_with(impact, "frame shift"))
        ++imp_frame;
      else
        ++imp_nonsyn;
    }
  }
  void write(FILE* f) const {
    fprintf(f, "# pwasm-tpu event summary\n");
    fprintf(f, "alignments\t%ld\n", alignments);
    fprintf(f, "aligned_query_bases\t%ld\n", aligned_bases);
    fprintf(f, "events_total\t%ld\n", ev_n[0] + ev_n[1] + ev_n[2]);
    fprintf(f, "substitutions\t%ld\t%ld bases\n", ev_n[0], ev_b[0]);
    fprintf(f, "insertions\t%ld\t%ld bases\n", ev_n[1], ev_b[1]);
    fprintf(f, "deletions\t%ld\t%ld bases\n", ev_n[2], ev_b[2]);
    fprintf(f, "cause_homopolymer\t%ld\n", cause_hpoly);
    fprintf(f, "cause_motif\t%ld\n", cause_motif);
    fprintf(f, "cause_unknown\t%ld\n", cause_unknown);
    fprintf(f, "impact_synonymous\t%ld\n", imp_syn);
    fprintf(f, "impact_nonsynonymous\t%ld\n", imp_nonsyn);
    fprintf(f, "impact_premature_stop\t%ld\n", imp_stop);
    fprintf(f, "impact_frame_shift\t%ld\n", imp_frame);
  }
};

std::string truncate_display(const std::string& s) {
  if ((long)s.size() > MAX_EVLEN) return sformat("[%zu]", s.size());
  return s;
}

// printDiffInfo (pafreport.cpp:885-955): header + one TSV row per event.
void print_diff_info(FILE* f, const AlnInfo& al, long alnscore, long edist,
                     std::vector<DiffEvent>& evs, const std::string& rlabel,
                     const std::string& tlabel, const std::string& refseq,
                     bool skip_codan, const std::vector<std::string>& motifs,
                     Summary* summary) {
  // degenerate zero-length query: print "nan" like the Python CLI
  // (an unsigned quiet NaN; 0.0/0.0 would give "-nan" on x86)
  double cov = al.r_len ? (double)(al.r_alnend - al.r_alnstart) * 100.00 /
                              (double)al.r_len
                        : std::nan("");
  if (rlabel.empty())
    fprintf(f, ">%s coverage:%.2f score=%ld edit_distance=%ld\n",
            tlabel.c_str(), cov, alnscore, edist);
  else
    fprintf(f, ">%s--%s coverage:%.2f score=%ld edit_distance=%ld\n",
            rlabel.c_str(), tlabel.c_str(), cov, alnscore, edist);
  if (summary) summary->add_alignment(al);
  for (auto& di : evs) {
    upper_inplace(di.bases);  // printDiffInfo loop head (pafreport.cpp:895)
    long aapos = di.rloc / 3;
    char aa = translate_codon(refseq, 3 * aapos);
    aapos += 1;
    RefCtx ctx = get_ref_context(refseq, di.rloc);
    std::string status =
        hpoly_check(di.bases, ctx.win, ctx.loc) ? "homopolymer" : "";
    long r_trloc = 3 * (aapos - 2);  // start editing one codon before
    if (r_trloc < 0) r_trloc = 0;
    if (status.empty()) status = mmotif_check(ctx.win, motifs);
    std::string impact;
    if (!skip_codan) impact = predict_impact(di, refseq, r_trloc);
    if (status.empty()) status = "[unknown]";
    if (summary) summary->add_event(di, status, impact);
    std::string tcontext = di.tctx;
    if ((long)tcontext.size() > 10 + MAX_EVLEN)
      tcontext = di.tctx.substr(0, 5) +
                 sformat("[%zu]", di.tctx.size() - 10) +
                 di.tctx.substr(di.tctx.size() - 5);
    std::string eb = truncate_display(di.bases);
    std::string mid;
    if (di.evt == 'S')
      mid = truncate_display(di.sub) + ":" + eb;
    else if (di.evt == 'I')
      mid = ":" + eb;
    else
      mid = eb + ":";
    fprintf(f, "%c\t%ld\t%ld(%c)\t%s\t%ld\t%s\t%s\t%s\t%s\n", di.evt,
            di.rloc + 1, aapos, aa, mid.c_str(), di.tloc + 1,
            tcontext.c_str(), ctx.win.c_str(), status.c_str(),
            impact.c_str());
  }
}

// ---------------------------------------------------------------------------
// CLI argument parsing — GArgs-style semantics shared with cli.py:
// single-letter flags (joined or separated values) plus --long[=value];
// -d/-p/-m accept values that are never read (quirk §2.5.2).
// ---------------------------------------------------------------------------
const char* BOOL_FLAGS = "DGFCNvh";
const char* VALUE_FLAGS = "dprmowcs";

struct Opts {
  std::unordered_map<std::string, std::string> vals;  // valued options
  std::set<std::string> flags;                        // boolean presence
  std::vector<std::string> positional;

  bool has(const std::string& k) const {
    return flags.count(k) || vals.count(k);
  }
  bool is_bool(const std::string& k) const { return flags.count(k) > 0; }
  std::string get(const std::string& k, const std::string& dflt = "") const {
    auto it = vals.find(k);
    return it == vals.end() ? dflt : it->second;
  }
};

Opts parse_args(int argc, char** argv) {
  Opts o;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    if (starts_with(a, "--")) {
      size_t eq = a.find('=');
      if (eq != std::string::npos)
        o.vals[a.substr(2, eq - 2)] = a.substr(eq + 1);
      else
        o.flags.insert(a.substr(2));
    } else if (a.size() > 1 && a[0] == '-') {
      size_t j = 1;
      while (j < a.size()) {
        char ch = a[j];
        if (strchr(BOOL_FLAGS, ch)) {
          o.flags.insert(std::string(1, ch));
          ++j;
        } else if (strchr(VALUE_FLAGS, ch)) {
          if (j + 1 < a.size()) {
            o.vals[std::string(1, ch)] = a.substr(j + 1);
          } else {
            ++i;
            if (i >= argc)
              throw PwErr(sformat("%s\nInvalid argument: -%c\n", USAGE, ch));
            o.vals[std::string(1, ch)] = argv[i];
          }
          j = a.size();
        } else {
          throw PwErr(sformat("%s\nInvalid argument: %s\n", USAGE,
                              a.c_str()));
        }
      }
    } else {
      o.positional.push_back(a);
    }
  }
  return o;
}

// -c parsing (pafreport.cpp:217-240), messages as cli.py:_parse_clipmax.
double parse_clipmax(std::string s, bool verbose) {
  bool ispercent = !s.empty() && s.back() == '%';
  while (!s.empty() && s.back() == '%') s.pop_back();
  long c = atol(s.c_str());
  if (c <= 0)
    throw PwErr(sformat(
        "Error: invalid -c <clipmax> (%ld) option provided (must be a "
        "positive integer)!\n",
        c));
  if (ispercent && c > 99)
    throw PwErr(sformat(
        "Error: invalid percent value (%ld) for -c option  (must be an "
        "integer between 1 and 99)!\n",
        c));
  if (ispercent) {
    if (verbose)
      fprintf(stderr, "Percentual max clipping set to %ld%%\n", c);
    return (double)c / 100;
  }
  if (verbose) fprintf(stderr, "Max clipping set to %ld bases\n", c);
  return (double)c;
}

std::vector<std::string> load_motifs(const std::string& path) {
  // ASCII text, any readable file object (FIFOs/process substitution
  // work in the Python CLI, so they must here too) — only directories
  // are pre-rejected, because fopen would "open" one on Linux; same
  // error message as config.load_motifs + cli.py's handler
  struct stat st;
  if (stat(path.c_str(), &st) != 0 || S_ISDIR(st.st_mode))
    throw PwErr("Cannot open motif file " + path + "!\n");
  FILE* fp = fopen(path.c_str(), "rb");
  if (!fp) throw PwErr("Cannot open motif file " + path + "!\n");
  std::vector<std::string> out;
  LineReader reader(fp);  // universal newlines, like Python text mode
  std::string line;
  while (reader.next(line)) {
    for (unsigned char c : line)
      if (c >= 0x80) {  // non-ASCII content: parity with encoding="ascii"
        fclose(fp);
        throw PwErr("Cannot open motif file " + path + "!\n");
      }
    // strip whitespace, upper-case, skip comments (core/config.py)
    size_t b = line.find_first_not_of(" \t\r\n\v\f");
    if (b == std::string::npos) continue;
    size_t e = line.find_last_not_of(" \t\r\n\v\f");
    std::string m = line.substr(b, e - b + 1);
    upper_inplace(m);
    if (!m.empty() && m[0] != '#') out.push_back(m);
  }
  fclose(fp);
  return out;
}

struct RunStats {
  struct timespec t0;
  long lines = 0, alignments = 0, skipped_bad = 0, skipped_dedup = 0,
       skipped_self = 0, aligned_bases = 0, events = 0, msa_dropped = 0,
       resumed_past = 0;
  RunStats() { clock_gettime(CLOCK_MONOTONIC, &t0); }
  double wall_s() const {
    struct timespec t1;
    clock_gettime(CLOCK_MONOTONIC, &t1);
    return (double)(t1.tv_sec - t0.tv_sec) +
           (double)(t1.tv_nsec - t0.tv_nsec) * 1e-9;
  }
  void write(FILE* f) const {
    double w = wall_s();
    double rate = w > 0 ? (double)aligned_bases / w : 0.0;
    fprintf(f,
            "{\"lines\": %ld, \"alignments\": %ld, \"skipped_bad_lines\": "
            "%ld, \"skipped_duplicates\": %ld, \"skipped_self\": %ld, "
            "\"resumed_past\": %ld, \"aligned_bases\": %ld, \"events\": "
            "%ld, \"device_batches\": 0, \"fallback_batches\": 0, "
            "\"realigned\": 0, \"msa_dropped\": %ld, "
            "\"engine_fallbacks\": 0, \"wall_s\": %.3f, "
            "\"aligned_bases_per_s\": %.1f}\n",
            lines, alignments, skipped_bad, skipped_dedup, skipped_self,
            resumed_past, aligned_bases, events, msa_dropped, w, rate);
  }
};

struct Cfg {
  bool debug = false, verbose = false, fullgenome = false, gene_cds = false,
       skip_codan = false, skip_bad_lines = false,
       remove_cons_gaps = false, refine_clip = true;
  double clipmax = 0.0;
  std::vector<std::string> motifs = {"CCTGG", "CCAGG", "GATC", "GTAC"};
};

// Shared by the selftest hooks: fill a GapSeq's gap array from a
// comma-joined list, maintaining numgaps.
void parse_gap_list(const std::string& gs, GapSeq& s) {
  size_t start = 0, gi = 0;
  while (start <= gs.size() && gi < s.gaps.size()) {
    size_t comma = gs.find(',', start);
    std::string tok = gs.substr(
        start, comma == std::string::npos ? std::string::npos
                                          : comma - start);
    s.gaps[gi] = (int32_t)atol(tok.c_str());
    s.numgaps += s.gaps[gi];
    ++gi;
    if (comma == std::string::npos) break;
    start = comma + 1;
  }
}

// Hidden test hook: exercise the X-drop clip refinement with nonzero
// clips (unreachable from the CLI flow, where nothing sets clp5/clp3 —
// clipmax is parsed but evalClipping is never called, mirroring the
// reference).  Input: first line the consensus; then one line per case,
// tab-separated: name, revcompl, clp5, clp3, cpos, skip_dels,
// comma-joined gaps, bases.  Output: name\tclp5\tclp3 after refinement.
// tests/test_native_cli.py fuzzes this against the Python engine's
// transliterated reference walk (gapseq.py refine_clipping_scalar).
int run_refine_selftest(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) throw PwErr("Cannot open input file " + path + "!\n");
  LineReader reader(f);
  std::string cons;
  if (!reader.next(cons)) {
    fclose(f);
    throw PwErr("refine-selftest: empty input\n");
  }
  std::string line;
  while (reader.next(line)) {
    if (line.empty()) continue;
    std::vector<std::string> fields = split_tabs(line);
    if (fields.size() != 8)
      throw PwErr("refine-selftest: bad case line\n");
    GapSeq s(fields[0], fields[7]);
    s.revcompl = (int)atol(fields[1].c_str());
    s.clp5 = atol(fields[2].c_str());
    s.clp3 = atol(fields[3].c_str());
    long cpos = atol(fields[4].c_str());
    bool skip_dels = atol(fields[5].c_str()) != 0;
    parse_gap_list(fields[6], s);
    s.refine_clipping(cons, cpos, skip_dels);
    printf("%s\t%ld\t%ld\n", s.name.c_str(), s.clp5, s.clp3);
  }
  fclose(f);
  return 0;
}

// Hidden test hook for the clipping transaction (evalClipping/
// applyClipping, unreachable from the CLI flow like the reference,
// where clipmax is parsed but never consumed).  Input: line 1 the
// clipmax value; then SEQ lines (name, revcompl, offset, clp5, clp3,
// comma-joined gaps, seqlen) building one MSA in order, then EVAL
// lines (seq index, c5, c3).  Each EVAL gets a fresh transaction and
// applies on success; output per EVAL is "ok"/"rejected", then one
// final line per seq: name\tclp5\tclp3.  Fuzz-compared against the
// Python engine in tests/test_native_cli.py.
int run_clip_selftest(const std::string& path) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) throw PwErr("Cannot open input file " + path + "!\n");
  LineReader reader(f);
  std::string line;
  if (!reader.next(line)) {
    fclose(f);
    throw PwErr("clip-selftest: empty input\n");
  }
  double clipmax = atof(line.c_str());
  std::vector<std::unique_ptr<GapSeq>> arena;
  Msa msa;
  while (reader.next(line)) {
    if (line.empty()) continue;
    std::vector<std::string> fld = split_tabs(line);
    if (fld[0] == "SEQ") {
      if (fld.size() != 8 && fld.size() != 9)
        throw PwErr("clip-selftest: bad SEQ line\n");
      long seqlen = atol(fld[7].c_str());
      // optional 9th field: the bases, enabling WRITE commands below
      std::string bases = fld.size() == 9 ? fld[8] : std::string();
      if (!bases.empty() && (long)bases.size() != seqlen)
        throw PwErr("clip-selftest: bases/seqlen mismatch\n");
      arena.push_back(std::make_unique<GapSeq>(
          fld[1], bases, seqlen, atol(fld[3].c_str()),
          (int)atol(fld[2].c_str())));
      GapSeq* s = arena.back().get();
      s->clp5 = atol(fld[4].c_str());
      s->clp3 = atol(fld[5].c_str());
      parse_gap_list(fld[6], *s);
      if (msa.count() == 0) {
        msa.seqs.push_back(s);  // waiting for its pairwise partner
        s->msa = &msa;
      } else if (msa.count() == 1) {
        msa.seed_pair(msa.seqs[0], s);
      } else {
        msa.add_seq(s, s->offset, s->ng_ofs);
      }
    } else if (fld[0] == "EVAL") {
      if (fld.size() != 4) throw PwErr("clip-selftest: bad EVAL line\n");
      size_t idx = (size_t)atol(fld[1].c_str());
      if (idx >= msa.count())
        throw PwErr("clip-selftest: EVAL index out of range\n");
      pwnative::AlnClipOps ops;
      bool ok = msa.eval_clipping(msa.seqs[idx], atol(fld[2].c_str()),
                                  atol(fld[3].c_str()), clipmax, ops);
      if (ok) msa.apply_clipping(ops);
      printf("%s\n", ok ? "ok" : "rejected");
    } else if (fld[0] == "WRITE") {
      // emit a writer's output for the current (possibly clip-bearing)
      // MSA — parity-fuzzes the clip paths of write_ace/write_info
      // (QA clip math, negative AF offsets, seql/seqr strand swap)
      // that the CLI flow can never reach
      if (fld.size() != 2) throw PwErr("clip-selftest: bad WRITE line\n");
      if (msa.count() < 2)  // an unseeded MSA has no layout (length 0)
        throw PwErr("clip-selftest: WRITE needs a seeded MSA "
                    "(>= 2 SEQ lines)\n");
      if (fld[1] == "ace")
        msa.write_ace(stdout, "ctg", false, false);
      else if (fld[1] == "info")
        msa.write_info(stdout, "ctg", false, false);
      else
        throw PwErr("clip-selftest: unknown WRITE kind\n");
    }
  }
  fclose(f);
  for (const GapSeq* s : msa.seqs)
    printf("%s\t%ld\t%ld\n", s->name.c_str(), s->clp5, s->clp3);
  return 0;
}

int run(int argc, char** argv) {
  Opts opts = parse_args(argc, argv);
  if (opts.vals.count("refine-selftest"))
    return run_refine_selftest(opts.get("refine-selftest"));
  if (opts.vals.count("clip-selftest"))
    return run_clip_selftest(opts.get("clip-selftest"));
  if (opts.has("h")) {
    fprintf(stderr, "%s\n", USAGE);
    return 1;
  }
  Cfg cfg;
  cfg.debug = opts.has("D");
  cfg.fullgenome = opts.has("F");
  cfg.gene_cds = opts.has("G");
  if (cfg.fullgenome && cfg.gene_cds) {
    fprintf(stderr, "%s Error: cannot use both -G and -F!\n", USAGE);
    return 1;
  }
  bool force_coding = opts.has("C");
  bool force_noncoding = opts.has("N");
  if (force_coding && force_noncoding) {
    fprintf(stderr, "%s Error: cannot use both -N and -C!\n", USAGE);
    return 1;
  }
  cfg.verbose = opts.has("v") || cfg.debug;
  // --device: 'cpu' is this binary; 'tpu' is valid but lives in the
  // Python CLI; anything else is invalid (same wording as cli.py)
  std::string device = opts.is_bool("device") ? "True"
                                              : opts.get("device", "cpu");
  if (device == "tpu") {
    fprintf(stderr,
            "%s\nError: --device=tpu is handled by the Python CLI "
            "(python -m pwasm_tpu.cli); this native binary is the "
            "--device=cpu path.\n",
            USAGE);
    return 1;
  }
  if (device != "cpu") {
    fprintf(stderr, "%s\nInvalid --device value: %s (must be cpu or tpu)\n",
            USAGE, device.c_str());
    return 1;
  }
  // Python-CLI-only features: fail clearly rather than silently ignore
  for (const char* k : {"realign", "shard", "profile"}) {
    if (opts.has(k)) {
      fprintf(stderr,
              "Error: --%s is handled by the Python CLI "
              "(python -m pwasm_tpu.cli), not the native binary.\n",
              k);
      return 1;
    }
  }
  // --band/--batch are accepted (device-path tuning knobs with no effect
  // on the host report path) but validated like the Python CLI
  for (const char* knob : {"band", "batch"}) {
    if (!opts.has(knob)) continue;
    std::string val = opts.is_bool(knob) ? "True" : opts.get(knob);
    bool ok = !opts.is_bool(knob) && !val.empty();
    for (char c : val)
      if (!isdigit((unsigned char)c)) ok = false;
    if (ok && atol(val.c_str()) < 1) ok = false;
    if (!ok) {
      fprintf(stderr, "%s\nInvalid --%s value: %s\n", USAGE, knob,
              val.c_str());
      return 1;
    }
  }
  // bare --motifs is rejected before input handling, bare --stats after
  // the -c parse — matching the Python CLI's check order exactly
  if (opts.is_bool("motifs")) {
    fprintf(stderr, "%s\n--motifs requires a file argument\n", USAGE);
    return 1;
  }
  FILE* inf = stdin;
  std::string infile;
  if (!opts.positional.empty()) infile = opts.positional[0];
  if (!infile.empty()) {
    inf = fopen(infile.c_str(), "rb");
    if (!inf) throw PwErr("Cannot open input file " + infile + "!\n");
  }
  if (opts.vals.count("motifs")) cfg.motifs = load_motifs(opts.get("motifs"));
  if (opts.vals.count("c"))
    cfg.clipmax = parse_clipmax(opts.get("c"), cfg.verbose);
  cfg.skip_bad_lines = opts.has("skip-bad-lines");
  bool resume = opts.has("resume");
  if (opts.is_bool("stats")) {
    fprintf(stderr, "%s\n--stats requires a file argument\n", USAGE);
    return 1;
  }
  long resume_skip = 0;
  if (resume) {
    // --resume (cli.py:214-258): the report is per-alignment
    // independent, so resume = drop the LAST record (its rows may be
    // torn), truncate there, count the surviving headers, and skip
    // that many accepted alignments
    if (!opts.vals.count("o"))
      throw PwErr(sformat("%s\n--resume requires -o <report>\n", USAGE));
    FILE* rf = fopen(opts.get("o").c_str(), "rb");
    if (rf != nullptr) {
      long n_headers = 0, last_header = -1, size = 0;
      char prev_byte = '\n';  // virtual newline before file start
      int first = fgetc(rf);
      bool starts_ok = first == '>';
      fseek(rf, 0, SEEK_SET);
      std::vector<char> chunk(1 << 20);
      size_t got;
      while ((got = fread(chunk.data(), 1, chunk.size(), rf)) > 0) {
        for (size_t i = 0; i < got; ++i) {
          // the virtual leading '\n' makes a '>' at offset 0 count,
          // exactly like the Python scan's prepended prev_byte
          if (prev_byte == '\n' && chunk[i] == '>') {
            ++n_headers;
            last_header = size + (long)i;
          }
          prev_byte = chunk[i];
        }
        size += (long)got;
      }
      fclose(rf);
      long keep = 0;
      if (starts_ok && n_headers > 0) {
        keep = n_headers > 1 ? last_header : 0;
        resume_skip = n_headers - 1;
      }
      if (keep != size && truncate(opts.get("o").c_str(), keep) != 0)
        resume_skip = 0;  // like the Python scan's OSError fallback:
        // treat an untruncatable report as a fresh run (append mode)
    }
  }
  FILE* freport = stdout;
  if (opts.vals.count("o")) {
    freport = fopen(opts.get("o").c_str(), resume ? "ab" : "wb");
    if (!freport)
      throw PwErr("Cannot open file " + opts.get("o") + " for writing!\n");
  }
  std::string rpath = opts.get("r");
  if (rpath.empty())  // missing OR empty value, like Python's falsy check
    throw PwErr("Error: query FASTA file (-r) is required!\n");
  FastaDb qfasta(rpath);
  long fsize = qfasta.file_size();
  if (fsize <= 0) throw PwErr("Error: invalid FASTA file " + rpath + " !\n");
  if (!cfg.fullgenome && !cfg.gene_cds &&
      fsize > AUTO_FULLGENOME_FASTA_BYTES)
    cfg.fullgenome = true;
  cfg.skip_codan = cfg.fullgenome || force_noncoding;
  if (!cfg.skip_codan && !force_coding &&
      fsize > AUTO_FULLGENOME_FASTA_BYTES)
    cfg.skip_codan = true;
  FILE* fmsa = nullptr;
  std::unordered_map<std::string, FILE*> cons_outs;  // ace/info/cons
  const char* cons_kinds[] = {"ace", "info", "cons"};
  bool any_cons = false;
  for (const char* kind : cons_kinds)
    if (opts.has(kind)) any_cons = true;
  if (opts.vals.count("w") || any_cons) {
    if (cfg.fullgenome) {
      fprintf(stderr, "%s Error: can only generate MSA for -G mode!\n",
              USAGE);
      return 1;
    }
    if (opts.vals.count("w")) {
      fmsa = fopen(opts.get("w").c_str(), "wb");
      if (!fmsa)
        throw PwErr("Cannot open file " + opts.get("w") +
                    " for writing!\n");
    }
    for (const char* kind : cons_kinds) {
      if (opts.is_bool(kind)) {
        fprintf(stderr, "%s\n--%s requires a file argument\n", USAGE,
                kind);
        return 1;
      }
    }
    for (const char* kind : cons_kinds) {
      if (!opts.vals.count(kind)) continue;
      FILE* f = fopen(opts.get(kind).c_str(), "wb");
      if (!f)
        throw PwErr("Cannot open file " + opts.get(kind) +
                    " for writing!\n");
      cons_outs[kind] = f;
    }
  }
  cfg.remove_cons_gaps = opts.has("remove-cons-gaps");
  cfg.refine_clip = !opts.has("no-refine-clip");
  FILE* fsummary = nullptr;
  if (opts.vals.count("s")) {
    fsummary = fopen(opts.get("s").c_str(), "wb");
    if (!fsummary)
      throw PwErr("Cannot open file " + opts.get("s") + " for writing!\n");
  }
  Summary summary;
  RunStats stats;

  // ---- per-PAF-line loop (pafreport.cpp:296-460; cli.py _main_loop)
  std::unordered_map<std::string, long> alnpairs;  // gene-mode dedup
  std::unordered_map<std::string, std::string> ref_cache;
  std::string refseq_id, refseq, refseq_rc;
  bool have_ref = false;

  // progressive MSA state (-w; cli.py msa_add / pafreport.cpp:394-421):
  // one arena owns every GapSeq/Msa; Msas hold raw pointers into it
  std::vector<std::unique_ptr<GapSeq>> seq_arena;
  std::vector<std::unique_ptr<Msa>> msa_arena;
  GapSeq* ref_gseq = nullptr;  // current query's MSA instance
  Msa* ref_msa = nullptr;
  long numalns = 0;

  auto msa_add = [&](const Extraction& ex, const AlnInfo& al,
                     const std::string& tlabel, long ord_num) {
    seq_arena.push_back(std::make_unique<GapSeq>(
        tlabel, ex.tseq, -1, al.r_alnstart, al.reverse));
    GapSeq* taseq = seq_arena.back().get();
    bool first_ref_aln = ref_gseq == nullptr;
    GapSeq* rseq;
    if (first_ref_aln) {
      seq_arena.push_back(
          std::make_unique<GapSeq>(al.r_id, refseq));
      rseq = seq_arena.back().get();
      rseq->set_flag(pwnative::FLAG_IS_REF);
    } else {  // bare instance of refseq for this alignment
      seq_arena.push_back(
          std::make_unique<GapSeq>(al.r_id, "", al.r_len));
      rseq = seq_arena.back().get();
    }
    // once a gap, always a gap: apply this alignment's gaps to fresh
    // objects so an out-of-layout gap fails BEFORE any MSA mutation
    // (skippable under --skip-bad-lines, cli.py msa_add)
    try {
      for (const auto& g : ex.gaps) {
        if (g[0] == 0)
          rseq->set_gap(g[1], g[2]);
        else
          taseq->set_gap(g[1], g[2]);
      }
    } catch (const PwErr&) {
      if (!cfg.skip_bad_lines) throw;
      ++stats.msa_dropped;
      fprintf(stderr,
              "Warning: excluding alignment %s from the MSA "
              "(out-of-layout gap structure in the input)\n",
              tlabel.c_str());
      alnpairs.erase(al.r_id + "~" + al.t_id);
      // nothing references the two objects just pushed (rseq last)
      seq_arena.pop_back();
      seq_arena.pop_back();
      return;
    }
    if (first_ref_aln && seq_arena.size() > 2) {
      // only the LAST query's MSA is ever written (cli.py keeps a
      // single ref_msa and the Python GC frees the previous query's
      // object graph at this point) — release everything except the
      // two sequences of the new pairwise seed
      std::unique_ptr<GapSeq> t = std::move(seq_arena[seq_arena.size() - 2]);
      std::unique_ptr<GapSeq> r = std::move(seq_arena.back());
      seq_arena.clear();
      seq_arena.push_back(std::move(t));
      seq_arena.push_back(std::move(r));
      msa_arena.clear();
      ref_msa = nullptr;
    }
    msa_arena.push_back(std::make_unique<Msa>(rseq, taseq));
    Msa* newmsa = msa_arena.back().get();
    if (first_ref_aln) {
      newmsa->ordnum = ord_num;
      ref_msa = newmsa;
      ref_gseq = rseq;
    } else {
      ref_gseq->msa->add_align(ref_gseq, newmsa, rseq);
      ref_msa = ref_gseq->msa;
    }
  };

  const bool build_msa_out = fmsa != nullptr || !cons_outs.empty();
  LineReader reader(inf);
  std::string line;
  long file_line = 0;
  while (reader.next(line)) {
    ++file_line;
    if (line.empty() || line[0] == '#') continue;
    ++stats.lines;
    PafRecord rec;
    try {
      rec = parse_paf_line(line);
    } catch (const PwErr&) {
      if (!cfg.skip_bad_lines) throw;
      ++stats.skipped_bad;
      fprintf(stderr, "Warning: skipping malformed PAF line %ld\n",
              file_line);
      continue;
    }
    const AlnInfo& al = rec.al;
    if (al.r_id == al.t_id) {
      ++stats.skipped_self;
      if (cfg.verbose)
        fprintf(stderr, "Skipping alignment of qry seq to itself.\n");
      continue;
    }
    std::string new_pair;
    if (!cfg.fullgenome) {  // gene CDS mode: first q~t alignment only
      std::string key = al.r_id + "~" + al.t_id;
      auto it = alnpairs.find(key);
      if (it == alnpairs.end()) {
        alnpairs[key] = 0;
        new_pair = key;
      } else {
        ++it->second;
        ++stats.skipped_dedup;
        if (it->second == 1)
          fprintf(stderr,
                  "Warning: alignment %s to %s already seen, ignoring \n",
                  al.r_id.c_str(), al.t_id.c_str());
        continue;
      }
    }
    ++numalns;
    if (!build_msa_out && !cfg.skip_bad_lines &&
        stats.resumed_past < resume_skip) {
      // --resume fast path (cli.py:539-553): this alignment is already
      // in the report; advance the cursor on parse-level info alone so
      // resume cost scales with the REMAINING work.  Disabled under
      // --skip-bad-lines (a parseable line can still have been skipped
      // at extraction in the original run) and with MSA outputs (the
      // MSA needs every alignment).
      ++stats.resumed_past;
      ++stats.alignments;
      stats.aligned_bases += al.t_alnend - al.t_alnstart;
      continue;
    }
    if (refseq_id != al.r_id || !have_ref) {
      auto it = ref_cache.find(al.r_id);
      if (it != ref_cache.end()) {
        refseq = it->second;
      } else {
        if (!qfasta.fetch(al.r_id, refseq))
          throw PwErr("Error: could not retrieve sequence for " + al.r_id +
                      " !\n");
        upper_inplace(refseq);
        ref_cache[al.r_id] = refseq;
      }
      refseq_rc = revcomp(refseq);
      refseq_id = al.r_id;
      have_ref = true;
      ref_gseq = nullptr;  // a new query starts a new MSA (cli.py)
    }
    if (al.r_len != (long)refseq.size())
      throw PwErr(sformat(
          "Error: ref seq len in this PAF line (%ld) differs from loaded "
          "sequence length(%zu)!\n%s\n",
          al.r_len, refseq.size(), line.c_str()));
    const std::string& refseq_aln = al.reverse ? refseq_rc : refseq;
    Extraction ex;
    try {
      ex = extract_alignment(rec, refseq_aln);
    } catch (const PwErr&) {
      if (!cfg.skip_bad_lines) throw;
      --numalns;
      if (!new_pair.empty()) alnpairs.erase(new_pair);
      ++stats.skipped_bad;
      fprintf(stderr, "Warning: skipping malformed PAF line %ld\n",
              file_line);
      continue;
    }
    ++stats.alignments;
    stats.aligned_bases += al.t_alnend - al.t_alnstart;
    stats.events += (long)ex.evs.size();
    std::string tlabel = sformat("%s:%ld-%ld%c", al.t_id.c_str(),
                                 al.t_alnstart, al.t_alnend,
                                 al.reverse ? '-' : '+');
    std::string rlabel = al.r_id;
    if (cfg.fullgenome)
      rlabel += sformat(":%ld-%ld", al.r_alnstart, al.r_alnend);
    if (qfasta.size() == 1 && !cfg.fullgenome) rlabel.clear();
    if (stats.resumed_past < resume_skip) {
      // --resume cursor: rows already in the report from the
      // interrupted run (slow path: MSA/skip-bad-lines modes)
      ++stats.resumed_past;
    } else {
      print_diff_info(freport, al, rec.alnscore, rec.edist, ex.evs,
                      rlabel, tlabel, refseq, cfg.skip_codan, cfg.motifs,
                      fsummary ? &summary : nullptr);
    }
    if (build_msa_out) msa_add(ex, al, tlabel, numalns);
  }
  if (inf != stdin) fclose(inf);
  if (cfg.debug && ref_msa != nullptr) {
    fprintf(stderr, ">MSA (%zu)\n", ref_msa->count());
    ref_msa->print_layout(stderr, 'v');
  }
  if (fmsa != nullptr) {
    if (ref_msa != nullptr) ref_msa->write_msa(fmsa);
    fclose(fmsa);
  }
  if (!cons_outs.empty() && ref_msa != nullptr) {
    // consensus path (the library capability pafreport never calls,
    // SURVEY.md §2.3): refine once, then emit the requested formats —
    // write_msa above already captured the unrefined layout (cli.py)
    ref_msa->finalize();
    ref_msa->refine_msa(cfg.remove_cons_gaps, cfg.refine_clip);
    std::string contig =
        ref_msa->seqs.empty() ? "contig" : ref_msa->seqs[0]->name;
    if (cons_outs.count("ace"))
      ref_msa->write_ace(cons_outs["ace"], contig);
    if (cons_outs.count("info"))
      ref_msa->write_info(cons_outs["info"], contig);
    if (cons_outs.count("cons"))
      ref_msa->write_cons(cons_outs["cons"], contig);
  }
  for (auto& kv : cons_outs) fclose(kv.second);
  if (fsummary) {
    summary.write(fsummary);
    fclose(fsummary);
  }
  if (freport != stdout) fclose(freport);
  if (opts.vals.count("stats")) {
    FILE* f = fopen(opts.get("stats").c_str(), "wb");
    if (!f)
      throw PwErr("Cannot open file " + opts.get("stats") +
                  " for writing!\n");
    stats.write(f);
    fclose(f);
  }
  if (cfg.verbose)
    fprintf(stderr, "%ld alignments, %ld events, %ld aligned bases in "
                    "%.3fs (%.0f bases/s)\n",
            stats.alignments, stats.events, stats.aligned_bases,
            stats.wall_s(),
            stats.wall_s() > 0 ? (double)stats.aligned_bases /
                                     stats.wall_s()
                               : 0.0);
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  try {
    return run(argc, argv);
  } catch (const PwErr& e) {
    fputs(e.msg.c_str(), stderr);
    return e.code;
  }
}
