// Native MSA engine for the pafreport binary: gapped-sequence model +
// progressive pairwise->MSA merging with bidirectional gap propagation,
// the offset-padded multifasta writer (-w), and the consensus path —
// column pileup counts, the bestChar vote with its '-'/'N'-yield
// tie-break, consensus-gap column removal, X-drop clip refinement, and
// the ACE / contig-info / consensus-FASTA writers (--ace/--info/--cons).
//
// C++ twin of pwasm_tpu/align/gapseq.py (GapSeq) and align/msa.py (Msa),
// which are themselves the behavior spec of the reference's GASeq /
// GSeqAlign / MSAColumns / GAlnColumn (GapAssem.h:35-461;
// GapAssem.cpp:27-1367).  Byte parity of every output with the Python
// CLI is enforced by tests/test_native_cli.py.
#pragma once

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <memory>
#include <string>
#include <vector>

#include "pafreport_util.h"

namespace pwnative {

constexpr int FLAG_IS_REF = 0;
constexpr int FLAG_PREPPED = 2;
constexpr int FLAG_BAD_ALN = 7;

// Warning sink for the engine's diagnostics.  The standalone binary
// leaves it on stderr; the ctypes bridge (fastparse.cpp pw_msa_*)
// points it at a capture file so the Python front end can route engine
// warnings through sys.stderr exactly like its own engine does.
inline FILE*& warn_stream() {
  static FILE* s = stderr;
  return s;
}

class Msa;

// (the bestChar vote rule lives in pafreport_util.h — one C++ copy)

// Column bucket of one base char: A0 C1 G2 T3, N for everything else,
// '-'/'*' 5 (msa.py _BUCKET).
inline int column_bucket(unsigned char ch) {
  switch (ch) {
    case 'A': case 'a': return 0;
    case 'C': case 'c': return 1;
    case 'G': case 'g': return 2;
    case 'T': case 't': return 3;
    case '-': case '*': return 5;
    default: return 4;
  }
}

// A sequence in an MSA layout: bases + per-base gap counts + offsets
// (GASeq, GapAssem.h:35-138).  gaps[i] = gap columns BEFORE base i;
// negative marks the base deleted (not used on the -w path).
class GapSeq {
 public:
  std::string name;
  std::string seq;      // may be empty for a bare layout instance
  long seqlen = 0;
  std::vector<int32_t> gaps;
  long numgaps = 0;
  long offset = 0, ng_ofs = 0;
  int revcompl = 0;
  int flags = 0;
  long clp5 = 0, clp3 = 0;
  int msaidx = -1;
  Msa* msa = nullptr;

  GapSeq(std::string name_, std::string seq_, long seqlen_ = -1,
         long offset_ = 0, int revcompl_ = 0)
      : name(std::move(name_)), seq(std::move(seq_)),
        seqlen(seqlen_ < 0 ? (long)seq.size() : seqlen_),
        gaps((size_t)(seqlen_ < 0 ? (long)seq.size() : seqlen_), 0),
        offset(offset_), ng_ofs(offset_), revcompl(revcompl_) {}

  void set_flag(int bit) { flags |= 1 << bit; }
  bool has_flag(int bit) const { return (flags >> bit) & 1; }

  long end_offset() const { return offset + seqlen + numgaps; }
  long end_ng_offset() const { return ng_ofs + seqlen; }
  int32_t gap(long pos) const { return gaps[(size_t)pos]; }

  // (GapAssem.cpp:104-111; gapseq.py set_gap)
  void set_gap(long pos, int32_t gaplen = 1) {
    if (pos < 0 || pos >= seqlen)
      throw PwErr(sformat(
          "Error: invalid gap position (%ld) given for sequence %s\n",
          pos + 1, name.c_str()));
    numgaps -= gaps[(size_t)pos];
    gaps[(size_t)pos] = gaplen;
    numgaps += gaplen;
  }

  // (GapAssem.cpp:113-120)
  void add_gap(long pos, int32_t gapadd) {
    if (pos < 0 || pos >= seqlen)
      throw PwErr(sformat(
          "Error: invalid gap position (%ld) given for sequence %s\n",
          pos + 1, name.c_str()));
    numgaps += gapadd;
    gaps[(size_t)pos] += gapadd;
  }

  // First position j whose walk coordinate passes alpos
  // (the reference's per-member walk, GapAssem.cpp:739-744; the Python
  // engine uses a prefix-sum + binary search over the same monotone
  // positions — this linear walk computes the identical stopping point).
  long find_walk_pos(long alpos) const {
    long w = offset;
    for (long j = 0; j < seqlen; ++j) {
      w += 1 + gaps[(size_t)j];
      if (w > alpos) return j;
    }
    return seqlen;
  }

  void reverse_complement_bases() { seq = revcomp(seq); }

  // Reverse the gap array keeping index 0 fixed (GapAssem.cpp:351-364).
  void reverse_gaps() {
    if (seqlen > 1) std::reverse(gaps.begin() + 1, gaps.end());
  }

  void rev_complement(long alignlen = 0);  // needs Msa; defined below

  // Apply deferred deletions then RC once (GASeq::prepSeq,
  // GapAssem.cpp:89-101); the CLI flow has no delops.
  void prep_seq() {
    if (revcompl == 1) reverse_complement_bases();
    set_flag(FLAG_PREPPED);
  }

  // Remove one layout column at pos: a gap if one exists, else the base
  // itself — the gap count may go negative = deleted base
  // (GapAssem.cpp:122-180; gapseq.py remove_base).
  void remove_base(long pos) {
    if (pos < 0 || pos >= seqlen)
      throw PwErr(sformat(
          "Error: invalid gap position (%ld) given for sequence %s\n",
          pos + 1, name.c_str()));
    gaps[(size_t)pos] -= 1;
    numgaps -= 1;
  }

  // (clipL, clipR) in layout orientation — strand-aware aliasing of
  // clp5/clp3 (GapAssem.cpp:188-189).
  void clip_lr(long& l, long& r) const {
    if (revcompl != 0) {
      l = clp3;
      r = clp5;
    } else {
      l = clp5;
      r = clp3;
    }
  }

  // Zero gaps inside the clipped ends, fixing the offset
  // (GapAssem.cpp:522-549; gapseq.py remove_clip_gaps).
  long remove_clip_gaps() {
    long clipL, clipR;
    clip_lr(clipL, clipR);
    long delgaps_l = 0, delgaps_r = 0;
    for (long i = 0; i < seqlen; ++i) {
      if (i <= clipL) {
        delgaps_l += gaps[(size_t)i];
        gaps[(size_t)i] = 0;
        continue;
      }
      if (i >= seqlen - clipR) {
        delgaps_r += gaps[(size_t)i];
        gaps[(size_t)i] = 0;
      }
    }
    offset += delgaps_l;
    numgaps -= delgaps_l + delgaps_r;
    return delgaps_l + delgaps_r;
  }

  // X-drop end re-alignment against the consensus, updating clp5/clp3
  // (GASeq::refineClipping, GapAssem.cpp:182-349) — a direct port of
  // the reference walk (the same program as the Python engine's
  // transliterated oracle, gapseq.py refine_clipping_scalar).
  static constexpr int XDROP = -16, MATCH_SC = 1, MISMATCH_SC = -3;

  void refine_clipping(const std::string& cons, long cpos,
                       bool skip_dels = false) {
    if (clp3 == 0 && clp5 == 0) return;
    long cons_len = (long)cons.size();
    bool rev = revcompl != 0;
    long clipL, clipR;
    clip_lr(clipL, clipR);
    long glen = seqlen + numgaps;
    long allocsize = glen;
    long gclipR = clipR, gclipL = clipL;
    if (skip_dels) {
      for (long i = 1; i <= clipR; ++i) {
        if (gaps[(size_t)(seqlen - i)] < 0)
          ++allocsize;
        else
          gclipR += gaps[(size_t)(seqlen - i)];
      }
      for (long i = 0; i < clipL; ++i) {
        if (gaps[(size_t)i] < 0)
          ++allocsize;
        else
          gclipL += gaps[(size_t)i];
      }
    } else {
      for (long i = 1; i <= clipR; ++i) gclipR += gaps[(size_t)(seqlen - i)];
      for (long i = 0; i < clipL; ++i) gclipL += gaps[(size_t)i];
    }
    std::string gseq;
    std::vector<long> gxpos;
    for (long i = 0; i < seqlen; ++i) {
      int32_t g = gaps[(size_t)i];
      if (g < 0) {
        if (!skip_dels) continue;
        if (clipL <= i && i < seqlen - clipR) continue;
        ++glen;
      }
      for (int32_t k = 0; k < g; ++k) {
        gseq.push_back('*');
        gxpos.push_back(-1);
      }
      gseq.push_back(seq[(size_t)i]);
      gxpos.push_back(i);
    }
    if (glen != allocsize)
      throw PwErr(sformat(
          "Length mismatch (allocsize %ld vs. glen %ld) while "
          "refineClipping for seq %s !\n",
          allocsize, glen, name.c_str()));
    auto write_back = [&]() {
      // clipL/clipR are aliases of clp5/clp3 in the reference, so every
      // increment persists even on the early-warning returns
      if (rev) {
        clp3 = clipL;
        clp5 = clipR;
      } else {
        clp5 = clipL;
        clp3 = clipR;
      }
    };
    auto at = [&](long sp) -> int {
      return sp >= 0 && sp < (long)gseq.size()
                 ? (unsigned char)gseq[(size_t)sp] : -1;
    };
    if (clipR > 0) {
      long cp = cpos + glen - gclipR - 1;
      long sp = glen - gclipR - 1;
      bool ok = true;
      while (sp < 0 || cp < 0 || cp >= cons_len ||
             at(sp) != (unsigned char)cons[(size_t)cp] || at(sp) == '*') {
        if (sp >= 0 && at(sp) != '*') ++clipR;
        --sp;
        --cp;
        if (sp < gclipL) {
          fprintf(warn_stream(),
                  "Warning: reached clipL trying to find an initial "
                  "match on %s!\n",
                  name.c_str());
          ok = false;
          break;
        }
      }
      if (!ok) {
        write_back();
        return;
      }
      long score = MATCH_SC, maxscore = MATCH_SC;
      long startpos = sp, bestpos = sp;
      while (score > XDROP) {
        ++cp;
        ++sp;
        if (cp >= cons_len || sp >= glen) break;
        if (at(sp) == (unsigned char)cons[(size_t)cp]) {
          if (at(sp) != '*') {
            score += MATCH_SC;
            if (score > maxscore) {
              bestpos = sp;
              maxscore = score;
            }
          }
        } else if (at(sp) != '*') {
          score += MISMATCH_SC;
        }
      }
      if (bestpos > startpos) clipR = seqlen - gxpos[(size_t)bestpos] - 1;
    }
    if (clipL > 0) {
      long cp = cpos + gclipL;
      long sp = gclipL;
      bool ok = true;
      while (sp >= glen || cp >= cons_len || cp < 0 ||
             at(sp) != (unsigned char)cons[(size_t)cp] || at(sp) == '*') {
        if (sp < glen && at(sp) != '*') ++clipL;
        ++sp;
        ++cp;
        if (sp >= glen - gclipR) {
          fprintf(warn_stream(),
                  "Warning: reached clipR trying to find an initial "
                  "match on %s!\n",
                  name.c_str());
          ok = false;
          break;
        }
      }
      if (!ok) {
        write_back();
        return;
      }
      long score = MATCH_SC, maxscore = MATCH_SC;
      long startpos = sp, bestpos = sp;
      while (score > XDROP) {
        --cp;
        --sp;
        if (cp < 0 || sp < 0) break;
        if (at(sp) == (unsigned char)cons[(size_t)cp]) {
          if (at(sp) != '*') {
            score += MATCH_SC;
            if (score > maxscore) {
              bestpos = sp;
              maxscore = score;
            }
          }
        } else if (at(sp) != '*') {
          score += MISMATCH_SC;
        }
      }
      if (bestpos < startpos) clipL = gxpos[(size_t)bestpos];
    }
    write_back();
  }

  void check_loaded(const char* what) const {
    if (seq.empty() || (long)seq.size() != seqlen)
      throw PwErr(sformat(
          "GapSeq %s Error: invalid sequence data '%s' (len=%zu, "
          "seqlen=%ld)\n",
          what, name.c_str(), seq.size(), seqlen));
  }

  // Offset-padded multifasta record (GASeq::printMFasta,
  // GapAssem.cpp:482-520; gapseq.py print_mfasta).
  void print_mfasta(FILE* f, int llen = 60) const {
    check_loaded("print");
    fprintf(f, ">%s\n", name.c_str());
    std::string out;
    int printed = 0;
    auto put = [&](char ch) {
      ++printed;
      out.push_back(ch);
      if (printed == llen) {
        out.push_back('\n');
        printed = 0;
      }
    };
    for (long i = 0; i < offset; ++i) put('-');
    for (long i = 0; i < seqlen; ++i) {
      int32_t g = gaps[(size_t)i];
      if (g < 0) continue;  // deleted base
      for (int32_t k = 0; k < g; ++k) put('-');
      put(seq[(size_t)i]);
    }
    if (printed < llen) out.push_back('\n');
    fwrite(out.data(), 1, out.size(), f);
  }

  // Debug layout line with lowercase clips (GASeq::printGappedSeq,
  // GapAssem.cpp:412-440).
  void print_gapped_seq(FILE* f, long baseoffs = 0) const {
    check_loaded("print");
    long clipL, clipR;
    clip_lr(clipL, clipR);
    std::string out((size_t)(offset - baseoffs), ' ');
    for (long i = 0; i < seqlen; ++i) {
      int32_t g = gaps[(size_t)i];
      if (g < 0) continue;
      out.append((size_t)g, '-');
      char c = seq[(size_t)i];
      if (i < clipL || i >= seqlen - clipR)
        c = (char)tolower((unsigned char)c);
      out.push_back(c);
    }
    out.push_back('\n');
    fwrite(out.data(), 1, out.size(), f);
  }

  // ACE-style gapped sequence, '*' gaps, 60-col wrap; the exact-multiple
  // trailing blank line is preserved (GASeq::printGappedFasta,
  // GapAssem.cpp:442-480; gapseq.py print_gapped_fasta).
  void print_gapped_fasta(FILE* f) const {
    check_loaded("print");
    std::string out;
    int printed = 0;
    for (long i = 0; i < seqlen; ++i) {
      int32_t g = gaps[(size_t)i];
      if (g < 0) continue;
      for (int32_t k = 0; k < g; ++k) {
        out.push_back('*');
        if (++printed == 60) {
          out.push_back('\n');
          printed = 0;
        }
      }
      ++printed;
      out.push_back(seq[(size_t)i]);
      if (printed == 60) {
        out.push_back('\n');
        printed = 0;
      }
    }
    if (printed < 60) out.push_back('\n');
    fwrite(out.data(), 1, out.size(), f);
  }
};

// Column pileup: (size, 6) counts + live [mincol, maxcol] window
// (MSAColumns/GAlnColumn, GapAssem.h:255-376; msa.py MsaColumns).
struct MsaColumns {
  long size = 0, baseoffset = 0;
  std::vector<int32_t> counts;  // size x 6
  std::vector<int32_t> layers;
  long mincol = std::numeric_limits<long>::max(), maxcol = 0;

  MsaColumns(long size_, long baseoffset_)
      : size(size_), baseoffset(baseoffset_),
        counts((size_t)size_ * 6, 0), layers((size_t)size_, 0) {}

  void update_min_max(long minc, long maxc) {
    if (minc < mincol) mincol = minc;
    if (maxc > maxcol) maxcol = maxc;
  }
};

// A multiple sequence alignment (GSeqAlign, GapAssem.h:381-461).
// Holds raw pointers; the CLI keeps ownership in one arena.
class Msa {
 public:
  std::vector<GapSeq*> seqs;
  long length = 0, minoffset = 0, ng_len = 0, ng_minofs = 0;
  long ordnum = 0, badseqs = 0;
  std::string consensus;
  std::unique_ptr<MsaColumns> msacolumns;
  bool refined = false;

  Msa() = default;
  // pairwise seed (GapAssem.cpp:605-641)
  Msa(GapSeq* s1, GapSeq* s2) { seed_pair(s1, s2); }

  // the pairwise-seed bookkeeping, callable on a default-constructed
  // Msa too (the clip-selftest hook builds its MSA incrementally)
  void seed_pair(GapSeq* s1, GapSeq* s2) {
    s1->msa = this;
    s2->msa = this;
    seqs = {s1, s2};
    minoffset = std::min(s1->offset, s2->offset);
    ng_minofs = minoffset;
    length = std::max(s1->end_offset(), s2->end_offset()) - minoffset;
    ng_len = std::max(s1->end_ng_offset(), s2->end_ng_offset())
             - ng_minofs;
  }

  size_t count() const { return seqs.size(); }

  // (GSeqAlign::addSeq, GapAssem.cpp:694-716)
  void add_seq(GapSeq* s, long soffs, long ngofs) {
    s->offset = soffs;
    s->ng_ofs = ngofs;
    s->msa = this;
    seqs.push_back(s);
    if (soffs < minoffset) {
      length += minoffset - soffs;
      minoffset = soffs;
    }
    if (ngofs < ng_minofs) {
      ng_len += ng_minofs - ngofs;
      ng_minofs = ngofs;
    }
    if (s->end_offset() - minoffset > length)
      length = s->end_offset() - minoffset;
    if (s->end_ng_offset() - ng_minofs > ng_len)
      ng_len = s->end_ng_offset() - ng_minofs;
  }

  // Layout position of seq[pos] (GapAssem.cpp:721-725)
  long alpos_of(const GapSeq* seq, long pos) const {
    long gsum = 0;
    for (long j = 0; j <= pos; ++j) gsum += seq->gaps[(size_t)j];
    return seq->offset + pos + gsum;
  }

  // Delete one layout column from every member
  // (GSeqAlign::removeColumn, GapAssem.cpp:755-779)
  void remove_column(long column) {
    long alpos = column + minoffset;
    for (GapSeq* s : seqs) {
      if (s->offset >= alpos) {
        s->offset -= 1;
        continue;
      }
      long spos = s->find_walk_pos(alpos);
      if (spos >= s->seqlen) continue;
      s->remove_base(spos);
    }
    length -= 1;
  }

  // Propagate a gap through every member (GSeqAlign::injectGap,
  // GapAssem.cpp:720-753)
  void inject_gap(GapSeq* seq, long pos, int32_t xgap) {
    long alpos = alpos_of(seq, pos);
    for (GapSeq* s : seqs) {
      long spos;
      if (s == seq) {
        spos = pos;
      } else {
        if (s->offset >= alpos) {
          s->offset += xgap;
          continue;
        }
        spos = s->find_walk_pos(alpos);
        if (spos >= s->seqlen) continue;
      }
      s->add_gap(spos, xgap);
    }
    length += xgap;
  }

  // Merge another MSA through the shared sequence (GSeqAlign::addAlign,
  // GapAssem.cpp:645-690): RC on strand mismatch, bidirectional
  // per-position gap diff, then absorb the other members.
  void add_align(GapSeq* seq, Msa* omsa, GapSeq* oseq) {
    if (seq->seqlen != oseq->seqlen)
      throw PwErr(sformat(
          "GSeqAlign Error: invalid merge %s(len %ld) vs %s(len %ld)\n",
          seq->name.c_str(), seq->seqlen, oseq->name.c_str(),
          oseq->seqlen));
    if (seq->revcompl != oseq->revcompl) omsa->rev_complement();
    for (long i = 0; i < seq->seqlen; ++i) {
      int32_t d = seq->gap(i) - oseq->gap(i);
      if (d > 0)
        omsa->inject_gap(oseq, i, d);
      else if (d < 0)
        inject_gap(seq, i, -d);
    }
    for (GapSeq* s : omsa->seqs) {
      if (s == oseq) continue;
      add_seq(s, seq->offset + s->offset - oseq->offset,
              seq->ng_ofs + s->ng_ofs - oseq->ng_ofs);
    }
  }

  // (GSeqAlign::revComplement, GapAssem.cpp:998-1004)
  void rev_complement() {
    for (GapSeq* s : seqs) s->rev_complement(length);
    std::stable_sort(seqs.begin(), seqs.end(),
                     [](const GapSeq* a, const GapSeq* b) {
                       return a->offset < b->offset;
                     });
  }

  // (GSeqAlign::finalize, GapAssem.cpp:1006-1012)
  void finalize() {
    for (GapSeq* s : seqs) {
      if (s->seq.empty())
        throw PwErr(sformat("Error: sequence for %s not loaded!\n",
                            s->name.c_str()));
      if (!s->has_flag(FLAG_PREPPED)) s->prep_seq();
    }
  }

  // (GSeqAlign::writeMSA, GapAssem.cpp:1039-1046)
  void write_msa(FILE* f, int linelen = 60) {
    finalize();
    for (GapSeq* s : seqs) s->print_mfasta(f, linelen);
  }

  // ---- clipping transaction (GSeqAlign::evalClipping/applyClipping,
  // GapAssem.cpp:814-996; msa.py eval_clipping/apply_clipping) --------
  // declared here, defined after AlnClipOps below
  bool eval_clipping(GapSeq* seq, long c5, long c3, double clipmax,
                     class AlnClipOps& clipops);
  void apply_clipping(const class AlnClipOps& clipops);

  // ---- consensus path (GSeqAlign::buildMSA/refineMSA + writers,
  // GapAssem.cpp:1048-1367; msa.py build_msa/refine_msa/write_*) ------

  // Pour one sequence into the column pileup (GASeq::toMSA,
  // GapAssem.cpp:551-591; msa.py _seq_to_columns).  With count=false
  // only the geometry side effects happen (live window) — the counts
  // are expected to come from the device pileup kernel instead
  // (msa.py _seq_to_columns(count=False)).
  void seq_to_columns(const GapSeq* s, MsaColumns& cols,
                      bool count = true) const {
    if (s->seq.empty() || (long)s->seq.size() != s->seqlen)
      throw PwErr(sformat(
          "GapSeq toMSA Error: invalid sequence data '%s' (len=%zu, "
          "seqlen=%ld)\n",
          s->name.c_str(), s->seq.size(), s->seqlen));
    long clipL, clipR;
    s->clip_lr(clipL, clipR);
    // base i sits at offset - minoffset + i + inclusive-cumsum(gaps);
    // start one left so the += (1 + g) walk lands exactly there
    long col = s->offset - minoffset - 1;
    long first_col = -1, last_col = -1;
    int32_t first_gap = 0;
    for (long i = 0; i < s->seqlen; ++i) {
      int32_t g = s->gaps[(size_t)i];
      col += 1 + g;  // base i sits at `col` (inclusive-cumsum layout)
      bool unclipped = !(i < clipL || i >= s->seqlen - clipR);
      if (!unclipped) continue;
      if (count) {
        cols.counts[(size_t)col * 6 + column_bucket(
            (unsigned char)s->seq[(size_t)i])]++;
        cols.layers[(size_t)col]++;
        for (int32_t k = 1; k <= g; ++k) {  // gap run before the base
          cols.counts[(size_t)(col - k) * 6 + 5]++;
          cols.layers[(size_t)(col - k)]++;
        }
      }
      if (first_col < 0) {
        first_col = col;
        first_gap = g > 0 ? g : 0;
      }
      last_col = col;
    }
    if (first_col >= 0)
      cols.update_min_max(first_col - first_gap, last_col);
  }

  // (GSeqAlign::buildMSA, GapAssem.cpp:1088-1106)
  void build_msa(bool count = true) {
    if (msacolumns)
      throw PwErr("Error: cannot call buildMSA() twice!\n");
    msacolumns = std::make_unique<MsaColumns>(length, minoffset);
    for (size_t i = 0; i < seqs.size(); ++i) {
      GapSeq* s = seqs[i];
      s->msaidx = (int)i;
      if (s->seqlen - s->clp3 - s->clp5 < 1) {
        fprintf(warn_stream(),
                "Warning: sequence %s (length %ld) was trimmed too "
                "badly (%ld,%ld) -- should be removed from MSA w/ %s!\n",
                s->name.c_str(), s->seqlen, s->clp5, s->clp3,
                seqs[0]->name.c_str());
        s->set_flag(FLAG_BAD_ALN);
        ++badseqs;
      }
      seq_to_columns(s, *msacolumns, count);
    }
  }

  // Render the pre-refine MSA as a (count(), length) int8 code matrix
  // for the device consensus kernel — the C++ twin of
  // msa.py pileup_matrix's no-deletions fast path: A0 C1 G2 T3 N4,
  // gap-run columns 5, everything else (outside span / clipped) 6.
  // Pre-refine only (deleted bases would need spill rows; the device
  // delegation path always renders before any removal).
  void render_pileup(int8_t* out) const {
    memset(out, 6, (size_t)count() * (size_t)length);
    for (size_t r = 0; r < seqs.size(); ++r) {
      const GapSeq* s = seqs[r];
      int8_t* row = out + r * (size_t)length;
      long clipL, clipR;
      s->clip_lr(clipL, clipR);
      long col = s->offset - minoffset - 1;
      for (long i = 0; i < s->seqlen; ++i) {
        int32_t g = s->gaps[(size_t)i];
        if (g < 0)
          throw PwErr(sformat(
              "render_pileup: sequence %s has deleted bases "
              "(post-refine MSA)\n", s->name.c_str()));
        col += 1 + g;
        if (i < clipL || i >= s->seqlen - clipR) continue;
        row[col] = (int8_t)column_bucket((unsigned char)s->seq[(size_t)i]);
        for (int32_t k = 1; k <= g; ++k) row[col - k] = 5;
      }
    }
  }

  // (GSeqAlign::ErrZeroCov, GapAssem.cpp:1121-1131; exit 5)
  [[noreturn]] void err_zero_cov(long col) const {
    fprintf(warn_stream(),
            "WARNING: 0 coverage column %ld (mincol=%ld) found within "
            "alignment of %zu seqs!\n",
            col, msacolumns->mincol, count());
    for (const GapSeq* s : seqs) fprintf(warn_stream(), "%s\n", s->name.c_str());
    throw PwErr(sformat("zero-coverage column %ld", col), 5);
  }

  // Consensus construction + clipping refinement driver
  // (GSeqAlign::refineMSA, GapAssem.cpp:1133-1183; msa.py refine_msa).
  void refine_msa(bool remove_cons_gaps, bool refine_clipping) {
    build_msa();
    MsaColumns& cols = *msacolumns;
    // votes come from the counts as built — column removal below
    // mutates the members, never the counts (msa.py computes the vote
    // array up-front for the same reason)
    std::vector<int> votes;
    for (long col = cols.mincol; col <= cols.maxcol; ++col)
      votes.push_back(best_char_from_counts(
          &cols.counts[(size_t)col * 6], cols.layers[(size_t)col]));
    refine_with_votes(votes, remove_cons_gaps, refine_clipping);
  }

  // The post-vote half of refine_msa with the votes supplied by the
  // caller — the seam the device consensus delegation uses: the bridge
  // builds geometry only (build_msa(false)), renders the pileup for
  // the TPU kernel, and hands the kernel's bit-exact votes (char codes
  // over [mincol, maxcol]; 0 = zero coverage) back here.
  void refine_with_votes(const std::vector<int>& votes,
                         bool remove_cons_gaps, bool refine_clipping) {
    MsaColumns& cols = *msacolumns;
    long cols_removed = 0;
    consensus.clear();
    for (long col = cols.mincol; col <= cols.maxcol; ++col) {
      int c = votes[(size_t)(col - cols.mincol)];
      if (c == 0) err_zero_cov(col);
      if (c == '-' || c == '*') {
        if (remove_cons_gaps) {
          remove_column(col - cols_removed);
          ++cols_removed;
          continue;
        }
        c = '*';
      }
      consensus.push_back((char)c);
    }
    auto cpos = [&](const GapSeq* s) {
      return s->offset - minoffset - cols.mincol;
    };
    if (refine_clipping)
      for (GapSeq* s : seqs) s->refine_clipping(consensus, cpos(s));
    std::vector<GapSeq*> second;
    for (GapSeq* s : seqs) {
      long grem = remove_cons_gaps ? s->remove_clip_gaps() : 0;
      if (grem != 0 && refine_clipping) second.push_back(s);
    }
    for (GapSeq* s : second)
      s->refine_clipping(consensus, cpos(s), true);
    refined = true;
  }

  // ACE contig output (GSeqAlign::writeACE, GapAssem.cpp:1200-1262)
  void write_ace(FILE* f, const std::string& name,
                 bool remove_cons_gaps = true,
                 bool refine_clipping = true) {
    if (!refined) refine_msa(remove_cons_gaps, refine_clipping);
    size_t fwd = 0;
    for (const GapSeq* s : seqs)
      if (s->revcompl == 0) ++fwd;
    char cons_dir = count() - fwd > fwd ? 'C' : 'U';
    fprintf(f, "CO %s %zu %zu 0 %c\n", name.c_str(), consensus.size(),
            count(), cons_dir);
    for (size_t i = 0; i < consensus.size(); i += 60)
      fprintf(f, "%s\n",
              consensus.substr(i, std::min<size_t>(
                  60, consensus.size() - i)).c_str());
    fprintf(f, "\nBQ \n\n");
    long mincol = msacolumns->mincol;
    for (const GapSeq* s : seqs)
      fprintf(f, "AF %s %c %ld\n", s->name.c_str(),
              s->revcompl == 0 ? 'U' : 'C',
              s->offset - minoffset - mincol + 1);
    fprintf(f, "\n");
    for (GapSeq* s : seqs) {
      long gapped_len = s->seqlen + s->numgaps;
      fprintf(f, "RD %s %ld 0 0\n", s->name.c_str(), gapped_len);
      s->print_gapped_fasta(f);
      long clpl, clpr;
      s->clip_lr(clpl, clpr);
      long l = clpl, r = clpr;
      for (long j = 1; j <= r; ++j) clpr += s->gaps[(size_t)(s->seqlen - j)];
      for (long j = 0; j <= l; ++j) clpl += s->gaps[(size_t)j];
      long seql = clpl + 1;
      long seqr = gapped_len - clpr;
      if (seqr < seql) {
        fprintf(warn_stream(), "Bad trimming for %s of gapped len %ld (%ld, "
                        "%ld)\n",
                s->name.c_str(), gapped_len, seql, seqr);
        seqr = seql + 1;
      }
      fprintf(f, "\nQA %ld %ld %ld %ld\nDS \n\n", seql, seqr, seql, seqr);
    }
  }

  // Consensus FASTA ('*' marks kept all-gap columns; msa.py write_cons)
  void write_cons(FILE* f, const std::string& name,
                  bool remove_cons_gaps = true,
                  bool refine_clipping = true) {
    if (!refined) refine_msa(remove_cons_gaps, refine_clipping);
    fprintf(f, ">%s_cons %zu seqs\n", name.c_str(), count());
    for (size_t i = 0; i < consensus.size(); i += 60)
      fprintf(f, "%s\n",
              consensus.substr(i, std::min<size_t>(
                  60, consensus.size() - i)).c_str());
  }

  // Contig-info output with per-seq pid and run-length alndata,
  // including the reference's double-'+1' pid quirk
  // (GSeqAlign::writeInfo, GapAssem.cpp:1264-1367; msa.py write_info)
  void write_info(FILE* f, const std::string& name,
                  bool remove_cons_gaps = true,
                  bool refine_clipping = true) {
    if (!refined) refine_msa(remove_cons_gaps, refine_clipping);
    fprintf(f, ">%s %zu %s\n", name.c_str(), count(), consensus.c_str());
    long mincol = msacolumns->mincol;
    for (GapSeq* s : seqs) {
      long gapped_len = s->seqlen + s->numgaps;
      long seqoffset = s->offset - minoffset - mincol + 1;
      long clpl, clpr;
      s->clip_lr(clpl, clpr);
      long asml = seqoffset + 1;
      long asmr = asml - 1;
      double pid = 0.0;
      long aligned_len = 0, indel_ofs = 0;
      std::string alndata;
      for (long j = s->clp5; j < s->seqlen - s->clp3; ++j) {
        long indel = s->gaps[(size_t)j];
        char indel_type = '\0';
        asmr += indel + 1;
        if (indel < 0) {
          indel_type = 'd';
          indel = -indel;
        } else {
          if (indel > 0)
            indel_type = 'g';
          else
            ++indel_ofs;
          if (asmr - 1 >= 0 && asmr - 1 < (long)consensus.size() &&
              toupper((unsigned char)s->seq[(size_t)j]) ==
                  toupper((unsigned char)consensus[(size_t)(asmr - 1)]))
            pid += 1;
          ++aligned_len;
        }
        if (indel_type) {
          if (indel > 2)
            alndata += sformat("%ld%c%ld-", indel_ofs, indel_type, indel);
          else
            alndata.append((size_t)indel, indel_type);
          indel_ofs = 0;
        }
      }
      pid = aligned_len ? pid * 100.0 / (double)aligned_len : 0.0;
      long seql = clpl + 1;
      long seqr = (long)s->seq.size() - clpr;
      if (seqr < seql) {
        fprintf(warn_stream(),
                "WARNING: Bad trimming for %s of gapped len %ld (%ld, "
                "%ld)\n",
                s->name.c_str(), gapped_len, seql, seqr);
        seqr = seql + 1;
      }
      if (s->revcompl) std::swap(seql, seqr);
      fprintf(f, "%s %zu %ld %ld %ld %ld %ld %4.2f %s\n", s->name.c_str(),
              s->seq.size(), seqoffset, asml, asmr, seql, seqr, pid,
              alndata.c_str());
    }
  }

  // Debug layout view (GSeqAlign::print, GapAssem.cpp:1013-1037)
  void print_layout(FILE* f, char sep = '\0') {
    finalize();
    size_t width = 0;
    for (GapSeq* s : seqs) width = std::max(width, s->name.size());
    if (sep) {
      fprintf(f, "%*s   ", (int)width, "");
      for (long i = 0; i < length; ++i) fputc(sep, f);
      fputc('\n', f);
    }
    for (GapSeq* s : seqs) {
      fprintf(f, "%*s %c ", (int)width, s->name.c_str(),
              s->revcompl == 1 ? '-' : '+');
      s->print_gapped_seq(f, minoffset);
    }
  }
};

// Staged clipping transaction (AlnClipOps, GapAssem.h:183-253; msa.py
// AlnClipOps): collect per-seq clip updates, refusing any that exceed
// clipmax or leave a read under 25% of its length.
class AlnClipOps {
 public:
  struct Op {
    GapSeq* s;
    long clp5, clp3;  // -1 = leave unchanged
  };
  std::vector<Op> ops;
  long total = 0;

  static long maxovh(const GapSeq* s, double clipmax) {
    // Python: int(clipmax) if clipmax > 1 else int(round(clipmax *
    // seqlen)) — round() is round-half-even, which nearbyint matches
    // under the default FE_TONEAREST mode
    return clipmax > 1 ? (long)clipmax
                       : (long)std::nearbyint(clipmax *
                                              (double)s->seqlen);
  }

  bool add5(GapSeq* s, long clp, double clipmax) {
    if (s->clp5 < clp) {
      if (clipmax > 0 && clp > maxovh(s, clipmax)) return false;
      if (s->seqlen - s->clp3 - clp < (s->seqlen >> 2)) return false;
      total += 10000 + clp - s->clp5;
      ops.push_back({s, clp, -1});
    }
    return true;
  }

  bool add3(GapSeq* s, long clp, double clipmax) {
    if (s->clp3 < clp) {
      if (clipmax > 0 && clp > maxovh(s, clipmax)) return false;
      if (s->seqlen - s->clp5 - clp < (s->seqlen >> 2)) return false;
      total += 10000 + clp - s->clp3;
      ops.push_back({s, -1, clp});
    }
    return true;
  }
};

// (GSeqAlign::evalClipping, GapAssem.cpp:823-996; msa.py eval_clipping)
// Propagate a proposed end-trim of ``seq`` to every member, refusing if
// any member would be over-clipped.
inline bool Msa::eval_clipping(GapSeq* seq, long c5, long c3,
                               double clipmax, AlnClipOps& clipops) {
  if (c5 >= 0) {
    long pos = seq->revcompl != 0 ? seq->seqlen - c5 - 1 : c5;
    long alpos = alpos_of(seq, pos);
    for (GapSeq* s : seqs) {
      if (s == seq) {
        if (!clipops.add5(s, c5, clipmax)) return false;
        continue;
      }
      if (s->offset >= alpos) {
        if (seq->revcompl != 0) return false;  // clipped entirely
        continue;
      }
      long spos = s->find_walk_pos(alpos);
      if (spos >= s->seqlen) {
        if (seq->revcompl == 0) return false;
        continue;
      }
      if (seq->revcompl != 0) {  // trimming the right side of the msa
        if (s->revcompl != 0) {
          if (!clipops.add5(s, s->seqlen - spos - 1, clipmax))
            return false;
        } else {
          if (!clipops.add3(s, s->seqlen - spos - 1, clipmax))
            return false;
        }
      } else {  // trimming the left side
        if (s->revcompl != 0) {
          if (!clipops.add3(s, spos, clipmax)) return false;
        } else {
          if (!clipops.add5(s, spos, clipmax)) return false;
        }
      }
    }
  }
  if (c3 >= 0) {
    long pos = seq->revcompl != 0 ? c3 : seq->seqlen - c3 - 1;
    long alpos = alpos_of(seq, pos);
    for (GapSeq* s : seqs) {
      if (s == seq) {
        if (!clipops.add3(s, c3, clipmax)) return false;
        continue;
      }
      if (s->offset >= alpos) {
        if (seq->revcompl == 0) return false;
        continue;
      }
      long spos = s->find_walk_pos(alpos);
      if (spos >= s->seqlen) {
        if (seq->revcompl != 0) return false;
        continue;
      }
      if (seq->revcompl != 0) {  // trim left side
        if (s->revcompl != 0) {
          if (!clipops.add3(s, spos, clipmax)) return false;
        } else {
          if (!clipops.add5(s, spos, clipmax)) return false;
        }
      } else {  // trim right side
        if (s->revcompl != 0) {
          if (!clipops.add5(s, s->seqlen - spos - 1, clipmax))
            return false;
        } else {
          if (!clipops.add3(s, s->seqlen - spos - 1, clipmax))
            return false;
        }
      }
    }
  }
  return true;
}

// (GSeqAlign::applyClipping, GapAssem.cpp:814-822)
inline void Msa::apply_clipping(const AlnClipOps& clipops) {
  for (const auto& op : clipops.ops) {
    if (op.clp5 >= 0) op.s->clp5 = op.clp5;
    if (op.clp3 >= 0) op.s->clp3 = op.clp3;
  }
}

// GASeq::revComplement within a layout (GapAssem.cpp:366-392) — defined
// after Msa because it reads the owning MSA's layout fields.
inline void GapSeq::rev_complement(long alignlen) {
  if (alignlen > 0) {
    offset = alignlen - end_offset();
    if (msa != nullptr) {
      ng_ofs = msa->ng_len - end_ng_offset();
      if (msa->minoffset > offset) msa->minoffset = offset;
      if (msa->ng_minofs > ng_ofs) msa->ng_minofs = ng_ofs;
    }
  }
  revcompl = revcompl ? 0 : 1;
  if ((long)seq.size() == seqlen) reverse_complement_bases();
  reverse_gaps();
}

}  // namespace pwnative
