// Native MSA engine for the pafreport binary's -w path: gapped-sequence
// model + progressive pairwise->MSA merging with bidirectional gap
// propagation + the offset-padded multifasta writer.
//
// C++ twin of pwasm_tpu/align/gapseq.py (GapSeq) and align/msa.py (Msa),
// which are themselves the behavior spec of the reference's GASeq /
// GSeqAlign (GapAssem.h:35-138,381-461; GapAssem.cpp:27-591,593-1046).
// Byte parity of the .mfa output with the Python CLI is enforced by
// tests/test_native_cli.py.  Only the -w surface lives here: set_gap,
// inject_gap, add_align, rev_complement, finalize/prep_seq, print_mfasta,
// print_gapped_seq (the -D debug layout).  The consensus/refinement path
// (refine_msa, ACE/info writers) stays in the Python engine.
#pragma once

#include <algorithm>
#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "pafreport_util.h"

namespace pwnative {

constexpr int FLAG_IS_REF = 0;
constexpr int FLAG_PREPPED = 2;

class Msa;

// A sequence in an MSA layout: bases + per-base gap counts + offsets
// (GASeq, GapAssem.h:35-138).  gaps[i] = gap columns BEFORE base i;
// negative marks the base deleted (not used on the -w path).
class GapSeq {
 public:
  std::string name;
  std::string seq;      // may be empty for a bare layout instance
  long seqlen = 0;
  std::vector<int32_t> gaps;
  long numgaps = 0;
  long offset = 0, ng_ofs = 0;
  int revcompl = 0;
  int flags = 0;
  Msa* msa = nullptr;

  GapSeq(std::string name_, std::string seq_, long seqlen_ = -1,
         long offset_ = 0, int revcompl_ = 0)
      : name(std::move(name_)), seq(std::move(seq_)),
        seqlen(seqlen_ < 0 ? (long)seq.size() : seqlen_),
        gaps((size_t)(seqlen_ < 0 ? (long)seq.size() : seqlen_), 0),
        offset(offset_), ng_ofs(offset_), revcompl(revcompl_) {}

  void set_flag(int bit) { flags |= 1 << bit; }
  bool has_flag(int bit) const { return (flags >> bit) & 1; }

  long end_offset() const { return offset + seqlen + numgaps; }
  long end_ng_offset() const { return ng_ofs + seqlen; }
  int32_t gap(long pos) const { return gaps[(size_t)pos]; }

  // (GapAssem.cpp:104-111; gapseq.py set_gap)
  void set_gap(long pos, int32_t gaplen = 1) {
    if (pos < 0 || pos >= seqlen)
      throw PwErr(sformat(
          "Error: invalid gap position (%ld) given for sequence %s\n",
          pos + 1, name.c_str()));
    numgaps -= gaps[(size_t)pos];
    gaps[(size_t)pos] = gaplen;
    numgaps += gaplen;
  }

  // (GapAssem.cpp:113-120)
  void add_gap(long pos, int32_t gapadd) {
    if (pos < 0 || pos >= seqlen)
      throw PwErr(sformat(
          "Error: invalid gap position (%ld) given for sequence %s\n",
          pos + 1, name.c_str()));
    numgaps += gapadd;
    gaps[(size_t)pos] += gapadd;
  }

  // First position j whose walk coordinate passes alpos
  // (the reference's per-member walk, GapAssem.cpp:739-744; the Python
  // engine uses a prefix-sum + binary search over the same monotone
  // positions — this linear walk computes the identical stopping point).
  long find_walk_pos(long alpos) const {
    long w = offset;
    for (long j = 0; j < seqlen; ++j) {
      w += 1 + gaps[(size_t)j];
      if (w > alpos) return j;
    }
    return seqlen;
  }

  void reverse_complement_bases() { seq = revcomp(seq); }

  // Reverse the gap array keeping index 0 fixed (GapAssem.cpp:351-364).
  void reverse_gaps() {
    if (seqlen > 1) std::reverse(gaps.begin() + 1, gaps.end());
  }

  void rev_complement(long alignlen = 0);  // needs Msa; defined below

  // Apply deferred deletions then RC once (GASeq::prepSeq,
  // GapAssem.cpp:89-101); the -w path has no delops.
  void prep_seq() {
    if (revcompl == 1) reverse_complement_bases();
    set_flag(FLAG_PREPPED);
  }

  void check_loaded(const char* what) const {
    if (seq.empty() || (long)seq.size() != seqlen)
      throw PwErr(sformat(
          "GapSeq %s Error: invalid sequence data '%s' (len=%zu, "
          "seqlen=%ld)\n",
          what, name.c_str(), seq.size(), seqlen));
  }

  // Offset-padded multifasta record (GASeq::printMFasta,
  // GapAssem.cpp:482-520; gapseq.py print_mfasta).
  void print_mfasta(FILE* f, int llen = 60) const {
    check_loaded("print");
    fprintf(f, ">%s\n", name.c_str());
    std::string out;
    int printed = 0;
    auto put = [&](char ch) {
      ++printed;
      out.push_back(ch);
      if (printed == llen) {
        out.push_back('\n');
        printed = 0;
      }
    };
    for (long i = 0; i < offset; ++i) put('-');
    for (long i = 0; i < seqlen; ++i) {
      int32_t g = gaps[(size_t)i];
      if (g < 0) continue;  // deleted base
      for (int32_t k = 0; k < g; ++k) put('-');
      put(seq[(size_t)i]);
    }
    if (printed < llen) out.push_back('\n');
    fwrite(out.data(), 1, out.size(), f);
  }

  // Debug layout line with lowercase clips (GASeq::printGappedSeq,
  // GapAssem.cpp:412-440).  The -w path never sets clips, so clp5/clp3
  // are omitted from this engine and every base prints as stored.
  void print_gapped_seq(FILE* f, long baseoffs = 0) const {
    check_loaded("print");
    std::string out((size_t)(offset - baseoffs), ' ');
    for (long i = 0; i < seqlen; ++i) {
      int32_t g = gaps[(size_t)i];
      if (g < 0) continue;
      out.append((size_t)g, '-');
      out.push_back(seq[(size_t)i]);
    }
    out.push_back('\n');
    fwrite(out.data(), 1, out.size(), f);
  }
};

// A multiple sequence alignment (GSeqAlign, GapAssem.h:381-461).
// Holds raw pointers; the CLI keeps ownership in one arena.
class Msa {
 public:
  std::vector<GapSeq*> seqs;
  long length = 0, minoffset = 0, ng_len = 0, ng_minofs = 0;
  long ordnum = 0;

  Msa() = default;
  // pairwise seed (GapAssem.cpp:605-641)
  Msa(GapSeq* s1, GapSeq* s2) {
    s1->msa = this;
    s2->msa = this;
    seqs = {s1, s2};
    minoffset = std::min(s1->offset, s2->offset);
    ng_minofs = minoffset;
    length = std::max(s1->end_offset(), s2->end_offset()) - minoffset;
    ng_len = std::max(s1->end_ng_offset(), s2->end_ng_offset())
             - ng_minofs;
  }

  size_t count() const { return seqs.size(); }

  // (GSeqAlign::addSeq, GapAssem.cpp:694-716)
  void add_seq(GapSeq* s, long soffs, long ngofs) {
    s->offset = soffs;
    s->ng_ofs = ngofs;
    s->msa = this;
    seqs.push_back(s);
    if (soffs < minoffset) {
      length += minoffset - soffs;
      minoffset = soffs;
    }
    if (ngofs < ng_minofs) {
      ng_len += ng_minofs - ngofs;
      ng_minofs = ngofs;
    }
    if (s->end_offset() - minoffset > length)
      length = s->end_offset() - minoffset;
    if (s->end_ng_offset() - ng_minofs > ng_len)
      ng_len = s->end_ng_offset() - ng_minofs;
  }

  // Layout position of seq[pos] (GapAssem.cpp:721-725)
  long alpos_of(const GapSeq* seq, long pos) const {
    long gsum = 0;
    for (long j = 0; j <= pos; ++j) gsum += seq->gaps[(size_t)j];
    return seq->offset + pos + gsum;
  }

  // Propagate a gap through every member (GSeqAlign::injectGap,
  // GapAssem.cpp:720-753)
  void inject_gap(GapSeq* seq, long pos, int32_t xgap) {
    long alpos = alpos_of(seq, pos);
    for (GapSeq* s : seqs) {
      long spos;
      if (s == seq) {
        spos = pos;
      } else {
        if (s->offset >= alpos) {
          s->offset += xgap;
          continue;
        }
        spos = s->find_walk_pos(alpos);
        if (spos >= s->seqlen) continue;
      }
      s->add_gap(spos, xgap);
    }
    length += xgap;
  }

  // Merge another MSA through the shared sequence (GSeqAlign::addAlign,
  // GapAssem.cpp:645-690): RC on strand mismatch, bidirectional
  // per-position gap diff, then absorb the other members.
  void add_align(GapSeq* seq, Msa* omsa, GapSeq* oseq) {
    if (seq->seqlen != oseq->seqlen)
      throw PwErr(sformat(
          "GSeqAlign Error: invalid merge %s(len %ld) vs %s(len %ld)\n",
          seq->name.c_str(), seq->seqlen, oseq->name.c_str(),
          oseq->seqlen));
    if (seq->revcompl != oseq->revcompl) omsa->rev_complement();
    for (long i = 0; i < seq->seqlen; ++i) {
      int32_t d = seq->gap(i) - oseq->gap(i);
      if (d > 0)
        omsa->inject_gap(oseq, i, d);
      else if (d < 0)
        inject_gap(seq, i, -d);
    }
    for (GapSeq* s : omsa->seqs) {
      if (s == oseq) continue;
      add_seq(s, seq->offset + s->offset - oseq->offset,
              seq->ng_ofs + s->ng_ofs - oseq->ng_ofs);
    }
  }

  // (GSeqAlign::revComplement, GapAssem.cpp:998-1004)
  void rev_complement() {
    for (GapSeq* s : seqs) s->rev_complement(length);
    std::stable_sort(seqs.begin(), seqs.end(),
                     [](const GapSeq* a, const GapSeq* b) {
                       return a->offset < b->offset;
                     });
  }

  // (GSeqAlign::finalize, GapAssem.cpp:1006-1012)
  void finalize() {
    for (GapSeq* s : seqs) {
      if (s->seq.empty())
        throw PwErr(sformat("Error: sequence for %s not loaded!\n",
                            s->name.c_str()));
      if (!s->has_flag(FLAG_PREPPED)) s->prep_seq();
    }
  }

  // (GSeqAlign::writeMSA, GapAssem.cpp:1039-1046)
  void write_msa(FILE* f, int linelen = 60) {
    finalize();
    for (GapSeq* s : seqs) s->print_mfasta(f, linelen);
  }

  // Debug layout view (GSeqAlign::print, GapAssem.cpp:1013-1037)
  void print_layout(FILE* f, char sep = '\0') {
    finalize();
    size_t width = 0;
    for (GapSeq* s : seqs) width = std::max(width, s->name.size());
    if (sep) {
      fprintf(f, "%*s   ", (int)width, "");
      for (long i = 0; i < length; ++i) fputc(sep, f);
      fputc('\n', f);
    }
    for (GapSeq* s : seqs) {
      fprintf(f, "%*s %c ", (int)width, s->name.c_str(),
              s->revcompl == 1 ? '-' : '+');
      s->print_gapped_seq(f, minoffset);
    }
  }
};

// GASeq::revComplement within a layout (GapAssem.cpp:366-392) — defined
// after Msa because it reads the owning MSA's layout fields.
inline void GapSeq::rev_complement(long alignlen) {
  if (alignlen > 0) {
    offset = alignlen - end_offset();
    if (msa != nullptr) {
      ng_ofs = msa->ng_len - end_ng_offset();
      if (msa->minoffset > offset) msa->minoffset = offset;
      if (msa->ng_minofs > ng_ofs) msa->ng_minofs = ng_ofs;
    }
  }
  revcompl = revcompl ? 0 : 1;
  if ((long)seq.size() == seqlen) reverse_complement_bases();
  reverse_gaps();
}

}  // namespace pwnative
