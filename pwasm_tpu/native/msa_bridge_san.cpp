// Sanitizer driver for the progressive-MSA ctypes bridge (the pw_msa_*
// C ABI in fastparse.cpp): exercises new/add/reset/refine/write/free —
// including the skip-bad-lines rejection path, the lazy query-change
// release, and the warning capture — under ASan/UBSan via `make
// memcheck`.  The Python test suite drives the same ABI unsanitized
// (tests/test_native_msa_bridge.py); this catches memory bugs there.
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

extern "C" {
void* pw_msa_new();
void pw_msa_free(void*);
void pw_msa_reset(void*);
int64_t pw_msa_count(void*);
void pw_msa_contig(void*, char*, int32_t);
int pw_msa_add(void*, const char*, const uint8_t*, int64_t, int64_t,
               int32_t, const char*, const uint8_t*, int64_t, int64_t,
               const int32_t*, int64_t, const int32_t*, int64_t, int64_t,
               char*, int32_t);
int pw_msa_refine(void*, int32_t, int32_t, const char*, char*, int32_t);
int pw_msa_write(void*, int32_t, const char*, const char*, int32_t,
                 int32_t, const char*, char*, int32_t);
}

int main() {
  char err[4096];
  void* h = pw_msa_new();
  const std::string q1 = "ACGTACGTACGTACGTACGTACGTACGT";
  // first query: seed + one merge (one alignment has a target gap)
  int rc = pw_msa_add(h, "t1:0-28+", (const uint8_t*)q1.data(),
                      (int64_t)q1.size(), 0, 0, "q1",
                      (const uint8_t*)q1.data(), (int64_t)q1.size(),
                      (int64_t)q1.size(), nullptr, 0, nullptr, 0, 1, err,
                      sizeof err);
  assert(rc == 0);
  const int32_t tg[2] = {14, 2};
  rc = pw_msa_add(h, "t2:0-30+", (const uint8_t*)q1.data(),
                  (int64_t)q1.size(), 0, 0, "q1", nullptr, 0,
                  (int64_t)q1.size(), nullptr, 0, tg, 1, 2, err,
                  sizeof err);
  assert(rc == 0 && pw_msa_count(h) == 3);
  // rejected add: out-of-range gap position fails before any mutation
  const int32_t badg[2] = {999, 2};
  rc = pw_msa_add(h, "t3:0-28+", (const uint8_t*)q1.data(),
                  (int64_t)q1.size(), 0, 0, "q1", nullptr, 0,
                  (int64_t)q1.size(), badg, 1, nullptr, 0, 3, err,
                  sizeof err);
  assert(rc == 1 && strstr(err, "invalid gap position"));
  assert(pw_msa_count(h) == 3);
  // query change: lazy reset keeps the old MSA until a successful add
  pw_msa_reset(h);
  assert(pw_msa_count(h) == 3);
  const std::string q2 = "TTTTCCCCGGGGAAAA";
  rc = pw_msa_add(h, "u1:0-16-", (const uint8_t*)q2.data(),
                  (int64_t)q2.size(), 0, 1, "q2",
                  (const uint8_t*)q2.data(), (int64_t)q2.size(),
                  (int64_t)q2.size(), nullptr, 0, nullptr, 0, 1, err,
                  sizeof err);
  assert(rc == 0 && pw_msa_count(h) == 2);
  char contig[256];
  pw_msa_contig(h, contig, sizeof contig);
  assert(contig[0] != '\0');
  rc = pw_msa_refine(h, 1, 1, "san_msa_warn.tmp", err, sizeof err);
  assert(rc == 0);
  for (int what = 0; what <= 4; ++what) {
    rc = pw_msa_write(h, what, "san_msa_out.tmp", contig, 1, 1,
                      "san_msa_warn.tmp", err, sizeof err);
    assert(rc == 0);
  }
  pw_msa_free(h);
  remove("san_msa_out.tmp");
  remove("san_msa_warn.tmp");
  printf("msa bridge sanitizer run OK\n");
  return 0;
}
