// Sanitizer driver for the progressive-MSA ctypes bridge (the pw_msa_*
// C ABI in fastparse.cpp): exercises new/add/reset/refine/write/free —
// including the skip-bad-lines rejection path, the lazy query-change
// release, and the warning capture — under ASan/UBSan via `make
// memcheck`.  The Python test suite drives the same ABI unsanitized
// (tests/test_native_msa_bridge.py); this catches memory bugs there.
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

#include <vector>

#include "pafreport_util.h"  // best_char_from_counts: the one vote rule

extern "C" {
void* pw_msa_new();
void pw_msa_free(void*);
void pw_msa_reset(void*);
int64_t pw_msa_count(void*);
void pw_msa_contig(void*, char*, int32_t);
int pw_msa_add(void*, const char*, const uint8_t*, int64_t, int64_t,
               int32_t, const char*, const uint8_t*, int64_t, int64_t,
               const int32_t*, int64_t, const int32_t*, int64_t, int64_t,
               char*, int32_t);
int pw_msa_refine(void*, int32_t, int32_t, const char*, char*, int32_t);
int pw_msa_write(void*, int32_t, const char*, const char*, int32_t,
                 int32_t, const char*, char*, int32_t);
void pw_msa_dims(void*, int64_t*);
int pw_msa_prepare_device(void*, const char*, char*, int32_t);
int pw_msa_render_pileup(void*, int8_t*, int64_t, int64_t, char*,
                         int32_t);
int pw_msa_refine_external(void*, const int32_t*, const uint8_t*,
                           int64_t, int32_t, int32_t, const char*,
                           char*, int32_t);
}

int main() {
  char err[4096];
  void* h = pw_msa_new();
  const std::string q1 = "ACGTACGTACGTACGTACGTACGTACGT";
  // first query: seed + one merge (one alignment has a target gap)
  int rc = pw_msa_add(h, "t1:0-28+", (const uint8_t*)q1.data(),
                      (int64_t)q1.size(), 0, 0, "q1",
                      (const uint8_t*)q1.data(), (int64_t)q1.size(),
                      (int64_t)q1.size(), nullptr, 0, nullptr, 0, 1, err,
                      sizeof err);
  assert(rc == 0);
  const int32_t tg[2] = {14, 2};
  rc = pw_msa_add(h, "t2:0-30+", (const uint8_t*)q1.data(),
                  (int64_t)q1.size(), 0, 0, "q1", nullptr, 0,
                  (int64_t)q1.size(), nullptr, 0, tg, 1, 2, err,
                  sizeof err);
  assert(rc == 0 && pw_msa_count(h) == 3);
  // rejected add: out-of-range gap position fails before any mutation
  const int32_t badg[2] = {999, 2};
  rc = pw_msa_add(h, "t3:0-28+", (const uint8_t*)q1.data(),
                  (int64_t)q1.size(), 0, 0, "q1", nullptr, 0,
                  (int64_t)q1.size(), badg, 1, nullptr, 0, 3, err,
                  sizeof err);
  assert(rc == 1 && strstr(err, "invalid gap position"));
  assert(pw_msa_count(h) == 3);
  // query change: lazy reset keeps the old MSA until a successful add
  pw_msa_reset(h);
  assert(pw_msa_count(h) == 3);
  const std::string q2 = "TTTTCCCCGGGGAAAA";
  rc = pw_msa_add(h, "u1:0-16-", (const uint8_t*)q2.data(),
                  (int64_t)q2.size(), 0, 1, "q2",
                  (const uint8_t*)q2.data(), (int64_t)q2.size(),
                  (int64_t)q2.size(), nullptr, 0, nullptr, 0, 1, err,
                  sizeof err);
  assert(rc == 0 && pw_msa_count(h) == 2);
  char contig[256];
  pw_msa_contig(h, contig, sizeof contig);
  assert(contig[0] != '\0');
  rc = pw_msa_refine(h, 1, 1, "san_msa_warn.tmp", err, sizeof err);
  assert(rc == 0);
  for (int what = 0; what <= 4; ++what) {
    rc = pw_msa_write(h, what, "san_msa_out.tmp", contig, 1, 1,
                      "san_msa_warn.tmp", err, sizeof err);
    assert(rc == 0);
  }
  pw_msa_free(h);

  // device-consensus delegation surface: geometry-only build, pileup
  // render, external counts+votes (host-computed here, same contract
  // as the kernel's), then writers
  h = pw_msa_new();
  rc = pw_msa_add(h, "t1:0-28+", (const uint8_t*)q1.data(),
                  (int64_t)q1.size(), 0, 0, "q1",
                  (const uint8_t*)q1.data(), (int64_t)q1.size(),
                  (int64_t)q1.size(), nullptr, 0, nullptr, 0, 1, err,
                  sizeof err);
  assert(rc == 0);
  rc = pw_msa_add(h, "t2:0-30+", (const uint8_t*)q1.data(),
                  (int64_t)q1.size(), 0, 0, "q1", nullptr, 0,
                  (int64_t)q1.size(), nullptr, 0, tg, 1, 2, err,
                  sizeof err);
  assert(rc == 0);
  rc = pw_msa_prepare_device(h, "san_msa_warn.tmp", err, sizeof err);
  assert(rc == 0);
  int64_t dims[2];
  pw_msa_dims(h, dims);
  assert(dims[0] == 3 && dims[1] > 0);
  std::vector<int8_t> mat((size_t)(dims[0] * dims[1]));
  rc = pw_msa_render_pileup(h, mat.data(), dims[0], dims[1], err,
                            sizeof err);
  assert(rc == 0);
  std::vector<int32_t> counts((size_t)dims[1] * 6, 0);
  std::vector<uint8_t> votes((size_t)dims[1], 0);
  for (int64_t c = 0; c < dims[1]; ++c) {
    int32_t layer = 0;
    for (int64_t r = 0; r < dims[0]; ++r) {
      int8_t code = mat[(size_t)(r * dims[1] + c)];
      if (code >= 0 && code < 6) {
        counts[(size_t)c * 6 + code]++;
        ++layer;
      }
    }
    // the kernel-contract vote: the single shared bestChar rule
    votes[(size_t)c] = (uint8_t)pwnative::best_char_from_counts(
        &counts[(size_t)c * 6], layer);
  }
  rc = pw_msa_refine_external(h, counts.data(), votes.data(), dims[1],
                              0, 1, "san_msa_warn.tmp", err, sizeof err);
  assert(rc == 0);
  rc = pw_msa_write(h, 1, "san_msa_out.tmp", "q1", 0, 1,
                    "san_msa_warn.tmp", err, sizeof err);
  assert(rc == 0);
  // dims-mismatch guard: refuse rather than read out of bounds
  rc = pw_msa_refine_external(h, counts.data(), votes.data(),
                              dims[1] + 1, 0, 1, "san_msa_warn.tmp",
                              err, sizeof err);
  assert(rc == -1);
  pw_msa_free(h);
  remove("san_msa_out.tmp");
  remove("san_msa_warn.tmp");
  printf("msa bridge sanitizer run OK\n");
  return 0;
}
