// pwasm-tpu native host core: fast per-alignment diff extraction and the
// single-core banded Gotoh CPU baseline.
//
// C ABI consumed through ctypes (pwasm_tpu/native/__init__.py).  The
// extraction mirrors pwasm_tpu/core/events.py (itself the behavior spec
// of the reference PAFAlignment constructor, pafreport.cpp:477-719):
// cs-string walk reconstructing the target and emitting S/I/D events with
// adjacent-substitution merging and reverse-strand fixups, CIGAR walk
// collecting gap lists, and the length cross-validations.  Parity between
// this and the Python extractor is enforced by tests/test_native.py.
//
// Layout contracts (all int32 little-endian):
//   event record  : evt(0=S,1=I,2=D), rloc, tloc, evtlen,
//                   bases_off, bases_len, sub_off, sub_len,
//                   tctx_off, tctx_len                      (10 fields)
//   gap record    : which(0=query/rgap, 1=target/tgap), pos, len
// Variable-length bytes (event bases / substituted bases / target
// context) live in a caller-provided arena buffer.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cctype>
#include <vector>
#include <string>

#include "pafreport_util.h"  // best_char_from_counts (the one C++ copy)

namespace {

constexpr int EV_FIELDS = 10;

struct Ev {
  int32_t evt, rloc, tloc, evtlen;
  std::string bases, sub, tctx;
};

char comp(char c) {
  switch (toupper((unsigned char)c)) {
    case 'A': return islower((unsigned char)c) ? 't' : 'T';
    case 'C': return islower((unsigned char)c) ? 'g' : 'G';
    case 'G': return islower((unsigned char)c) ? 'c' : 'C';
    case 'T': case 'U': return islower((unsigned char)c) ? 'a' : 'A';
    case 'M': return islower((unsigned char)c) ? 'k' : 'K';
    case 'K': return islower((unsigned char)c) ? 'm' : 'M';
    case 'R': return islower((unsigned char)c) ? 'y' : 'Y';
    case 'Y': return islower((unsigned char)c) ? 'r' : 'R';
    case 'V': return islower((unsigned char)c) ? 'b' : 'B';
    case 'B': return islower((unsigned char)c) ? 'v' : 'V';
    case 'H': return islower((unsigned char)c) ? 'd' : 'D';
    case 'D': return islower((unsigned char)c) ? 'h' : 'H';
    default:  return c;  // W, S, N, X map to themselves
  }
}

void revcomp_inplace(std::string& s) {
  std::string out(s.rbegin(), s.rend());
  for (auto& c : out) c = comp(c);
  s = out;
}

// error codes surfaced to the Python wrapper, which formats the exact
// reference-parity messages (pwasm_tpu/core/events.py constants)
enum ErrCode {
  OK = 0,
  ERR_CS_PARSE = 1,       // err_info[0] = cs position
  ERR_BASE_MISMATCH = 2,  // err_info[0] = q_pos, err_info[1] = qch
  ERR_SPLICE = 3,
  ERR_CS_OP = 4,          // err_info[0] = position after the op char
  ERR_CIGAR_PARSE = 5,    // err_info[0] = cigar position
  ERR_CIGAR_OP = 6,       // err_info[0] = op char, err_info[1] = count
  ERR_TSEQ_LEN = 7,       // err_info[0] = tpos
  ERR_REF_LEN = 8,        // err_info[0] = qpos
  ERR_COORDS = 9,         // negative/inverted alignment spans
  ERR_GROW = 100,         // output buffers too small; caller retries
};

bool parse_uint(const char* s, int& i, long& out) {
  int start = i;
  long v = 0;
  while (s[i] >= '0' && s[i] <= '9') {
    v = v * 10 + (s[i] - '0');
    ++i;
  }
  out = v;
  return i != start;
}

}  // namespace

extern "C" {

// Returns an ErrCode.  out_sizes = [tseq_len, n_events, arena_used,
// n_gaps, n_softclip_ops]; n_softclip_ops is valid even on error (S ops
// seen before the failure, so the wrapper can replay the reference's
// per-op warnings in order).  err_info carries per-code details.
int pw_extract(const char* cs, const char* cigar,
               const uint8_t* ref, int32_t ref_len,
               int32_t offset, int32_t reverse, int32_t r_len,
               int32_t t_alnstart, int32_t t_alnend,
               int32_t r_alnstart, int32_t r_alnend,
               uint8_t* tseq_out, int32_t tseq_cap,
               int32_t* ev_out, int32_t ev_cap,
               uint8_t* arena, int32_t arena_cap,
               int32_t* gaps_out, int32_t gap_cap,
               int32_t* out_sizes, int32_t* err_info) {
  int32_t n_softclip = 0;
  out_sizes[4] = 0;
  err_info[0] = err_info[1] = 0;
#define FAIL(code, a, b) \
  do { out_sizes[4] = n_softclip; err_info[0] = (int32_t)(a); \
       err_info[1] = (int32_t)(b); return (code); } while (0)
  // belt guard (the Python caller validates first): inverted/negative
  // spans must never reach the size computations below
  if (offset < 0 || r_len < 0 || ref_len < 0 || t_alnstart < 0 ||
      t_alnend < t_alnstart || r_alnstart < 0 || r_alnend < r_alnstart)
    FAIL(ERR_COORDS, 0, 0);
  std::string tseq;
  tseq.reserve((size_t)(t_alnend - t_alnstart) + 2);
  std::vector<Ev> evs;
  const int eff_t_len = t_alnend - t_alnstart;
  long qpos = 0, tpos = 0;
  int i = 0;

  // ---- cs walk
  while (cs[i] != '\0') {
    char op = cs[i++];
    if (op == ':') {
      long cl;
      if (!parse_uint(cs, i, cl)) FAIL(ERR_CS_PARSE, i, 0);
      if (offset + qpos + cl > ref_len)
        FAIL(ERR_CS_PARSE, i, 0);
      tseq.append((const char*)ref + offset + qpos, (size_t)cl);
      qpos += cl;
      tpos += cl;
    } else if (op == '*') {
      if (cs[i] == '\0' || cs[i + 1] == '\0')
        FAIL(ERR_CS_PARSE, i, 0);
      char tch = (char)toupper((unsigned char)cs[i]);
      char qch = (char)toupper((unsigned char)cs[i + 1]);
      i += 2;
      long q_pos = offset + qpos;
      if (q_pos >= ref_len || qch != (char)ref[q_pos])
        FAIL(ERR_BASE_MISMATCH, q_pos, qch);
      if (!evs.empty() && evs.back().evt == 0 &&
          evs.back().rloc == q_pos - (long)evs.back().bases.size()) {
        evs.back().bases.push_back(tch);
        evs.back().sub.push_back(qch);
        // NB: evtlen stays 1 for merged substitutions (reference quirk)
      } else {
        Ev e;
        e.evt = 0;
        e.evtlen = 1;
        e.rloc = (int32_t)q_pos;
        e.tloc = (int32_t)tpos;
        e.bases.push_back(tch);
        e.sub.push_back(qch);
        evs.push_back(std::move(e));
      }
      tseq.push_back((char)tolower((unsigned char)tch));
      ++qpos;
      ++tpos;
    } else if (op == '-') {  // bases present only in the target: Insertion
      long s_pos = tpos;
      while (isalpha((unsigned char)cs[i])) {
        tseq.push_back((char)tolower((unsigned char)cs[i]));
        ++i;
        ++tpos;
      }
      long e_len = tpos - s_pos;
      long q_pos = offset + qpos;
      Ev e;
      e.evt = 1;
      e.evtlen = (int32_t)e_len;
      e.rloc = (int32_t)q_pos;
      e.tloc = (int32_t)s_pos;
      e.bases = tseq.substr(tseq.size() - (size_t)e_len);
      if (reverse) {
        revcomp_inplace(e.bases);
        e.rloc = (int32_t)(r_len - q_pos);
      }
      evs.push_back(std::move(e));
    } else if (op == '+') {  // query bases missing from target: Deletion
      long s_pos = qpos;
      while (isalpha((unsigned char)cs[i])) {
        ++i;
        ++qpos;
      }
      long e_len = qpos - s_pos;
      long q_pos = s_pos + offset;
      if (q_pos + e_len > ref_len)
        FAIL(ERR_CS_PARSE, i, 0);
      Ev e;
      e.evt = 2;
      e.evtlen = (int32_t)e_len;
      e.rloc = (int32_t)q_pos;
      e.tloc = (int32_t)tpos;
      e.bases.assign((const char*)ref + q_pos, (size_t)e_len);
      if (reverse) {
        revcomp_inplace(e.bases);
        e.rloc = (int32_t)(r_len - q_pos - e_len);
      }
      evs.push_back(std::move(e));
    } else if (op == '~') {
      FAIL(ERR_SPLICE, 0, 0);
    } else {
      FAIL(ERR_CS_OP, i, 0);
    }
  }

  // ---- context fill + reverse fixups
  const long tlen = (long)tseq.size();
  for (auto& e : evs) {
    long tc_start = e.tloc - 5;
    if (tc_start < 0) tc_start = 0;
    long evt_len = (e.evt == 2) ? 0 : e.evtlen;
    long tc_end = e.tloc + evt_len + 5;
    if (tc_end >= tlen) tc_end = tlen - 1;
    e.tctx = tseq.substr((size_t)tc_start, (size_t)(tc_end - tc_start));
    if (reverse) {
      revcomp_inplace(e.tctx);
      e.tloc = (int32_t)(tlen - e.tloc);
      if (e.evt == 0) {
        revcomp_inplace(e.bases);
        revcomp_inplace(e.sub);
        e.rloc = (int32_t)(r_len - e.rloc - (long)e.bases.size());
      }
    }
  }
  if (reverse) {
    std::vector<Ev> rev(evs.rbegin(), evs.rend());
    evs = std::move(rev);
  }

  // ---- CIGAR walk
  std::vector<int32_t> gaps;  // triples
  qpos = 0;
  tpos = 0;
  i = 0;
  while (cigar[i] != '\0') {
    long cl;
    if (!parse_uint(cigar, i, cl))
      FAIL(ERR_CIGAR_PARSE, i, 0);
    char cop = cigar[i];
    if (cop == '\0') FAIL(ERR_CIGAR_PARSE, i, 0);
    switch (cop) {
      case 'X': case 'M': case '=':
        tpos += cl;
        qpos += cl;
        break;
      case 'P': case 'H':
        break;
      case 'S':
        ++n_softclip;  // Python layer replays the per-op warning
        qpos += cl;
        break;
      case 'I': {
        long pos = reverse ? eff_t_len - tpos : tpos;
        gaps.push_back(1);
        gaps.push_back((int32_t)pos);
        gaps.push_back((int32_t)cl);
        qpos += cl;
        break;
      }
      case 'D': case 'N': {
        long pos = offset + qpos;
        if (reverse) pos = r_len - pos;
        gaps.push_back(0);
        gaps.push_back((int32_t)pos);
        gaps.push_back((int32_t)cl);
        tpos += cl;
        break;
      }
      default:
        FAIL(ERR_CIGAR_OP, (unsigned char)cop, cl);
    }
    ++i;
  }

  // ---- cross-validation
  if (eff_t_len != tpos || (long)tseq.size() != tpos)
    FAIL(ERR_TSEQ_LEN, tpos, 0);
  if (r_alnend - r_alnstart != qpos)
    FAIL(ERR_REF_LEN, qpos, 0);

  // ---- serialize
  if ((int32_t)tseq.size() > tseq_cap) return ERR_GROW;
  if ((int32_t)evs.size() * EV_FIELDS > ev_cap) return ERR_GROW;
  if ((int32_t)gaps.size() > gap_cap) return ERR_GROW;
  long arena_used = 0;
  for (auto& e : evs)
    arena_used += (long)(e.bases.size() + e.sub.size() + e.tctx.size());
  if (arena_used > arena_cap) return ERR_GROW;

  memcpy(tseq_out, tseq.data(), tseq.size());
  int32_t* p = ev_out;
  long aoff = 0;
  for (auto& e : evs) {
    p[0] = e.evt;
    p[1] = e.rloc;
    p[2] = e.tloc;
    p[3] = e.evtlen;
    p[4] = (int32_t)aoff;
    p[5] = (int32_t)e.bases.size();
    memcpy(arena + aoff, e.bases.data(), e.bases.size());
    aoff += (long)e.bases.size();
    p[6] = (int32_t)aoff;
    p[7] = (int32_t)e.sub.size();
    memcpy(arena + aoff, e.sub.data(), e.sub.size());
    aoff += (long)e.sub.size();
    p[8] = (int32_t)aoff;
    p[9] = (int32_t)e.tctx.size();
    memcpy(arena + aoff, e.tctx.data(), e.tctx.size());
    aoff += (long)e.tctx.size();
    p += EV_FIELDS;
  }
  if (!gaps.empty())
    memcpy(gaps_out, gaps.data(), gaps.size() * sizeof(int32_t));
  out_sizes[0] = (int32_t)tseq.size();
  out_sizes[1] = (int32_t)evs.size();
  out_sizes[2] = (int32_t)arena_used;
  out_sizes[3] = (int32_t)(gaps.size() / 3);
  out_sizes[4] = n_softclip;
  return OK;
}
#undef FAIL

// Batched extraction (ROADMAP item 5): ONE ffi crossing extracts a
// whole flush of alignments — the per-alignment ctypes marshalling
// around pw_extract was the last unbatched in-loop host term.  Inputs
// arrive as NUL-separated blobs + int64 start offsets (cs/cigar), an
// array of per-item query pointers (items need not share one query),
// and a 7-int32 param row per item (offset, reverse, r_len,
// t_alnstart, t_alnend, r_alnstart, r_alnend).  Outputs pack
// back-to-back into the shared buffers with int64 offset arrays
// (tseq/arena in bytes, ev/gaps in int32 slots); sizes_out holds each
// item's 5-field pw_extract out_sizes row.  Items extract strictly IN
// ORDER and the call stops at the first failure, exactly like
// pw_msa_add_batch: on any non-zero code *done_out is the count of
// items fully extracted before the failing one and err_info carries
// that item's details (ERR_GROW included — the caller re-marshals
// with larger buffers and retries the whole flush).
int pw_extract_batch(int64_t n,
                     const char* cs_blob, const int64_t* cs_off,
                     const char* cigar_blob, const int64_t* cigar_off,
                     const uint8_t* const* refs, const int32_t* ref_lens,
                     const int32_t* params,
                     uint8_t* tseq_out, int64_t tseq_cap,
                     int64_t* tseq_off_out,
                     int32_t* ev_out, int64_t ev_cap,
                     int64_t* ev_off_out,
                     uint8_t* arena_out, int64_t arena_cap,
                     int64_t* arena_off_out,
                     int32_t* gaps_out, int64_t gap_cap,
                     int64_t* gap_off_out,
                     int32_t* sizes_out, int32_t* err_info,
                     int64_t* done_out) {
  *done_out = 0;
  tseq_off_out[0] = 0;
  ev_off_out[0] = 0;
  arena_off_out[0] = 0;
  gap_off_out[0] = 0;
  const int64_t cap32 = 0x7fffffff;
  for (int64_t i = 0; i < n; ++i) {
    const int32_t* p = params + 7 * i;
    int64_t tq = tseq_off_out[i], ev = ev_off_out[i],
            ar = arena_off_out[i], gp = gap_off_out[i];
    int64_t tc = tseq_cap - tq, ec = ev_cap - ev, ac = arena_cap - ar,
            gc = gap_cap - gp;
    if (tc <= 0 || ec <= 0 || ac <= 0 || gc <= 0) return ERR_GROW;
    int rc = pw_extract(
        cs_blob + cs_off[i], cigar_blob + cigar_off[i], refs[i],
        ref_lens[i], p[0], p[1], p[2], p[3], p[4], p[5], p[6],
        tseq_out + tq, (int32_t)(tc > cap32 ? cap32 : tc),
        ev_out + ev, (int32_t)(ec > cap32 ? cap32 : ec),
        arena_out + ar, (int32_t)(ac > cap32 ? cap32 : ac),
        gaps_out + gp, (int32_t)(gc > cap32 ? cap32 : gc),
        sizes_out + 5 * i, err_info);
    if (rc != 0) return rc;
    tseq_off_out[i + 1] = tq + sizes_out[5 * i];
    ev_off_out[i + 1] = ev + (int64_t)EV_FIELDS * sizes_out[5 * i + 1];
    arena_off_out[i + 1] = ar + sizes_out[5 * i + 2];
    gap_off_out[i + 1] = gp + (int64_t)3 * sizes_out[5 * i + 3];
    ++*done_out;
  }
  return OK;
}

// Single-core banded Gotoh over int8 base codes — the honest CPU baseline
// for the TPU banded-DP benchmarks (same recurrence as
// pwasm_tpu/ops/banded_dp.py, no Ix<->Iy adjacency).  Returns the global
// score at (m, t_len), or NEG if t_len's end diagonal is out of band.
int32_t pw_banded_gotoh(const int8_t* q, int32_t m,
                        const int8_t* t, int32_t t_len,
                        int32_t band, int32_t dlo,
                        int32_t match, int32_t mismatch,
                        int32_t gap_open, int32_t gap_extend) {
  const int32_t NEG = -(1 << 30);
  const int32_t go = gap_open + gap_extend;
  const int32_t ge = gap_extend;
  const int32_t n = t_len;
  std::vector<int32_t> M(band), Ix(band), Iy(band);
  std::vector<int32_t> M2(band), Ix2(band), Iy2(band);
  for (int b = 0; b < band; ++b) {
    int j = dlo + b;
    M[b] = (j == 0) ? 0 : NEG;
    Iy[b] = (j >= 1 && j <= n) ? -(go + (j - 1) * ge) : NEG;
    Ix[b] = NEG;
  }
  for (int i = 1; i <= m; ++i) {
    const int8_t qi = q[i - 1];
    for (int b = 0; b < band; ++b) {
      int j = i + dlo + b;
      bool valid = (j >= 1 && j <= n);
      int32_t mnew = NEG;
      if (valid) {
        int32_t diag = M[b];
        if (Ix[b] > diag) diag = Ix[b];
        if (Iy[b] > diag) diag = Iy[b];
        int32_t s = (qi == t[j - 1] && qi < 4) ? match : -mismatch;
        mnew = diag + s;
      }
      M2[b] = mnew;
      int32_t upM = (b + 1 < band) ? M[b + 1] : NEG;
      int32_t upIx = (b + 1 < band) ? Ix[b + 1] : NEG;
      int32_t ix = upM - go;
      if (upIx - ge > ix) ix = upIx - ge;
      if (j == 0) ix = -(go + (i - 1) * ge);
      if (j < 0 || j > n) ix = NEG;
      Ix2[b] = ix;
      int32_t iy = NEG;
      if (valid && b > 0) {
        int32_t a = M2[b - 1] - go;
        int32_t c = Iy2[b - 1] - ge;
        iy = (a > c) ? a : c;
      }
      Iy2[b] = iy;
    }
    M.swap(M2);
    Ix.swap(Ix2);
    Iy.swap(Iy2);
  }
  int b_end = n - m - dlo;
  if (b_end < 0 || b_end >= band) return NEG;
  int32_t best = M[b_end];
  if (Ix[b_end] > best) best = Ix[b_end];
  if (Iy[b_end] > best) best = Iy[b_end];
  return best;
}

// Batched wrapper over contiguous (T, n_pad) targets.
void pw_banded_gotoh_batch(const int8_t* q, int32_t m,
                           const int8_t* ts, const int32_t* t_lens,
                           int32_t T, int32_t n_pad,
                           int32_t band, int32_t dlo,
                           int32_t match, int32_t mismatch,
                           int32_t gap_open, int32_t gap_extend,
                           int32_t* out) {
  for (int32_t k = 0; k < T; ++k) {
    out[k] = pw_banded_gotoh(q, m, ts + (size_t)k * n_pad, t_lens[k],
                             band, dlo, match, mismatch, gap_open,
                             gap_extend);
  }
}

// Single-core consensus vote — the honest CPU baseline for the TPU
// consensus kernel and the native fast path of the MSA engine's column
// vote.  bestChar's stable-sort + '-'/'N'-yield rule (GapAssem.cpp:
// 1048-1069, quirk SURVEY.md §2.5.10), delegating to the shared closed
// form in pafreport_util.h (same rule as align/msa.py
// best_char_from_counts).  Zero coverage -> 0.
static inline uint8_t vote_from_counts(const int32_t* c, int32_t layers) {
  return (uint8_t)pwnative::best_char_from_counts(c, layers);
}

// Pileup variant: (depth, cols) int8 base codes, 0..5 = A C G T N gap;
// codes outside 0..5 contribute nothing (padding).
void pw_consensus_vote(const int8_t* pileup, int32_t depth, int32_t cols,
                       uint8_t* out) {
  std::vector<int32_t> counts((size_t)cols * 6, 0);
  for (int32_t d = 0; d < depth; ++d) {
    const int8_t* row = pileup + (size_t)d * cols;
    for (int32_t c = 0; c < cols; ++c) {
      int8_t v = row[c];
      if (v >= 0 && v < 6) counts[(size_t)c * 6 + v]++;
    }
  }
  for (int32_t c = 0; c < cols; ++c) {
    const int32_t* cc = &counts[(size_t)c * 6];
    int32_t layers = cc[0] + cc[1] + cc[2] + cc[3] + cc[4] + cc[5];
    out[c] = vote_from_counts(cc, layers);
  }
}

// Counts variant for the MSA engine (counts already accumulated):
// counts is (cols, 6) int32, layers (cols,) int32.
void pw_consensus_vote_counts(const int32_t* counts, const int32_t* layers,
                              int32_t cols, uint8_t* out) {
  for (int32_t c = 0; c < cols; ++c)
    out[c] = vote_from_counts(counts + (size_t)c * 6, layers[c]);
}

// ---------------------------------------------------------------------------
// FASTA faidx-style index + fetch + base-code packing (SURVEY.md §2.4.2,
// the gclib GFastaIndex/GFaSeqGet capability, pafreport.cpp:255,346).
// ---------------------------------------------------------------------------

// Streaming index build: one pass over the file, recording for every
// record its id, sequence length (whitespace excluded — exactly the bytes
// a fetch returns), first-sequence-byte offset and one-past-end offset,
// plus the per-record line geometry so the caller can persist a
// samtools-compatible .fai without re-reading the file: linebases /
// linewidth of the first line and a uniformity flag that is 1 only when
// EVERY line of the record is describable by that geometry (all full
// lines exactly linebases bases + the same terminator, no interior
// whitespace, no blank lines, at most one final short line whose
// terminator may be missing only at end of record).
// Duplicate ids keep the FIRST record (dict-insert semantics of the
// Python FastaFile; dedup is done by the Python wrapper which sees
// names).  Entry layout: 8 int64 per record
//   [name_off, name_len, seqlen, seq_start, end, linebases, linewidth,
//    uniform]
// with names concatenated into name_arena.  Returns the record count,
// -1 on open failure, or -(2 + needed_records) when ent_cap/arena_cap is
// too small (caller grows and retries).
int64_t pw_fasta_index(const char* path, int64_t* entries, int64_t ent_cap,
                       uint8_t* name_arena, int64_t arena_cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  std::vector<char> buf(1 << 20);
  int64_t nrec = 0, arena_used = 0, pos = 0;
  int64_t seqlen = 0, seq_start = 0;
  bool have_rec = false, overflow = false;
  bool at_line_start = true, in_header = false, header_name_done = false;
  // line-geometry state for the current record
  int64_t lb = -1, lw = -1;        // first line's bases / total bytes
  int64_t cur_bases = 0, pend_ws = 0;
  bool uniform = true, short_seen = false, line_open = false;
  std::string name;
  auto close_line = [&](bool has_newline) {
    // a line ends: check it against the record's first-line geometry
    int64_t bytes = cur_bases + pend_ws + (has_newline ? 1 : 0);
    if (short_seen) uniform = false;  // a short line was not the last
    if (cur_bases == 0) {
      uniform = false;                // blank line inside the window
    } else if (lb < 0) {
      lb = cur_bases;
      lw = bytes;
      if (!has_newline) uniform = false;  // single unterminated line:
      // lw would include no terminator, underiving the window
      if (lw <= lb) uniform = false;
    } else if (cur_bases == lb && bytes == lw && has_newline) {
      // a regular full line
    } else if (!has_newline && bytes == cur_bases && cur_bases <= lb) {
      short_seen = true;   // unterminated final line at end of record
    } else if (cur_bases < lb && bytes - cur_bases == lw - lb) {
      short_seen = true;   // terminated short line: final only
    } else {
      uniform = false;
    }
    cur_bases = 0;
    pend_ws = 0;
    line_open = false;
  };
  auto flush_rec = [&](int64_t end_pos) {
    if (!have_rec) return;
    if (in_header) {  // header line hit EOF with no newline: empty seq
      seq_start = end_pos;
      seqlen = 0;
    }
    if (line_open) close_line(false);
    if (lb < 1 || lw <= lb || seqlen == 0) uniform = false;
    if (nrec < ent_cap &&
        arena_used + (int64_t)name.size() <= arena_cap) {
      int64_t* e = entries + nrec * 8;
      e[0] = arena_used;
      e[1] = (int64_t)name.size();
      e[2] = seqlen;
      e[3] = seq_start;
      e[4] = end_pos;
      e[5] = lb;
      e[6] = lw;
      e[7] = uniform ? 1 : 0;
      memcpy(name_arena + arena_used, name.data(), name.size());
      arena_used += (int64_t)name.size();
    } else {
      overflow = true;
    }
    ++nrec;
  };
  size_t got;
  while ((got = fread(buf.data(), 1, buf.size(), f)) > 0) {
    for (size_t i = 0; i < got; ++i) {
      char c = buf[i];
      if (at_line_start && c == '>') {
        flush_rec(pos);
        have_rec = true;
        name.clear();
        seqlen = 0;
        lb = lw = -1;
        cur_bases = pend_ws = 0;
        uniform = true;
        short_seen = false;
        line_open = false;
        in_header = true;
        header_name_done = false;
        at_line_start = false;
        ++pos;
        continue;
      }
      if (in_header) {
        if (c == '\n') {
          in_header = false;
          at_line_start = true;
          seq_start = pos + 1;
        } else if (!header_name_done) {
          if (isspace((unsigned char)c)) {
            if (!name.empty()) header_name_done = true;
          } else {
            name.push_back(c);
          }
        }
      } else {
        at_line_start = (c == '\n');
        if (have_rec) {
          if (c == '\n') {
            close_line(true);
          } else if (isspace((unsigned char)c)) {
            line_open = true;
            ++pend_ws;
          } else {
            if (pend_ws > 0) uniform = false;  // interior whitespace
            line_open = true;
            ++cur_bases;
            ++seqlen;
          }
        }
      }
      ++pos;
    }
  }
  flush_rec(pos);
  fclose(f);
  if (overflow) return -(2 + nrec);
  return nrec;
}

// Fetch [seq_start, end) and strip ALL whitespace in place; returns the
// stripped length, or -1 on IO failure.  out must hold end - seq_start.
int64_t pw_fasta_fetch(const char* path, int64_t seq_start, int64_t end,
                       uint8_t* out) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  if (fseeko(f, (off_t)seq_start, SEEK_SET) != 0) { fclose(f); return -1; }
  int64_t want = end - seq_start;
  int64_t got = (int64_t)fread(out, 1, (size_t)want, f);
  fclose(f);
  int64_t w = 0;
  for (int64_t i = 0; i < got; ++i) {
    uint8_t c = out[i];
    if (!isspace(c)) out[w++] = c;
  }
  return w;
}

// Byte sequence -> int8 base codes (A0 C1 G2 T3 N4 gap5, U=T, case
// folded) — the native twin of pwasm_tpu.core.dna.encode.  The lookup
// table is built once at load time (ctypes calls release the GIL, so a
// lazily-initialized static would race).
static const struct EncTbl {
  int8_t t[256];
  EncTbl() {
    for (int i = 0; i < 256; ++i) t[i] = 4;  // N
    const char* bases = "ACGT";
    for (int k = 0; k < 4; ++k) {
      t[(unsigned char)bases[k]] = (int8_t)k;
      t[(unsigned char)tolower(bases[k])] = (int8_t)k;
    }
    t['U'] = 3; t['u'] = 3;
    t['-'] = 5; t['*'] = 5;
  }
} kEncTbl;

void pw_encode_codes(const uint8_t* seq, int64_t n, int8_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = kEncTbl.t[seq[i]];
}

// Pack int8 base codes (must be 0..3; callers map N/gap beforehand) into
// 2-bit form, 4 codes per byte, little-endian within the byte.  Length of
// out is ceil(n/4); trailing slots pad with 0.
void pw_pack_2bit(const int8_t* codes, int64_t n, uint8_t* out) {
  int64_t nb = (n + 3) / 4;
  for (int64_t b = 0; b < nb; ++b) {
    uint8_t v = 0;
    for (int k = 0; k < 4; ++k) {
      int64_t i = b * 4 + k;
      if (i < n) v |= (uint8_t)((codes[i] & 3) << (2 * k));
    }
    out[b] = v;
  }
}

// Unpack 2-bit form back to int8 codes.
void pw_unpack_2bit(const uint8_t* packed, int64_t n, int8_t* out) {
  for (int64_t i = 0; i < n; ++i)
    out[i] = (int8_t)((packed[i / 4] >> (2 * (i % 4))) & 3);
}


// Full-matrix Gotoh global alignment WITH traceback — the native form
// of the host oracle in ops/realign.py (full_gotoh_traceback), for the
// re-aligner's beyond-the-band fallback.  Tie-breaks are identical by
// construction: the diagonal argmax prefers M, then Ix, then Iy; the
// gap recurrences prefer open on ties (strict > for the extend bit).
// No Ix<->Iy adjacency (standard Gotoh).  Writes forward-order op codes
// (1=diag, 2=Ix consumes query, 3=Iy consumes target) into ops_out
// (capacity m+n) and the final score into *score_out; returns the op
// count, or -1 on allocation failure.  Work/memory: O(m*n) time, one
// uint8 pointer byte per cell (dm 2 bits | bx<<2 | by<<3), three
// rolling int64 rows.
int64_t pw_gotoh_traceback(const int8_t* q, int64_t m, const int8_t* t,
                           int64_t n, int32_t match, int32_t mismatch,
                           int32_t gap_open, int32_t gap_extend,
                           int8_t* ops_out, int64_t* score_out) {
  const int64_t NEG = -((int64_t)1 << 40);
  const int64_t ge = gap_extend, go = (int64_t)gap_open + gap_extend;
  std::vector<int64_t> Mp, Ip, Yp, Mc, Ic, Yc;
  std::vector<uint8_t> ptr;
  try {
    Mp.assign(n + 1, NEG); Ip.assign(n + 1, NEG); Yp.assign(n + 1, NEG);
    Mc.assign(n + 1, NEG); Ic.assign(n + 1, NEG); Yc.assign(n + 1, NEG);
    ptr.assign((size_t)(m + 1) * (size_t)(n + 1), 0);
  } catch (...) {
    return -1;
  }
  Mp[0] = 0;
  for (int64_t j = 1; j <= n; ++j) {
    Yp[j] = -(go + (j - 1) * ge);
    if (j > 1) ptr[j] |= 8;  // BY row 0
  }
  for (int64_t i = 1; i <= m; ++i) {
    uint8_t* prow = ptr.data() + (size_t)i * (size_t)(n + 1);
    Mc[0] = NEG; Yc[0] = NEG;
    Ic[0] = -(go + (i - 1) * ge);
    if (i > 1) prow[0] |= 4;  // BX col 0
    for (int64_t j = 1; j <= n; ++j) {
      int64_t s = (q[i - 1] == t[j - 1] && q[i - 1] < 4) ? match
                                                         : -mismatch;
      int64_t a = Mp[j - 1], b = Ip[j - 1], c = Yp[j - 1];
      uint8_t dm;
      int64_t diag;
      if (a >= b && a >= c) { dm = 0; diag = a; }
      else if (b >= c)      { dm = 1; diag = b; }
      else                  { dm = 2; diag = c; }
      Mc[j] = diag + s;
      int64_t op_sc = Mp[j] - go, ext_sc = Ip[j] - ge;
      uint8_t bx = ext_sc > op_sc ? 4 : 0;
      Ic[j] = ext_sc > op_sc ? ext_sc : op_sc;
      int64_t op2 = Mc[j - 1] - go, ext2 = Yc[j - 1] - ge;
      uint8_t by = ext2 > op2 ? 8 : 0;
      Yc[j] = ext2 > op2 ? ext2 : op2;
      prow[j] = (uint8_t)(dm | bx | by);
    }
    std::swap(Mp, Mc); std::swap(Ip, Ic); std::swap(Yp, Yc);
  }
  int64_t mv = Mp[n], xv = Ip[n], yv = Yp[n];
  int mat;
  if (mv >= xv && mv >= yv) mat = 0;
  else if (xv >= yv)        mat = 1;
  else                      mat = 2;
  int64_t best = mv > xv ? mv : xv;
  if (yv > best) best = yv;
  *score_out = best;
  // backward walk, then reverse into forward order
  int64_t i = m, j = n, k = 0;
  while (i > 0 || j > 0) {
    if (i == 0)      { ops_out[k++] = 3; --j; continue; }
    if (j == 0)      { ops_out[k++] = 2; --i; continue; }
    uint8_t p = ptr[(size_t)i * (size_t)(n + 1) + j];
    if (mat == 0)      { ops_out[k++] = 1; mat = p & 3; --i; --j; }
    else if (mat == 1) { ops_out[k++] = 2; mat = (p & 4) ? 1 : 0; --i; }
    else               { ops_out[k++] = 3; mat = (p & 8) ? 2 : 0; --j; }
  }
  for (int64_t a2 = 0, b2 = k - 1; a2 < b2; ++a2, --b2) {
    int8_t tmp = ops_out[a2]; ops_out[a2] = ops_out[b2]; ops_out[b2] = tmp;
  }
  return k;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Progressive-MSA engine bridge: the Python CLI delegates its -w /
// consensus MSA builds to the native engine (pafreport_msa.h) through
// this C ABI, mirroring cli.py msa_add / the end-of-run writer block of
// pafreport_main.cpp verbatim (byte parity with the Python engine is
// enforced by tests/test_native_cli.py + tests/test_native_msa_bridge.py).
// Engine warnings are redirected into a caller-given capture file so the
// Python side can replay them through sys.stderr.
// ---------------------------------------------------------------------------

#include "pafreport_msa.h"

namespace {

struct MsaBridge {
  std::vector<std::unique_ptr<pwnative::GapSeq>> seq_arena;
  std::vector<std::unique_ptr<pwnative::Msa>> msa_arena;
  pwnative::GapSeq* ref_gseq = nullptr;
  pwnative::Msa* ref_msa = nullptr;
};

void fill_err(char* errbuf, int32_t errcap, const std::string& msg) {
  if (errbuf && errcap > 0) {
    snprintf(errbuf, (size_t)errcap, "%s", msg.c_str());
  }
}

// Redirect the engine's warning sink to a capture file for the duration
// of one bridge call (NULL path = leave it on stderr).
struct WarnCapture {
  FILE* prev;
  FILE* f = nullptr;
  explicit WarnCapture(const char* path) : prev(pwnative::warn_stream()) {
    if (path && *path) {
      f = fopen(path, "wb");
      if (f) pwnative::warn_stream() = f;
    }
  }
  ~WarnCapture() {
    pwnative::warn_stream() = prev;
    if (f) fclose(f);
  }
};

}  // namespace

extern "C" {

void* pw_msa_new() { return new MsaBridge(); }

void pw_msa_free(void* h) { delete (MsaBridge*)h; }

// A new query starts a new MSA (cli.py: ref_gseq = None on query
// change).  Only the seed pointer resets here — ref_msa and the arena
// survive until the new query's FIRST SUCCESSFUL add (the lazy release
// in pw_msa_add), so that a final query whose alignments are all
// dropped under --skip-bad-lines still writes the previous query's MSA,
// exactly like the Python engine and the standalone binary.
void pw_msa_reset(void* h) {
  MsaBridge* b = (MsaBridge*)h;
  b->ref_gseq = nullptr;
}

int64_t pw_msa_count(void* h) {
  MsaBridge* b = (MsaBridge*)h;
  return b->ref_msa ? (int64_t)b->ref_msa->count() : 0;
}

// Contig name for the consensus writers: the MSA's first member (the
// cli.py `ref_msa.seqs[0].name` — order may change after a strand
// flip's re-sort, so the Python side cannot derive it).
void pw_msa_contig(void* h, char* buf, int32_t cap) {
  MsaBridge* b = (MsaBridge*)h;
  const std::string name =
      (b->ref_msa && !b->ref_msa->seqs.empty())
          ? b->ref_msa->seqs[0]->name
          : std::string("contig");
  snprintf(buf, (size_t)cap, "%s", name.c_str());
}

// Insert one alignment (cli.py msa_add / pafreport_main.cpp msa_add).
// refseq is the full query sequence (used only for the first alignment
// of a query; later adds build a bare layout instance of length r_len).
// rgaps/tgaps are (pos,len) int32 pairs.  Returns 0 ok; 1 out-of-layout
// gap structure (nothing mutated — the caller handles --skip-bad-lines);
// -1 other engine error (errbuf).
static int msa_add_one(MsaBridge* b, const char* tlabel,
                       const uint8_t* tseq, int64_t tseq_len,
                       int64_t t_offset, int32_t reverse, const char* rid,
                       const uint8_t* refseq, int64_t refseq_len,
                       int64_t r_len, const int32_t* rgaps, int64_t n_rgaps,
                       const int32_t* tgaps, int64_t n_tgaps,
                       int64_t ord_num, char* errbuf, int32_t errcap) {
  try {
    b->seq_arena.push_back(std::make_unique<pwnative::GapSeq>(
        tlabel, std::string((const char*)tseq, (size_t)tseq_len), -1,
        t_offset, reverse));
    pwnative::GapSeq* taseq = b->seq_arena.back().get();
    bool first_ref_aln = b->ref_gseq == nullptr;
    pwnative::GapSeq* rseq;
    if (first_ref_aln) {
      b->seq_arena.push_back(std::make_unique<pwnative::GapSeq>(
          rid, std::string((const char*)refseq, (size_t)refseq_len)));
      rseq = b->seq_arena.back().get();
      rseq->set_flag(pwnative::FLAG_IS_REF);
    } else {  // bare instance of refseq for this alignment
      b->seq_arena.push_back(
          std::make_unique<pwnative::GapSeq>(rid, "", r_len));
      rseq = b->seq_arena.back().get();
    }
    // once a gap, always a gap — applied to the fresh objects so an
    // out-of-layout gap fails BEFORE any MSA mutation
    try {
      for (int64_t k = 0; k < n_rgaps; ++k)
        rseq->set_gap(rgaps[2 * k], rgaps[2 * k + 1]);
      for (int64_t k = 0; k < n_tgaps; ++k)
        taseq->set_gap(tgaps[2 * k], tgaps[2 * k + 1]);
    } catch (const pwnative::PwErr& e) {
      b->seq_arena.pop_back();
      b->seq_arena.pop_back();
      fill_err(errbuf, errcap, e.msg);  // exact set_gap message for the
      return 1;                        // caller's fatal (non-skip) path
    }
    if (first_ref_aln && b->seq_arena.size() > 2) {
      // only the LAST query's MSA is ever written: release the previous
      // query's object graph, keeping the new pairwise seed
      std::unique_ptr<pwnative::GapSeq> t =
          std::move(b->seq_arena[b->seq_arena.size() - 2]);
      std::unique_ptr<pwnative::GapSeq> r = std::move(b->seq_arena.back());
      b->seq_arena.clear();
      b->seq_arena.push_back(std::move(t));
      b->seq_arena.push_back(std::move(r));
      b->msa_arena.clear();
      b->ref_msa = nullptr;
    }
    b->msa_arena.push_back(std::make_unique<pwnative::Msa>(rseq, taseq));
    pwnative::Msa* newmsa = b->msa_arena.back().get();
    if (first_ref_aln) {
      newmsa->ordnum = ord_num;
      b->ref_msa = newmsa;
      b->ref_gseq = rseq;
    } else {
      b->ref_gseq->msa->add_align(b->ref_gseq, newmsa, rseq);
      b->ref_msa = b->ref_gseq->msa;
    }
    return 0;
  } catch (const pwnative::PwErr& e) {
    fill_err(errbuf, errcap, e.msg);
    return -1;
  } catch (const std::exception& e) {
    fill_err(errbuf, errcap, e.what());
    return -1;
  }
}

int pw_msa_add(void* h, const char* tlabel, const uint8_t* tseq,
               int64_t tseq_len, int64_t t_offset, int32_t reverse,
               const char* rid, const uint8_t* refseq, int64_t refseq_len,
               int64_t r_len, const int32_t* rgaps, int64_t n_rgaps,
               const int32_t* tgaps, int64_t n_tgaps, int64_t ord_num,
               char* errbuf, int32_t errcap) {
  return msa_add_one((MsaBridge*)h, tlabel, tseq, tseq_len, t_offset,
                     reverse, rid, refseq, refseq_len, r_len, rgaps,
                     n_rgaps, tgaps, n_tgaps, ord_num, errbuf, errcap);
}

// Batched insert (ROADMAP item 2 lever a): ONE ffi crossing marshals a
// whole flush of alignments instead of one call per alignment — the
// per-alignment ctypes argument conversion was the largest surviving
// in-loop host term (~0.37 s on the realistic corpus).  All items share
// one query (rid/refseq/r_len — cli.py flushes the buffer on query
// change); per-item fields arrive as blobs + int64 offset arrays
// (labels and tseq bytes: offs[i]..offs[i+1]; gaps: int32 (pos,len)
// pairs, pair-count offsets).  Items are inserted IN ORDER starting at
// ``start`` and the call stops at the first failure so the Python side
// keeps exactly the sequential semantics: returns 0 with *done_out ==
// n - start when every remaining item inserted, else sets *done_out to
// the count inserted before the failing item and returns that item's
// code (1 out-of-layout, nothing mutated for it; -1 fatal) with its
// message in errbuf.  The caller handles the item (skip or raise) and
// re-enters at start = done + 1.
int pw_msa_add_batch(void* h, int64_t n, int64_t start,
                     const char* labels, const int64_t* label_off,
                     const uint8_t* tseq_blob, const int64_t* tseq_off,
                     const int64_t* t_offsets, const int32_t* reverses,
                     const int64_t* ord_nums, const char* rid,
                     const uint8_t* refseq, int64_t refseq_len,
                     int64_t r_len, const int32_t* rgaps,
                     const int64_t* rgap_off, const int32_t* tgaps,
                     const int64_t* tgap_off, int64_t* done_out,
                     char* errbuf, int32_t errcap) {
  MsaBridge* b = (MsaBridge*)h;
  *done_out = 0;
  for (int64_t i = start; i < n; ++i) {
    const std::string label(labels + label_off[i],
                            (size_t)(label_off[i + 1] - label_off[i]));
    int rc = msa_add_one(
        b, label.c_str(), tseq_blob + tseq_off[i],
        tseq_off[i + 1] - tseq_off[i], t_offsets[i], reverses[i], rid,
        refseq, refseq_len, r_len, rgaps + 2 * rgap_off[i],
        rgap_off[i + 1] - rgap_off[i], tgaps + 2 * tgap_off[i],
        tgap_off[i + 1] - tgap_off[i], ord_nums[i], errbuf, errcap);
    if (rc != 0) return rc;
    ++*done_out;
  }
  return 0;
}

// finalize + refine_msa (the cli.py consensus block, cli.py:648-651).
// Returns 0 ok, a PwErr code (5 = zero-coverage column) with the exact
// message in errbuf, or -1.
int pw_msa_refine(void* h, int32_t remove_cons_gaps, int32_t refine_clip,
                  const char* warn_path, char* errbuf, int32_t errcap) {
  MsaBridge* b = (MsaBridge*)h;
  if (!b->ref_msa) return 0;
  WarnCapture cap(warn_path);
  try {
    b->ref_msa->finalize();
    b->ref_msa->refine_msa(remove_cons_gaps != 0, refine_clip != 0);
    return 0;
  } catch (const pwnative::PwErr& e) {
    fill_err(errbuf, errcap, e.msg);
    return e.code > 0 ? e.code : -1;
  } catch (const std::exception& e) {
    fill_err(errbuf, errcap, e.what());
    return -1;
  }
}

// Write one output to ``path``: what 0 = -w multifasta, 1 = ACE,
// 2 = contig info, 3 = consensus FASTA, 4 = -D layout dump.  ``contig``
// names the contig for 1-3 (ignored otherwise).  The caller refines
// first for 1-3 (pw_msa_refine), mirroring the Python CLI's refine-once
// ordering.  Returns 0 ok, a PwErr code with message, or -1.
int pw_msa_write(void* h, int32_t what, const char* path,
                 const char* contig, int32_t remove_cons_gaps,
                 int32_t refine_clip, const char* warn_path, char* errbuf,
                 int32_t errcap) {
  MsaBridge* b = (MsaBridge*)h;
  if (!b->ref_msa) return 0;
  WarnCapture cap(warn_path);
  FILE* f = fopen(path, "wb");
  if (!f) {
    fill_err(errbuf, errcap,
             std::string("Cannot open file ") + path + " for writing!\n");
    return -1;
  }
  int rc = 0;
  try {
    switch (what) {
      case 0: b->ref_msa->write_msa(f); break;
      case 1:
        b->ref_msa->write_ace(f, contig, remove_cons_gaps != 0,
                              refine_clip != 0);
        break;
      case 2:
        b->ref_msa->write_info(f, contig, remove_cons_gaps != 0,
                               refine_clip != 0);
        break;
      case 3:
        b->ref_msa->write_cons(f, contig, remove_cons_gaps != 0,
                               refine_clip != 0);
        break;
      case 4: b->ref_msa->print_layout(f, 'v'); break;
      default:
        fill_err(errbuf, errcap, "pw_msa_write: unknown output kind\n");
        rc = -1;
    }
  } catch (const pwnative::PwErr& e) {
    fill_err(errbuf, errcap, e.msg);
    rc = e.code > 0 ? e.code : -1;
  } catch (const std::exception& e) {
    fill_err(errbuf, errcap, e.what());
    rc = -1;
  }
  fclose(f);
  return rc;
}

}  // extern "C"

extern "C" {

// Dims of the pre-refine pileup the engine would render: [depth, length]
// (0,0 when no MSA).
void pw_msa_dims(void* h, int64_t* out2) {
  MsaBridge* b = (MsaBridge*)h;
  out2[0] = b->ref_msa ? (int64_t)b->ref_msa->count() : 0;
  out2[1] = b->ref_msa ? (int64_t)b->ref_msa->length : 0;
}

// Device-consensus preparation: finalize members (prep_seq/RC) and
// build the column GEOMETRY only (counts come from the device kernel)
// — the native twin of msa.py build_msa(device=True)'s host half.
int pw_msa_prepare_device(void* h, const char* warn_path, char* errbuf,
                          int32_t errcap) {
  MsaBridge* b = (MsaBridge*)h;
  if (!b->ref_msa) return 0;
  WarnCapture cap(warn_path);
  try {
    b->ref_msa->finalize();
    b->ref_msa->build_msa(/*count=*/false);
    return 0;
  } catch (const pwnative::PwErr& e) {
    fill_err(errbuf, errcap, e.msg);
    return e.code > 0 ? e.code : -1;
  } catch (const std::exception& e) {
    fill_err(errbuf, errcap, e.what());
    return -1;
  }
}

// Render the (depth, length) int8 pileup into caller memory (dims must
// match pw_msa_dims).  Callable after pw_msa_prepare_device.
int pw_msa_render_pileup(void* h, int8_t* out, int64_t depth,
                         int64_t cols, char* errbuf, int32_t errcap) {
  MsaBridge* b = (MsaBridge*)h;
  if (!b->ref_msa) return 0;
  if (depth != (int64_t)b->ref_msa->count() ||
      cols != (int64_t)b->ref_msa->length) {
    fill_err(errbuf, errcap, "pw_msa_render_pileup: dims mismatch\n");
    return -1;
  }
  try {
    b->ref_msa->render_pileup(out);
    return 0;
  } catch (const pwnative::PwErr& e) {
    fill_err(errbuf, errcap, e.msg);
    return e.code > 0 ? e.code : -1;
  } catch (const std::exception& e) {
    fill_err(errbuf, errcap, e.what());
    return -1;
  }
}

// Finish the consensus with EXTERNAL counts+votes (from the device
// kernel): fill the column counts/layers the geometry-only build left
// empty, then run the post-vote half of refine_msa.  ``votes`` is one
// char code per layout column over the FULL [0, length) range ('A'..,
// 'N', '-', 0 = zero coverage); counts is (length, 6) int32 C-order.
// Returns 0 ok, a PwErr code (5 = zero-coverage column), or -1.
int pw_msa_refine_external(void* h, const int32_t* counts,
                           const uint8_t* votes, int64_t n,
                           int32_t remove_cons_gaps, int32_t refine_clip,
                           const char* warn_path, char* errbuf,
                           int32_t errcap) {
  MsaBridge* b = (MsaBridge*)h;
  if (!b->ref_msa) return 0;
  WarnCapture cap(warn_path);
  try {
    pwnative::Msa& m = *b->ref_msa;
    if (!m.msacolumns || n != (int64_t)m.length) {
      fill_err(errbuf, errcap,
               "pw_msa_refine_external: prepare_device not run or dims "
               "mismatch\n");
      return -1;
    }
    pwnative::MsaColumns& cols = *m.msacolumns;
    for (int64_t c = 0; c < n; ++c) {
      int32_t layer = 0;
      for (int k = 0; k < 6; ++k) {
        cols.counts[(size_t)c * 6 + k] = counts[c * 6 + k];
        layer += counts[c * 6 + k];
      }
      cols.layers[(size_t)c] = layer;
    }
    std::vector<int> v;
    for (long col = cols.mincol; col <= cols.maxcol; ++col)
      v.push_back((int)votes[(size_t)col]);
    m.refine_with_votes(v, remove_cons_gaps != 0, refine_clip != 0);
    return 0;
  } catch (const pwnative::PwErr& e) {
    fill_err(errbuf, errcap, e.msg);
    return e.code > 0 ? e.code : -1;
  } catch (const std::exception& e) {
    fill_err(errbuf, errcap, e.what());
    return -1;
  }
}

}  // extern "C"
