// Shared small utilities for the native pafreport binary: fatal-error
// type, printf-style string formatting, IUPAC complement, and the
// universal-newline line reader.  Split out of pafreport_main.cpp so the
// MSA engine header (pafreport_msa.h) can use them too.
#pragma once

#include <cctype>
#include <cstdarg>
#include <cstdint>
#include <cstdio>
#include <string>

namespace pwnative {

// The consensus vote for one column's A,C,G,T,N,- counts: bestChar's
// stable-sort + '-'/'N'-yield tie-break in closed form (reference
// GapAssem.cpp:1048-1069, quirk SURVEY.md §2.5.10; Python twin
// align/msa.py best_char_from_counts).  The ONE C++ copy of the rule —
// both the ctypes library (fastparse.cpp) and the MSA engine
// (pafreport_msa.h) delegate here.  Returns the winning character, or
// 0 for a zero-coverage column.
inline int best_char_from_counts(const int32_t c[6], int32_t layers) {
  if (layers == 0) return 0;
  int32_t m = c[0];
  for (int k = 1; k < 6; ++k)
    if (c[k] > m) m = c[k];
  static const char nuc[4] = {'A', 'C', 'G', 'T'};
  for (int k = 0; k < 4; ++k)
    if (c[k] == m) return nuc[k];
  if (c[4] == m && c[5] == m) return '-';
  return c[4] == m ? 'N' : '-';
}

struct PwErr {
  std::string msg;
  int code;
  explicit PwErr(std::string m, int c = 1) : msg(std::move(m)), code(c) {}
};

inline std::string sformat(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  char stackbuf[512];
  va_list ap2;
  va_copy(ap2, ap);
  int n = vsnprintf(stackbuf, sizeof stackbuf, fmt, ap);
  va_end(ap);
  if (n < (int)sizeof stackbuf) {
    va_end(ap2);
    return std::string(stackbuf, (size_t)(n < 0 ? 0 : n));
  }
  std::string out((size_t)n + 1, '\0');
  vsnprintf(&out[0], out.size(), fmt, ap2);
  va_end(ap2);
  out.resize((size_t)n);
  return out;
}

// IUPAC complement (case preserving) — native twin of core/dna.py
// COMP_TABLE (gclib gdna as used by revCompl, pafreport.cpp:469-472).
struct CompTbl {
  unsigned char t[256];
  CompTbl() {
    for (int i = 0; i < 256; ++i) t[i] = (unsigned char)i;
    const char* a = "ACGTUMRWSYKVHDBNX";
    const char* b = "TGCAAKYWSRMBDHVNX";
    for (int i = 0; a[i]; ++i) {
      t[(unsigned char)a[i]] = (unsigned char)b[i];
      t[(unsigned char)tolower(a[i])] =
          (unsigned char)tolower(b[i]);
    }
  }
};
inline const CompTbl kComp;

inline std::string revcomp(const std::string& s) {
  std::string out(s.rbegin(), s.rend());
  for (auto& c : out) c = (char)kComp.t[(unsigned char)c];
  return out;
}

inline void upper_inplace(std::string& s) {
  for (auto& c : s) c = (char)toupper((unsigned char)c);
}

// Buffered line reader with Python universal-newline semantics: '\n',
// '\r\n' and lone '\r' all terminate a line (the Python CLI reads its
// text inputs in text mode, which performs exactly this translation).
class LineReader {
 public:
  explicit LineReader(FILE* f) : f_(f) {}
  bool next(std::string& line) {
    line.clear();
    for (;;) {
      if (pos_ >= len_) {
        len_ = fread(buf_, 1, sizeof buf_, f_);
        pos_ = 0;
        if (len_ == 0) {
          if (ferror(f_))
            throw PwErr("Error: read failure on input stream\n");
          return !line.empty();
        }
      }
      if (pending_cr_) {  // swallow the '\n' of a '\r\n' pair
        pending_cr_ = false;
        if (buf_[pos_] == '\n') ++pos_;
        continue;
      }
      char c = buf_[pos_++];
      if (c == '\n') return true;
      if (c == '\r') {  // lone '\r' (or start of '\r\n') ends the line
        pending_cr_ = true;
        return true;
      }
      line.push_back(c);
    }
  }

 private:
  FILE* f_;
  char buf_[1 << 16];
  size_t pos_ = 0, len_ = 0;
  bool pending_cr_ = false;
};

}  // namespace pwnative
