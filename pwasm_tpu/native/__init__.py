"""Native host core: lazy-built C++ shared library + ctypes bindings.

Provides the hot host-side loops as native code (SURVEY.md §2.4): the
per-alignment cs/CIGAR diff extraction and a single-core banded Gotoh
(the honest CPU baseline for the TPU DP benchmarks).
Built on first use with g++ (cached .so, rebuilt when the
source is newer); every entry point has a pure-Python fallback, so the
package works without a toolchain.

Set ``PWASM_NATIVE=0`` to disable the native path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastparse.cpp")
_SO = os.path.join(_HERE, "_fastparse.so")
_lock = threading.Lock()
_lib = None
_tried = False

EV_FIELDS = 10


def _compile(extra_args: list[str], dest: str, what: str) -> bool:
    """g++-compile to a process-unique temp path, then publish atomically
    with rename so concurrent processes never load a partially written
    artifact."""
    tmp = f"{dest}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-std=c++17", *extra_args, "-o", tmp]
    try:
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=180)
    except (OSError, subprocess.TimeoutExpired):
        res = None
    if res is None or res.returncode != 0:
        if res is not None:
            print(f"pwasm-tpu: native {what} build failed:\n"
                  f"{res.stderr[:2000]}", file=sys.stderr)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    # durable publish (utils.fsio): the compiled artifact is cached
    # state a sibling process may dlopen seconds later — it must never
    # appear complete-but-empty after a crash
    from pwasm_tpu.utils.fsio import replace_durable
    try:
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass
    replace_durable(tmp, dest)
    return True


def _build() -> bool:
    return _compile(["-shared", "-fPIC", _SRC], _SO, "library")


def get_lib():
    """The loaded native library, or None (fallback to Python paths)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PWASM_NATIVE", "1") == "0":
            return None
        try:
            so_deps = [_SRC, os.path.join(_HERE, "pafreport_util.h"),
                       os.path.join(_HERE, "pafreport_msa.h")]
            if (not os.path.exists(_SO)
                    or any(os.path.getmtime(_SO) < os.path.getmtime(d)
                           for d in so_deps)):
                if not _build():
                    return None
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.pw_extract.restype = ctypes.c_int
        lib.pw_extract_batch.restype = ctypes.c_int
        lib.pw_extract_batch.argtypes = [
            ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_void_p,    # cs blob + offsets
            ctypes.c_char_p, ctypes.c_void_p,    # cigar blob + offsets
            ctypes.c_void_p, ctypes.c_void_p,    # ref ptrs + ref lens
            ctypes.c_void_p,                     # params (n x 7 int32)
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,  # tseq
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,  # events
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,  # arena
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,  # gaps
            ctypes.c_void_p, ctypes.c_void_p,    # sizes, err_info
            ctypes.c_void_p]                     # done_out
        lib.pw_banded_gotoh.restype = ctypes.c_int32
        lib.pw_banded_gotoh_batch.restype = None
        lib.pw_consensus_vote.restype = None
        lib.pw_consensus_vote_counts.restype = None
        lib.pw_fasta_index.restype = ctypes.c_int64
        lib.pw_fasta_index.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64]
        lib.pw_fasta_fetch.restype = ctypes.c_int64
        lib.pw_fasta_fetch.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p]
        lib.pw_encode_codes.restype = None
        lib.pw_encode_codes.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.pw_pack_2bit.restype = None
        lib.pw_pack_2bit.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.pw_unpack_2bit.restype = None
        lib.pw_unpack_2bit.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.pw_gotoh_traceback.restype = ctypes.c_int64
        lib.pw_gotoh_traceback.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_void_p]
        lib.pw_msa_new.restype = ctypes.c_void_p
        lib.pw_msa_new.argtypes = []
        lib.pw_msa_free.restype = None
        lib.pw_msa_free.argtypes = [ctypes.c_void_p]
        lib.pw_msa_reset.restype = None
        lib.pw_msa_reset.argtypes = [ctypes.c_void_p]
        lib.pw_msa_count.restype = ctypes.c_int64
        lib.pw_msa_count.argtypes = [ctypes.c_void_p]
        lib.pw_msa_add.restype = ctypes.c_int
        lib.pw_msa_add.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int32]
        lib.pw_msa_add_batch.restype = ctypes.c_int
        lib.pw_msa_add_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_void_p,    # labels + offsets
            ctypes.c_char_p, ctypes.c_void_p,    # tseq blob + offsets
            ctypes.c_void_p, ctypes.c_void_p,    # t_offsets, reverses
            ctypes.c_void_p,                     # ord_nums
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int64,                      # rid, refseq(+len), r_len
            ctypes.c_void_p, ctypes.c_void_p,    # rgaps + pair offsets
            ctypes.c_void_p, ctypes.c_void_p,    # tgaps + pair offsets
            ctypes.c_void_p,                     # done_out
            ctypes.c_char_p, ctypes.c_int32]
        lib.pw_msa_refine.restype = ctypes.c_int
        lib.pw_msa_refine.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32]
        lib.pw_msa_write.restype = ctypes.c_int
        lib.pw_msa_write.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32]
        lib.pw_msa_contig.restype = None
        lib.pw_msa_contig.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32]
        lib.pw_msa_dims.restype = None
        lib.pw_msa_dims.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.pw_msa_prepare_device.restype = ctypes.c_int
        lib.pw_msa_prepare_device.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_int32]
        lib.pw_msa_render_pileup.restype = ctypes.c_int
        lib.pw_msa_render_pileup.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_int32]
        lib.pw_msa_refine_external.restype = ctypes.c_int
        lib.pw_msa_refine_external.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int32]
        _lib = lib
    return _lib


_CLI_SRC = os.path.join(_HERE, "pafreport_main.cpp")
_CLI_BIN = os.path.join(_HERE, "pafreport")
_cli_lock = threading.Lock()
_cli_path: str | None = None
_cli_tried = False


def native_cli_path() -> str | None:
    """Path to the standalone C++ ``pafreport`` binary, building it on
    first use (like the shared library), or None when no toolchain is
    available.  The binary is the pure-native ``--device=cpu`` CLI
    (SURVEY.md §2.4.7-8, §7.3); byte-parity with the Python CLI is
    enforced by tests/test_native_cli.py."""
    global _cli_path, _cli_tried
    if _cli_path is not None or _cli_tried:
        return _cli_path
    with _cli_lock:
        if _cli_path is not None or _cli_tried:
            return _cli_path
        _cli_tried = True
        if os.environ.get("PWASM_NATIVE", "1") == "0":
            return None
        try:
            deps = [_CLI_SRC, _SRC] + [
                os.path.join(_HERE, h)
                for h in ("pafreport_msa.h", "pafreport_util.h")]
            fresh = os.path.exists(_CLI_BIN) and all(
                os.path.getmtime(_CLI_BIN) >= os.path.getmtime(d)
                for d in deps)
        except OSError:
            return None
        if not fresh and not _compile([_CLI_SRC, _SRC], _CLI_BIN, "CLI"):
            return None
        _cli_path = _CLI_BIN
    return _cli_path


def native_available() -> bool:
    return get_lib() is not None


# ---------------------------------------------------------------------------
def _raise_native_error(rc: int, info, sizes, rec, refseq_aln: bytes):
    """Translate a native error code into the exact message the Python
    extractor raises (shared constants in pwasm_tpu.core.events), after
    replaying any soft-clip warnings seen before the failure."""
    from pwasm_tpu.core import events as E
    from pwasm_tpu.core.errors import PwasmError

    for _ in range(int(sizes[4])):
        print(f"{E.SOFTCLIP_WARNING}\n{rec.line}", file=sys.stderr)
    line = rec.line
    al = rec.alninfo
    a, b = int(info[0]), int(info[1])
    if rc == 1:
        raise PwasmError(E.CS_ERROR.format(line, rec.cs[a:]))
    if rc == 2:
        refc = chr(refseq_aln[a]) if a < len(refseq_aln) else "?"
        raise PwasmError(E.BASE_MISMATCH_ERROR.format(chr(b), a, refc,
                                                      line))
    if rc == 3:
        raise PwasmError(E.SPLICE_ERROR.format(line))
    if rc == 4:
        raise PwasmError(E.CS_OP_ERROR.format(rec.cs[a:], line))
    if rc == 5:
        raise PwasmError(E.CIGAR_ERROR.format(line, rec.cigar[a:]))
    if rc == 6:
        raise PwasmError(E.CIGAR_OP_ERROR.format(chr(a), b, line))
    if rc == 7:
        raise PwasmError(E.TSEQ_LEN_ERROR.format(
            a, al.t_alnend - al.t_alnstart, al.t_alnend, al.t_alnstart,
            line))
    if rc == 8:
        raise PwasmError(E.REF_LEN_ERROR.format(
            a, al.r_alnend, al.r_alnstart, line))
    if rc == 9:
        raise PwasmError(E.COORDS_ERROR.format(
            al.r_alnstart, al.r_alnend, al.r_len,
            al.t_alnstart, al.t_alnend, line))
    raise PwasmError(f"native extraction failed (code {rc})\n")


def extract_native(rec, refseq_aln: bytes):
    """Native counterpart of ``pwasm_tpu.core.events.extract_alignment``.
    Returns a PafAlignment, or None if the native library is unavailable.
    Raises PwasmError with the same messages as the Python path."""
    from pwasm_tpu.core import events as E
    from pwasm_tpu.core.errors import PwasmError
    from pwasm_tpu.core.events import DiffEvent, GapData, PafAlignment

    lib = get_lib()
    if lib is None:
        return None
    al = rec.alninfo
    # same coordinate sanity as the Python path (negative/inverted spans
    # would otherwise size buffers below with a negative value); the C++
    # entry carries a belt guard too for non-Python callers
    E.validate_coords(al, rec.line)
    if not rec.cigar:
        raise PwasmError(E.CIGAR_ERROR.format(rec.line, 0))
    if rec.cs is None:
        raise PwasmError(E.CS_ERROR.format(rec.line, 0))
    offset = al.r_alnstart
    if al.reverse:
        offset = al.r_len - al.r_alnend
    eff = al.t_alnend - al.t_alnstart
    tseq_cap = eff + 16
    ev_cap = EV_FIELDS * (len(rec.cs) + 4)
    arena_cap = 4 * (len(rec.cs) + 64)
    gap_cap = 3 * (len(rec.cigar) + 4)
    for _ in range(3):
        tseq_buf = np.empty(tseq_cap, dtype=np.uint8)
        ev_buf = np.empty(ev_cap, dtype=np.int32)
        arena = np.empty(arena_cap, dtype=np.uint8)
        gaps_buf = np.empty(gap_cap, dtype=np.int32)
        sizes = np.zeros(5, dtype=np.int32)
        err_info = np.zeros(2, dtype=np.int32)
        ref = np.frombuffer(refseq_aln, dtype=np.uint8)
        rc = lib.pw_extract(
            rec.cs.encode(), rec.cigar.encode(),
            ref.ctypes.data_as(ctypes.c_void_p), len(refseq_aln),
            offset, int(al.reverse), al.r_len,
            al.t_alnstart, al.t_alnend, al.r_alnstart, al.r_alnend,
            tseq_buf.ctypes.data_as(ctypes.c_void_p), tseq_cap,
            ev_buf.ctypes.data_as(ctypes.c_void_p), ev_cap,
            arena.ctypes.data_as(ctypes.c_void_p), arena_cap,
            gaps_buf.ctypes.data_as(ctypes.c_void_p), gap_cap,
            sizes.ctypes.data_as(ctypes.c_void_p),
            err_info.ctypes.data_as(ctypes.c_void_p))
        if rc == 100:  # grow buffers and retry
            tseq_cap *= 4
            ev_cap *= 4
            arena_cap *= 4
            gap_cap *= 4
            continue
        if rc != 0:
            _raise_native_error(rc, err_info, sizes, rec, refseq_aln)
        for _ in range(int(sizes[4])):
            print(f"{E.SOFTCLIP_WARNING}\n{rec.line}", file=sys.stderr)
        break
    else:
        raise PwasmError("native extraction buffers exhausted\n")

    aln = PafAlignment(alninfo=al, seqname=al.t_id, reverse=al.reverse,
                       edist=rec.edist, alnscore=rec.alnscore)
    aln.offset = offset
    aln.seqlen = eff
    aln.tseq = tseq_buf[: sizes[0]].tobytes()
    evt_map = "SID"
    ab = arena.tobytes()
    # one bulk tolist, then pure python-int row unpacking: ~2x faster
    # than per-event numpy slicing at realistic-scale event counts
    n_ev = int(sizes[1])
    rows = ev_buf[:n_ev * EV_FIELDS].reshape(n_ev, EV_FIELDS).tolist()
    tdiffs = aln.tdiffs
    for (f0, f1, f2, f3, f4, f5, f6, f7, f8, f9) in rows:
        tdiffs.append(DiffEvent(
            evt=evt_map[f0], evtlen=f3,
            evtbases=ab[f4:f4 + f5], evtsub=ab[f6:f6 + f7],
            rloc=f1, tloc=f2, tctx=ab[f8:f8 + f9]))
    n_gap = int(sizes[3])
    for which, pos, length in \
            gaps_buf[:n_gap * 3].reshape(n_gap, 3).tolist():
        (aln.rgaps if which == 0 else aln.tgaps).append(
            GapData(pos, length))
    return aln


def extract_batch_native(recs, ref_alns):
    """Batched native extraction: one ``pw_extract_batch`` crossing for
    a whole flush of parsed records, mirroring ``pw_msa_add_batch``'s
    stop-at-failing-item protocol.  ``ref_alns[i]`` is record *i*'s
    alignment-orientation reference slice — items carry their own
    reference pointer, so a flush may span queries.

    Returns ``(alns, err)``: the PafAlignments for the leading items
    that extracted cleanly, plus ``None`` or the PwasmError the FIRST
    failing item raises (the caller consumes ``alns`` — their rows land
    exactly as per-item mode would emit them — then raises ``err``).
    ``(None, None)`` when the native library is unavailable.  Per-item
    soft-clip warnings replay in input order at the flush boundary, so
    output files stay byte-identical to the per-item path (stderr is
    ordering-equivalent, same contract as NativeMsa.add_batch)."""
    from pwasm_tpu.core import events as E
    from pwasm_tpu.core.errors import PwasmError
    from pwasm_tpu.core.events import DiffEvent, GapData, PafAlignment

    lib = get_lib()
    if lib is None:
        return None, None
    err = None
    n = len(recs)
    for i, rec in enumerate(recs):
        try:
            E.validate_coords(rec.alninfo, rec.line)
            if not rec.cigar:
                raise PwasmError(E.CIGAR_ERROR.format(rec.line, 0))
            if rec.cs is None:
                raise PwasmError(E.CS_ERROR.format(rec.line, 0))
        except PwasmError as e:
            n, err = i, e
            break
    if n == 0:
        return [], err
    cs_bs = [recs[i].cs.encode() for i in range(n)]
    cg_bs = [recs[i].cigar.encode() for i in range(n)]
    cs_blob = b"\0".join(cs_bs) + b"\0"
    cg_blob = b"\0".join(cg_bs) + b"\0"
    cs_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(b) + 1 for b in cs_bs], out=cs_off[1:])
    cg_off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(b) + 1 for b in cg_bs], out=cg_off[1:])
    refs_keep = [bytes(r) for r in ref_alns[:n]]
    refs = (ctypes.c_char_p * n)(*refs_keep)
    ref_lens = np.asarray([len(r) for r in refs_keep], dtype=np.int32)
    params = np.zeros((n, 7), dtype=np.int32)
    offs, effs = [], []
    for i in range(n):
        al = recs[i].alninfo
        off = al.r_alnstart
        if al.reverse:
            off = al.r_len - al.r_alnend
        offs.append(off)
        effs.append(al.t_alnend - al.t_alnstart)
        params[i] = (off, int(al.reverse), al.r_len, al.t_alnstart,
                     al.t_alnend, al.r_alnstart, al.r_alnend)
    tseq_cap = sum(effs) + 16 * n
    ev_cap = sum(EV_FIELDS * (len(b) + 4) for b in cs_bs)
    arena_cap = sum(4 * (len(b) + 64) for b in cs_bs)
    gap_cap = sum(3 * (len(b) + 4) for b in cg_bs)
    sizes = np.zeros(5 * n, dtype=np.int32)
    err_info = np.zeros(2, dtype=np.int32)
    done = np.zeros(1, dtype=np.int64)
    for _ in range(3):
        tseq_buf = np.empty(tseq_cap, dtype=np.uint8)
        ev_buf = np.empty(ev_cap, dtype=np.int32)
        arena = np.empty(arena_cap, dtype=np.uint8)
        gaps_buf = np.empty(gap_cap, dtype=np.int32)
        tq_off = np.zeros(n + 1, dtype=np.int64)
        ev_off = np.zeros(n + 1, dtype=np.int64)
        ar_off = np.zeros(n + 1, dtype=np.int64)
        gp_off = np.zeros(n + 1, dtype=np.int64)
        rc = lib.pw_extract_batch(
            n, cs_blob, cs_off.ctypes.data_as(ctypes.c_void_p),
            cg_blob, cg_off.ctypes.data_as(ctypes.c_void_p),
            ctypes.cast(refs, ctypes.c_void_p),
            ref_lens.ctypes.data_as(ctypes.c_void_p),
            params.ctypes.data_as(ctypes.c_void_p),
            tseq_buf.ctypes.data_as(ctypes.c_void_p), tseq_cap,
            tq_off.ctypes.data_as(ctypes.c_void_p),
            ev_buf.ctypes.data_as(ctypes.c_void_p), ev_cap,
            ev_off.ctypes.data_as(ctypes.c_void_p),
            arena.ctypes.data_as(ctypes.c_void_p), arena_cap,
            ar_off.ctypes.data_as(ctypes.c_void_p),
            gaps_buf.ctypes.data_as(ctypes.c_void_p), gap_cap,
            gp_off.ctypes.data_as(ctypes.c_void_p),
            sizes.ctypes.data_as(ctypes.c_void_p),
            err_info.ctypes.data_as(ctypes.c_void_p),
            done.ctypes.data_as(ctypes.c_void_p))
        if rc == 100:  # grow all buffers and retry the whole flush
            tseq_cap *= 4
            ev_cap *= 4
            arena_cap *= 4
            gap_cap *= 4
            continue
        break
    else:
        raise PwasmError("native extraction buffers exhausted\n")
    n_done = int(done[0])
    evt_map = "SID"
    ab = arena.tobytes()
    alns = []
    for i in range(n_done):
        rec = recs[i]
        al = rec.alninfo
        sz = sizes[5 * i:5 * i + 5]
        for _ in range(int(sz[4])):
            print(f"{E.SOFTCLIP_WARNING}\n{rec.line}", file=sys.stderr)
        aln = PafAlignment(alninfo=al, seqname=al.t_id,
                           reverse=al.reverse, edist=rec.edist,
                           alnscore=rec.alnscore)
        aln.offset = offs[i]
        aln.seqlen = effs[i]
        tq = int(tq_off[i])
        aln.tseq = tseq_buf[tq:tq + int(sz[0])].tobytes()
        n_ev = int(sz[1])
        ev = int(ev_off[i])
        rows = ev_buf[ev:ev + n_ev * EV_FIELDS] \
            .reshape(n_ev, EV_FIELDS).tolist()
        base = int(ar_off[i])  # arena slots are item-relative
        tdiffs = aln.tdiffs
        for (f0, f1, f2, f3, f4, f5, f6, f7, f8, f9) in rows:
            tdiffs.append(DiffEvent(
                evt=evt_map[f0], evtlen=f3,
                evtbases=ab[base + f4:base + f4 + f5],
                evtsub=ab[base + f6:base + f6 + f7],
                rloc=f1, tloc=f2, tctx=ab[base + f8:base + f8 + f9]))
        n_gap = int(sz[3])
        g0 = int(gp_off[i])
        for which, pos, length in \
                gaps_buf[g0:g0 + n_gap * 3].reshape(n_gap, 3).tolist():
            (aln.rgaps if which == 0 else aln.tgaps).append(
                GapData(pos, length))
        alns.append(aln)
    if n_done < n and rc != 0:
        # the item the C side stopped on wins over any later
        # validation failure: translate to the exact per-item message
        frec = recs[n_done]
        try:
            _raise_native_error(rc, err_info,
                                sizes[5 * n_done:5 * n_done + 5],
                                frec, ref_alns[n_done])
        except PwasmError as e:
            err = e
    return alns, err


def banded_gotoh_batch(q_codes: np.ndarray, ts_codes: np.ndarray,
                       t_lens: np.ndarray, band: int, dlo: int,
                       match: int, mismatch: int, gap_open: int,
                       gap_extend: int) -> np.ndarray | None:
    """Single-core C++ banded Gotoh over a (T, n_pad) batch; None if the
    native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    q = np.ascontiguousarray(q_codes, dtype=np.int8)
    ts = np.ascontiguousarray(ts_codes, dtype=np.int8)
    tl = np.ascontiguousarray(t_lens, dtype=np.int32)
    T, n_pad = ts.shape
    out = np.empty(T, dtype=np.int32)
    lib.pw_banded_gotoh_batch(
        q.ctypes.data_as(ctypes.c_void_p), len(q),
        ts.ctypes.data_as(ctypes.c_void_p),
        tl.ctypes.data_as(ctypes.c_void_p), T, n_pad,
        band, dlo, match, mismatch, gap_open, gap_extend,
        out.ctypes.data_as(ctypes.c_void_p))
    return out




def consensus_vote_pileup(pileup: np.ndarray) -> np.ndarray | None:
    """Single-core C++ consensus vote over a (depth, cols) int8 pileup;
    returns (cols,) uint8 consensus chars ('-' for gap columns, 0 for
    zero coverage), or None if the native library is unavailable.
    Bit-exact with pwasm_tpu.align.msa.best_char_from_counts."""
    lib = get_lib()
    if lib is None:
        return None
    p = np.ascontiguousarray(pileup, dtype=np.int8)
    depth, cols = p.shape
    out = np.empty(cols, dtype=np.uint8)
    lib.pw_consensus_vote(p.ctypes.data_as(ctypes.c_void_p), depth, cols,
                          out.ctypes.data_as(ctypes.c_void_p))
    return out


def consensus_vote_counts(counts: np.ndarray,
                          layers: np.ndarray) -> np.ndarray | None:
    """Native column vote over an already-accumulated (cols, 6) int32
    count tensor (the MSA engine's pileup format); None when the native
    library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    c = np.ascontiguousarray(counts, dtype=np.int32)
    la = np.ascontiguousarray(layers, dtype=np.int32)
    cols = c.shape[0]
    out = np.empty(cols, dtype=np.uint8)
    lib.pw_consensus_vote_counts(c.ctypes.data_as(ctypes.c_void_p),
                                 la.ctypes.data_as(ctypes.c_void_p),
                                 cols, out.ctypes.data_as(ctypes.c_void_p))
    return out


def fasta_index(path: str
                ) -> list[tuple[str, int, int, int, int, int, int]] | None:
    """Native streaming FASTA index build: one pass over the file.

    Returns [(name, seqlen, seq_start, end, linebases, linewidth,
    uniform), ...] in file order (duplicates NOT removed — the caller
    keeps the first, matching the Python indexer); the last three fields
    describe the record's line geometry for .fai persistence (uniform=1
    iff every line is reproducible from linebases/linewidth — see
    pw_fasta_index).  None when the native library is unavailable.
    Raises OSError if the file can't be opened.
    """
    lib = get_lib()
    if lib is None:
        return None
    ent_cap, arena_cap = 1024, 1 << 16
    for _ in range(8):
        entries = np.empty(ent_cap * 8, dtype=np.int64)
        arena = np.empty(arena_cap, dtype=np.uint8)
        n = lib.pw_fasta_index(
            os.fsencode(path), entries.ctypes.data_as(ctypes.c_void_p),
            ent_cap, arena.ctypes.data_as(ctypes.c_void_p), arena_cap)
        if n == -1:
            raise OSError(f"cannot open FASTA file {path}")
        if n < -1:  # capacity overflow: -(2 + needed_records)
            need = -(n + 2)
            ent_cap = max(ent_cap * 4, need + 16)
            arena_cap *= 4
            continue
        ab = arena.tobytes()
        out = []
        for k in range(int(n)):
            noff, nlen, seqlen, start, end, lb, lw, uni = (
                int(x) for x in entries[k * 8:(k + 1) * 8])
            out.append((ab[noff:noff + nlen].decode(), seqlen, start,
                        end, lb, lw, uni))
        return out
    raise OSError(f"FASTA index buffers exhausted for {path}")


def gotoh_traceback(q: np.ndarray, t: np.ndarray, match: int,
                    mismatch: int, gap_open: int, gap_extend: int
                    ) -> tuple[int, np.ndarray] | None:
    """Native full-matrix Gotoh with traceback — the single-core form of
    the re-aligner's host oracle (ops/realign.py full_gotoh_traceback;
    tie-breaks identical, parity fuzzed in tests/test_native.py).
    Returns (score, forward int8 op array) or None when the native
    library is unavailable or allocation fails."""
    lib = get_lib()
    if lib is None:
        return None
    qc = np.ascontiguousarray(q, dtype=np.int8)
    tc = np.ascontiguousarray(t, dtype=np.int8)
    m, n = len(qc), len(tc)
    ops = np.empty(m + n, dtype=np.int8)
    score = ctypes.c_int64(0)
    k = lib.pw_gotoh_traceback(
        qc.ctypes.data_as(ctypes.c_void_p), m,
        tc.ctypes.data_as(ctypes.c_void_p), n,
        match, mismatch, gap_open, gap_extend,
        ops.ctypes.data_as(ctypes.c_void_p), ctypes.byref(score))
    if k < 0:
        return None
    return int(score.value), ops[:k].copy()


def fasta_fetch(path: str, seq_start: int, end: int) -> bytes | None:
    """Native range fetch with whitespace stripping; None when the native
    library is unavailable.  Raises OSError on IO failure."""
    lib = get_lib()
    if lib is None:
        return None
    buf = np.empty(max(end - seq_start, 1), dtype=np.uint8)
    n = lib.pw_fasta_fetch(os.fsencode(path), seq_start, end,
                           buf.ctypes.data_as(ctypes.c_void_p))
    if n < 0:
        raise OSError(f"cannot read FASTA file {path}")
    return buf[:n].tobytes()


def encode_codes(seq: bytes) -> np.ndarray | None:
    """Native byte-sequence -> int8 base-code encoding (twin of
    pwasm_tpu.core.dna.encode); None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    s = np.frombuffer(bytes(seq), dtype=np.uint8)
    out = np.empty(len(s), dtype=np.int8)
    lib.pw_encode_codes(s.ctypes.data_as(ctypes.c_void_p), len(s),
                        out.ctypes.data_as(ctypes.c_void_p))
    return out


def pack_2bit(codes: np.ndarray) -> np.ndarray | None:
    """Pack int8 base codes (0..3) into 2-bit form, 4 per byte
    (little-endian within the byte); None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    c = np.ascontiguousarray(codes, dtype=np.int8)
    out = np.empty((len(c) + 3) // 4, dtype=np.uint8)
    lib.pw_pack_2bit(c.ctypes.data_as(ctypes.c_void_p), len(c),
                     out.ctypes.data_as(ctypes.c_void_p))
    return out


def unpack_2bit(packed: np.ndarray, n: int) -> np.ndarray | None:
    """Inverse of pack_2bit; None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    p = np.ascontiguousarray(packed, dtype=np.uint8)
    out = np.empty(n, dtype=np.int8)
    lib.pw_unpack_2bit(p.ctypes.data_as(ctypes.c_void_p), n,
                       out.ctypes.data_as(ctypes.c_void_p))
    return out

# ---------------------------------------------------------------------------
# Progressive-MSA engine delegation (VERDICT r3 item 5): the Python CLI
# ships the native C++ MSA engine (pafreport_msa.h, ~8x faster per
# progressive merge than the Python engine) — this handle lets the CLI
# use it for -w / consensus builds on the pure-CPU path, byte-identical
# by the same parity contract the standalone binary is held to.
# ---------------------------------------------------------------------------
_MSA_WRITE_KINDS = {"mfa": 0, "ace": 1, "info": 2, "cons": 3, "layout": 4}


class NativeMsa:
    """ctypes handle to the native progressive-MSA engine.  Mirrors the
    cli.py msa_add protocol: ``add`` one alignment at a time, ``reset``
    on query change, then ``write``/``refine`` at end of input.  Engine
    warnings are captured per call and replayed through ``stream`` —
    set it to the same stream the Python engine's warnings use (the
    CLI passes its stderr) so both engines warn identically."""

    def __init__(self, lib, stream=None):
        import tempfile

        self._lib = lib
        self._h = lib.pw_msa_new()
        self._err = ctypes.create_string_buffer(8192)
        # None = resolve sys.stderr at replay time (late binding, so a
        # redirect_stderr active when the warning fires is honored)
        self.stream = stream
        fd, self._warn_path = tempfile.mkstemp(prefix="pwasm_msa_warn_")
        os.close(fd)

    def close(self) -> None:
        if self._h is not None:
            self._lib.pw_msa_free(self._h)
            self._h = None
        try:
            os.unlink(self._warn_path)
        except OSError:
            pass

    def __del__(self):  # belt: free the C++ arena with the object
        try:
            self.close()
        except Exception:
            pass

    def reset(self) -> None:
        self._lib.pw_msa_reset(self._h)

    def count(self) -> int:
        return int(self._lib.pw_msa_count(self._h))

    def contig(self) -> str:
        buf = ctypes.create_string_buffer(4096)
        self._lib.pw_msa_contig(self._h, buf, len(buf))
        return buf.value.decode("utf-8", "replace")

    def _replay_warnings(self) -> None:
        try:
            with open(self._warn_path, "r") as f:
                text = f.read()
        except OSError:
            return
        if text:
            (self.stream if self.stream is not None
             else sys.stderr).write(text)

    def _raise(self, rc: int) -> None:
        from pwasm_tpu.core.errors import PwasmError, ZeroCoverageError

        msg = self._err.value.decode("utf-8", "replace")
        if rc == 5:
            raise ZeroCoverageError(msg)
        raise PwasmError(msg or f"native MSA engine failed (code {rc})\n")

    def add(self, tlabel: str, tseq: bytes, t_offset: int, reverse: int,
            rid: str, refseq: bytes, r_len: int,
            rgaps, tgaps, ord_num: int) -> bool:
        """Insert one alignment.  Returns False when the alignment's gap
        structure does not fit the layout (the --skip-bad-lines case —
        nothing was mutated; ``gap_err`` holds the engine's message for
        the caller's fatal path); raises on other engine errors."""
        rg = np.asarray([(g.pos, g.len) for g in rgaps],
                        dtype=np.int32).reshape(-1)
        tg = np.asarray([(g.pos, g.len) for g in tgaps],
                        dtype=np.int32).reshape(-1)
        rc = self._lib.pw_msa_add(
            self._h, tlabel.encode(), tseq, len(tseq), t_offset,
            int(reverse), rid.encode(), refseq, len(refseq), r_len,
            rg.ctypes.data_as(ctypes.c_void_p), len(rg) // 2,
            tg.ctypes.data_as(ctypes.c_void_p), len(tg) // 2,
            ord_num, self._err, len(self._err))
        if rc == 1:
            self.gap_err = self._err.value.decode("utf-8", "replace")
            return False
        if rc != 0:
            self._raise(rc)
        return True

    def add_batch(self, rid: str, refseq: bytes, r_len: int, items,
                  on_drop) -> None:
        """Insert a whole flush of alignments for ONE query through a
        single ``pw_msa_add_batch`` crossing (ROADMAP item 2 lever a:
        the per-alignment ctypes marshalling was the largest surviving
        in-loop host term).  ``items`` is a list of
        ``(tlabel, tseq, t_offset, reverse, rgaps, tgaps, ord_num)``
        in insertion order; all share ``rid``/``refseq``/``r_len`` —
        the caller flushes its buffer on query change.  Insertion is
        strictly sequential on the native side and stops at the first
        failing item, so the semantics match per-item :meth:`add`
        exactly: ``on_drop(idx, msg)`` fires, in input order, for each
        item whose gap structure does not fit the layout (nothing
        mutated for it) — raise inside it to abort like the fatal
        non-``--skip-bad-lines`` path, or return to skip the item and
        continue with the rest.  Other engine errors raise as usual."""
        n = len(items)
        if n == 0:
            return
        label_bs = [it[0].encode() for it in items]
        labels = b"".join(label_bs)
        label_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(b) for b in label_bs], out=label_off[1:])
        tseq_blob = b"".join(bytes(it[1]) for it in items)
        tseq_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(it[1]) for it in items], out=tseq_off[1:])
        t_offsets = np.asarray([it[2] for it in items], dtype=np.int64)
        reverses = np.asarray([int(it[3]) for it in items],
                              dtype=np.int32)
        ord_nums = np.asarray([it[6] for it in items], dtype=np.int64)
        rg_flat: list[int] = []
        tg_flat: list[int] = []
        rg_off = np.zeros(n + 1, dtype=np.int64)
        tg_off = np.zeros(n + 1, dtype=np.int64)
        for i, it in enumerate(items):
            for g in it[4]:
                rg_flat.append(g.pos)
                rg_flat.append(g.len)
            for g in it[5]:
                tg_flat.append(g.pos)
                tg_flat.append(g.len)
            rg_off[i + 1] = len(rg_flat) // 2
            tg_off[i + 1] = len(tg_flat) // 2
        rg = np.asarray(rg_flat, dtype=np.int32)
        tg = np.asarray(tg_flat, dtype=np.int32)
        done = np.zeros(1, dtype=np.int64)
        rid_b = rid.encode()
        start = 0
        while start < n:
            rc = self._lib.pw_msa_add_batch(
                self._h, n, start, labels,
                label_off.ctypes.data_as(ctypes.c_void_p), tseq_blob,
                tseq_off.ctypes.data_as(ctypes.c_void_p),
                t_offsets.ctypes.data_as(ctypes.c_void_p),
                reverses.ctypes.data_as(ctypes.c_void_p),
                ord_nums.ctypes.data_as(ctypes.c_void_p), rid_b,
                refseq, len(refseq), r_len,
                rg.ctypes.data_as(ctypes.c_void_p),
                rg_off.ctypes.data_as(ctypes.c_void_p),
                tg.ctypes.data_as(ctypes.c_void_p),
                tg_off.ctypes.data_as(ctypes.c_void_p),
                done.ctypes.data_as(ctypes.c_void_p),
                self._err, len(self._err))
            start += int(done[0])
            if rc == 0:
                return
            if rc == 1:
                self.gap_err = self._err.value.decode(
                    "utf-8", "replace")
                on_drop(start, self.gap_err)
                start += 1
                continue
            self._raise(rc)

    def refine(self, remove_cons_gaps: bool, refine_clipping: bool) -> None:
        rc = self._lib.pw_msa_refine(
            self._h, int(remove_cons_gaps), int(refine_clipping),
            self._warn_path.encode(), self._err, len(self._err))
        self._replay_warnings()
        if rc != 0:
            self._raise(rc)

    # ---- device-consensus delegation (--device=tpu): the engine holds
    # the MSA, renders the pileup for the TPU kernel, and applies the
    # kernel's bit-exact votes (cli.py _native_msa_outputs) ------------
    def dims(self) -> tuple[int, int]:
        out = np.zeros(2, dtype=np.int64)
        self._lib.pw_msa_dims(self._h,
                              out.ctypes.data_as(ctypes.c_void_p))
        return int(out[0]), int(out[1])

    def prepare_device(self) -> None:
        """finalize + geometry-only column build (counts come from the
        device kernel) — the native twin of build_msa(device=True)'s
        host half."""
        rc = self._lib.pw_msa_prepare_device(
            self._h, self._warn_path.encode(), self._err, len(self._err))
        self._replay_warnings()
        if rc != 0:
            self._raise(rc)

    def render_pileup(self, out: np.ndarray) -> None:
        """Fill ``out`` (depth, length int8, C-order) with the engine's
        pre-refine pileup codes (0..6, exactly msa.py pileup_matrix)."""
        assert out.dtype == np.int8 and out.flags.c_contiguous
        rc = self._lib.pw_msa_render_pileup(
            self._h, out.ctypes.data_as(ctypes.c_void_p), out.shape[0],
            out.shape[1], self._err, len(self._err))
        if rc != 0:
            self._raise(rc)

    def refine_external(self, counts: np.ndarray, votes_chars: np.ndarray,
                        remove_cons_gaps: bool,
                        refine_clipping: bool) -> None:
        """Finish the consensus with the device kernel's counts+votes
        (votes_chars: one uint8 char code per layout column, 0 = zero
        coverage)."""
        c = np.ascontiguousarray(counts, dtype=np.int32)
        v = np.ascontiguousarray(votes_chars, dtype=np.uint8)
        # the C side sizes its counts reads by len(votes): a shorter
        # counts buffer would be a native out-of-bounds read
        assert c.shape == (len(v), 6), (c.shape, len(v))
        rc = self._lib.pw_msa_refine_external(
            self._h, c.ctypes.data_as(ctypes.c_void_p),
            v.ctypes.data_as(ctypes.c_void_p), len(v),
            int(remove_cons_gaps), int(refine_clipping),
            self._warn_path.encode(), self._err, len(self._err))
        self._replay_warnings()
        if rc != 0:
            self._raise(rc)

    def write(self, kind: str, path: str, contig: str = "contig",
              remove_cons_gaps: bool = True,
              refine_clipping: bool = True) -> None:
        rc = self._lib.pw_msa_write(
            self._h, _MSA_WRITE_KINDS[kind], os.fsencode(path),
            contig.encode(), int(remove_cons_gaps), int(refine_clipping),
            self._warn_path.encode(), self._err, len(self._err))
        self._replay_warnings()
        if rc != 0:
            self._raise(rc)


def native_msa(stream=None) -> NativeMsa | None:
    """A fresh native MSA engine handle, or None when the native library
    is unavailable or delegation is disabled (PWASM_NATIVE_MSA=0).
    ``stream`` receives replayed engine warnings (the CLI passes its
    stderr so both engines warn on the same stream)."""
    if os.environ.get("PWASM_NATIVE_MSA", "1") == "0":
        return None
    lib = get_lib()
    if lib is None:
        return None
    return NativeMsa(lib, stream=stream)
