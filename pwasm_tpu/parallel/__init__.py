"""Multi-chip sharding: mesh construction and the sharded pipeline step."""

from pwasm_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    sharded_consensus,
    make_pipeline_step,
)
