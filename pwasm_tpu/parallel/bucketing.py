"""Host-side length bucketing for ragged device batches (SURVEY §7.3).

Every device entry point in the framework wants rectangular tensors:
targets padded to a shared width with true lengths alongside
(``banded_scores_batch``), queries sharing one exact length (scores are
read at cell (m, t_len), so the query axis cannot be padded), and batch
counts divisible by mesh axis sizes (``shard_map``).  The reference has
no counterpart — it is a single-threaded per-alignment loop
(pafreport.cpp:296-460) — so this module is where the repo's
variable-length batching policy lives, shared by the CLI device path
(``ops/realign.py``), ``parallel/many2many.py``, and
``parallel/wavefront_sp.py`` instead of re-implemented per caller.

The policy: group by step-rounded shape so one outlier pads only its
own group ~step-fold, not the whole batch; keep the original index of
every row so results scatter back to input order; round batch counts
up with explicitly-marked filler rows (``idx == -1``) whose results
are dropped on reassembly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Sequence

import numpy as np

PAD = 127      # target-code sentinel the DP kernels treat as never-match


def round_up(x: int, step: int = 128) -> int:
    """``x`` rounded up to a positive multiple of ``step``."""
    return max(step, (x + step - 1) // step * step)


def mesh_multiple(mesh) -> int:
    """Total device count of a mesh (product of its axis sizes) — the
    ``batch_multiple`` a fully-flattened sharded dispatch needs so
    every shard receives equal rows.  Accepts None (1: unsharded) or
    any Mesh-shaped object with a ``.shape`` mapping; deliberately
    jax-free so host-only callers can import it."""
    if mesh is None:
        return 1
    return max(1, int(np.prod([int(v)
                               for v in dict(mesh.shape).values()])))


def encode_seqs(seqs) -> list[np.ndarray]:
    """Normalize a ragged sequence list to int8 code arrays: bytes/str
    encode upper-case via ``core.dna.encode``; arrays pass through."""
    from pwasm_tpu.core.dna import encode

    out = []
    for s in seqs:
        if isinstance(s, (bytes, bytearray)):
            out.append(encode(bytes(s).upper()))
        elif isinstance(s, str):
            out.append(encode(s.upper().encode()))
        else:
            out.append(np.asarray(s, dtype=np.int8))
    return out


@dataclass(frozen=True)
class Bucket:
    """One rectangular slice of a ragged batch.

    ``data``  (B, width) int8, padded with ``PAD``;
    ``lens``  (B,) int32 true lengths (0 for filler rows);
    ``idx``   (B,) int64 position of each row in the caller's input
              order, or -1 for filler rows added to satisfy
              ``batch_multiple``.
    """

    data: np.ndarray
    lens: np.ndarray
    idx: np.ndarray

    @property
    def width(self) -> int:
        return int(self.data.shape[1])

    @property
    def n_real(self) -> int:
        return int((self.idx >= 0).sum())


def _build_bucket(enc: list[np.ndarray], idxs: list[int], width: int,
                  batch_multiple: int, pad: int) -> Bucket:
    B = len(idxs)
    if batch_multiple > 1:
        B = (B + batch_multiple - 1) // batch_multiple * batch_multiple
    data = np.full((B, width), pad, dtype=np.int8)
    lens = np.zeros(B, dtype=np.int32)
    idx = np.full(B, -1, dtype=np.int64)
    for k, ki in enumerate(idxs):
        s = enc[ki]
        data[k, :len(s)] = s
        lens[k] = len(s)
        idx[k] = ki
    return Bucket(data, lens, idx)


def bucket_targets(seqs, *, step: int = 128, batch_multiple: int = 1,
                   pad: int = PAD) -> list[Bucket]:
    """Group target sequences by step-rounded length into padded
    (B, width) tensors with true lengths — ready for
    ``banded_scores_batch`` / ``many2many_scores`` / ``wavefront_sp``.

    ``seqs``: bytes/str (encoded upper-case via ``core.dna.encode``) or
    int8 code arrays.  ``batch_multiple`` rounds each bucket's row
    count up with filler rows (``idx == -1``) so the batch axis divides
    a mesh factor.  Buckets are returned widest first (compile the big
    program while the small ones queue)."""
    enc = encode_seqs(seqs)
    groups: dict[int, list[int]] = {}
    for k, s in enumerate(enc):
        groups.setdefault(round_up(len(s), step), []).append(k)
    return [_build_bucket(enc, idxs, w, batch_multiple, pad)
            for w, idxs in sorted(groups.items(), reverse=True)]


def bucket_queries(seqs, *, batch_multiple: int = 1,
                   pad: int = PAD) -> list[Bucket]:
    """Group query sequences by EXACT length (the banded DP reads its
    global score at cell (m, t_len): padding the query axis would move
    the read row, so queries can only batch with equal-length peers).
    Filler rows repeat ``pad`` and are dropped by ``scatter_results``.
    """
    enc = encode_seqs(seqs)
    groups: dict[int, list[int]] = {}
    for k, s in enumerate(enc):
        groups.setdefault(len(s), []).append(k)
    return [_build_bucket(enc, idxs, w, batch_multiple, pad)
            for w, idxs in sorted(groups.items(), reverse=True)]


def pad_to_width(seqs, width: int, *, batch_multiple: int = 1,
                 pad: int = PAD, truncate: bool = False) -> Bucket:
    """One rectangular Bucket at a caller-chosen ``width``.

    The banded DP couples the useful target width to the QUERY length
    (``band_dlo(m, n, band)``), so callers like the ragged many2many
    pick ``width`` per query bucket rather than bucketing targets by
    their own lengths.  ``lens`` always records TRUE lengths;
    ``truncate=True`` clips longer sequences' data (only sound when
    every cell needing the clipped content is out of band — the caller
    must pick ``width`` accordingly); ``truncate=False`` raises on
    overflow instead."""
    enc = encode_seqs(seqs)
    over = [k for k, s in enumerate(enc) if len(s) > width]
    if over and not truncate:
        raise ValueError(
            f"{len(over)} sequence(s) longer than width {width} "
            f"(first: index {over[0]}, length {len(enc[over[0]])})")
    b = _build_bucket([s[:width] for s in enc], list(range(len(enc))),
                      width, batch_multiple, pad)
    lens = b.lens.copy()
    for k in over:
        lens[k] = len(enc[k])       # true length, clipped data
    return Bucket(b.data, lens, b.idx)


def group_by_shape(shapes: Iterable[Sequence[int]],
                   step: int = 128) -> dict[tuple, list[int]]:
    """Indices grouped by their step-rounded shape tuple — the n-D
    generalization used by the re-aligner's (query, target) buckets."""
    groups: dict[tuple, list[int]] = {}
    for k, shp in enumerate(shapes):
        key = tuple(round_up(int(x), step) for x in shp)
        groups.setdefault(key, []).append(k)
    return groups


def scatter_results(buckets: Sequence[Bucket],
                    per_bucket: Sequence[np.ndarray], n: int,
                    fill=0, trailing_shape: Sequence[int] = (),
                    dtype=None) -> np.ndarray:
    """Reassemble per-bucket row results into input order.

    ``per_bucket[i]`` must have leading dimension equal to
    ``buckets[i].data.shape[0]``; filler rows (``idx == -1``) are
    dropped.  Returns an array of leading dimension ``n`` (rows never
    written stay ``fill`` — there are none when the buckets came from
    one ``bucket_*`` call over ``n`` sequences).

    When results exist, the trailing dimensions and dtype come from
    ``per_bucket`` itself.  With EMPTY ``buckets`` there is nothing to
    derive them from, so ``trailing_shape``/``dtype`` supply them
    (ADVICE round 5: the old 1-D default-dtype fallback handed callers
    an array whose shape/dtype silently disagreed with every non-empty
    call)."""
    if len(buckets) != len(per_bucket):
        raise ValueError("buckets and per_bucket differ in length")
    out = None
    for b, r in zip(buckets, per_bucket):
        r = np.asarray(r)
        if r.shape[0] != b.data.shape[0]:
            raise ValueError(
                f"result rows {r.shape[0]} != bucket rows "
                f"{b.data.shape[0]}")
        if out is None:
            out = np.full((n,) + r.shape[1:], fill, dtype=r.dtype)
        live = b.idx >= 0
        out[b.idx[live]] = r[live]
    if out is None:
        out = np.full((n,) + tuple(trailing_shape), fill, dtype=dtype)
    return out
