"""Many-to-many alignment over a 2-D (query x target) device mesh.

BASELINE.md config #3: many bacterial CDS queries vs many assembly
targets — the full (Q x T) score matrix of batched banded affine-gap DP.
The batch is embarrassingly parallel, so the idiomatic TPU mapping is a
2-D mesh with queries sharded on one axis and targets on the other: each
chip aligns its (Q/nq x T/nt) tile locally and the result lands already
sharded as P('query', 'target') — zero collectives in the hot loop, all
layout handled by `jax.sharding` (the reference is single-threaded C++,
Makefile:64-66; there is no counterpart to translate).

Queries must be length-bucketed on host (SURVEY.md §7.3: pad to the
bucket's length); scores are read at cell (m, t_len) per lane, so all
queries in one call share m.  Targets are padded to a shared n with
sentinel 127 and carry true lengths in ``t_lens``.
"""

from __future__ import annotations

import functools

import jax
import numpy as np
from pwasm_tpu.utils.jaxcompat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from pwasm_tpu.ops.banded_dp import (ScoreParams, banded_scores_batch,
                                     banded_scores_pallas)


def make_mesh2d(n_devices: int | None = None,
                axis_names: tuple[str, str] = ("query", "target"),
                devices=None) -> Mesh:
    """A 2-D mesh over the first ``n_devices`` devices; the query axis
    gets the largest factor <= sqrt(n) (targets usually outnumber
    queries, so the target axis gets the bigger factor).  ``devices``
    pins the pool to an explicit device list (a served job's device
    lease), like ``mesh.make_mesh``."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    nq = 1
    for cand in range(int(n ** 0.5), 0, -1):
        if n % cand == 0:
            nq = cand
            break
    return Mesh(np.asarray(devs).reshape(nq, n // nq), axis_names)


def make_many2many(mesh: Mesh, band: int = 64,
                   params: ScoreParams = ScoreParams(),
                   kernel: str = "xla"):
    """Build the sharded many-to-many scorer.

    Returns a jitted ``fn(qs (Q, m), ts (T, n), t_lens (T,)) -> (Q, T)``
    int32 scores with Q sharded over mesh axis 'query' and T over
    'target' (Q and T must divide by their mesh factors).  ``kernel``
    selects the local scorer: 'xla' (lax.scan rows) or 'pallas' (the
    anti-diagonal wavefront TPU kernel).
    """
    if kernel == "pallas":
        def score_all(q, ts_loc, tlens_loc):
            return banded_scores_pallas(q, ts_loc, tlens_loc, band=band,
                                        params=params)
    else:
        def score_all(q, ts_loc, tlens_loc):
            return banded_scores_batch(q, ts_loc, tlens_loc, band=band,
                                       params=params)

    def local(qs_loc, ts_loc, tlens_loc):
        return jax.vmap(
            lambda q: score_all(q, ts_loc, tlens_loc))(qs_loc)

    # check_vma off: the row scan's initial wavefront is built from
    # constants, which the varying-axes checker would otherwise reject as
    # unvarying carry inputs; the body is per-tile pure so the check adds
    # nothing here.
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P("query", None), P("target", None),
                             P("target")),
                   out_specs=P("query", "target"),
                   check_vma=False)
    return jax.jit(fn)


@functools.partial(jax.jit, static_argnames=("band", "params"))
def many2many_scores(qs: jax.Array, ts: jax.Array, t_lens: jax.Array,
                     band: int = 64,
                     params: ScoreParams = ScoreParams()) -> jax.Array:
    """Unsharded (Q, T) score matrix — the single-device reference the
    mesh version must match bit for bit."""
    return jax.vmap(
        lambda q: banded_scores_batch(q, ts, t_lens, band=band,
                                      params=params))(qs)


@functools.partial(jax.jit, static_argnames=("band", "params"))
def many2many_scores_pallas(qs: jax.Array, ts: jax.Array,
                            t_lens: jax.Array, band: int = 64,
                            params: ScoreParams = ScoreParams()
                            ) -> jax.Array:
    """Single-chip (Q, T) score matrix via the Pallas wavefront kernel,
    sequential over queries (``lax.map``), batched over targets inside
    each kernel launch.

    Memory stays O(T x band) regardless of Q — unlike vmapping the scan
    path, whose carry is O(Q x T x band) and OOMs at
    BASELINE.md config-3 scale (500 x 10k).  Bit-exact with
    ``many2many_scores``.
    """
    return jax.lax.map(
        lambda q: banded_scores_pallas(q, ts, t_lens, band=band,
                                       params=params), qs)


def many2many_scores_ragged(qs, ts, band: int = 64,
                            params: ScoreParams = ScoreParams(),
                            mesh: Mesh | None = None,
                            kernel: str = "xla",
                            supervisor=None) -> np.ndarray:
    """(Q, T) scores for RAGGED query/target sequence lists.

    The shape preconditions of the rectangular entry points (queries
    sharing one exact length, targets sharing one padded width, batch
    axes dividing the mesh factors) are satisfied here via
    ``parallel.bucketing``: queries bucket by exact length; for each
    query bucket the targets dispatch in TWO width groups, because the
    band placement ``band_dlo(m, n, band)`` couples the covered
    diagonal window to the padded width:

    - targets with ``t_len <= m`` at width ``m`` (dlo = -band//2, the
      most negative placement the API admits — covers end diagonals
      down to -band//2, no truncation possible);
    - longer targets at width ``m + band - 2`` (dlo = -1, in-band
      diagonals up to band-2); targets longer than that width are
      clipped, which cannot change any score — their end diagonal is
      provably out of band (NEG either way).

    Results scatter back to input order.  With ``mesh`` each call is
    the 2-D-sharded scorer (bucket row counts rounded up to the mesh
    factors with filler rows).

    ``qs``/``ts``: bytes/str or int8 code arrays.  Cells whose end
    diagonal falls outside [-band//2, band-2] are NEG — the union of
    what the two placements can cover.

    ``supervisor`` (resilience.BatchSupervisor) supervises each bucket
    dispatch: guardrail-validated scores, bounded retries, and on
    give-up the TPU→CPU degradation — the identical program re-runs
    pinned to the CPU backend (unsharded; bit-exact by the mesh/flat
    parity contract above).
    """
    import jax.numpy as jnp

    from pwasm_tpu.ops.banded_dp import NEG
    from pwasm_tpu.parallel.bucketing import (encode_seqs,
                                              bucket_queries,
                                              pad_to_width)

    qs = list(qs)
    ts_enc = encode_seqs(ts)
    qmult = int(mesh.shape["query"]) if mesh is not None else 1
    tmult = int(mesh.shape["target"]) if mesh is not None else 1
    fn = make_many2many(mesh, band=band, params=params,
                        kernel=kernel) if mesh is not None else None
    out = np.full((len(qs), len(ts_enc)), NEG, dtype=np.int32)
    for qb in bucket_queries(qs, batch_multiple=qmult):
        m = qb.width
        short = [k for k, t in enumerate(ts_enc) if len(t) <= m]
        long_ = [k for k, t in enumerate(ts_enc) if len(t) > m]
        for keep, n_eff, clip in ((short, m, False),
                                  (long_, m + band - 2, True)):
            if not keep:
                continue
            tb = pad_to_width([ts_enc[k] for k in keep], n_eff,
                              batch_multiple=tmult, truncate=clip)

            def dispatch(qb=qb, tb=tb):
                if fn is not None:
                    return np.asarray(fn(jnp.asarray(qb.data),
                                         jnp.asarray(tb.data),
                                         jnp.asarray(tb.lens)))
                flat = many2many_scores_pallas if kernel == "pallas" \
                    else many2many_scores
                return np.asarray(flat(
                    jnp.asarray(qb.data), jnp.asarray(tb.data),
                    jnp.asarray(tb.lens), band=band, params=params))

            if supervisor is not None:
                from pwasm_tpu.resilience.guardrails import \
                    check_scores_matrix

                def on_cpu(qb=qb, tb=tb):
                    # TPU→CPU degradation: the same scorer on the
                    # (always-present) CPU backend — sharded over the
                    # mesh's CPU twin when enough CPU devices exist,
                    # unsharded otherwise (bit-exact either way by the
                    # mesh/flat parity contract)
                    import jax

                    if mesh is not None:
                        from pwasm_tpu.parallel.mesh import cpu_like_mesh
                        cmesh = cpu_like_mesh(mesh)
                        if cmesh is not None:
                            cfn = make_many2many(cmesh, band=band,
                                                 params=params,
                                                 kernel=kernel)
                            return np.asarray(cfn(
                                jnp.asarray(qb.data),
                                jnp.asarray(tb.data),
                                jnp.asarray(tb.lens)))
                    with jax.default_device(jax.devices("cpu")[0]):
                        return np.asarray(many2many_scores(
                            jnp.asarray(qb.data), jnp.asarray(tb.data),
                            jnp.asarray(tb.lens), band=band,
                            params=params))

                s = supervisor.run(
                    "many2many", dispatch,
                    validate=lambda s, qb=qb, tb=tb, m=m:
                        check_scores_matrix(
                            s, qb.data.shape[0], tb.data.shape[0],
                            params.match, m),
                    fallback=on_cpu)
            else:
                s = dispatch()
            ql = qb.idx >= 0
            tl = tb.idx >= 0
            cols = np.asarray(keep)[tb.idx[tl]]
            out[np.ix_(qb.idx[ql], cols)] = s[ql][:, tl]
    return out
