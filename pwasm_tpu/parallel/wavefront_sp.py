"""Sequence-parallel banded DP: the wavefront pipelined over a device mesh.

This is the framework's long-context scaling path (SURVEY.md §5
'long-context / sequence parallelism'): when a single query is too long
for one chip's serial row loop to be acceptable, the band is split along
the diagonal — query rows are sharded over a 1-D ``seq`` mesh axis and
the wavefront edge is handed to the right neighbor over ICI with
``ppermute`` (ring-style halo exchange), exactly the design sketched in
SURVEY.md §5 for 50 kb+ reads.

Pipelining makes it efficient: the DP over ONE target is a serial
dependency chain, but with a batch of T targets device d can process
target ``b = stage - d`` while device d+1 processes target ``b - 1``.
After ``T + D - 1`` stages every target has flowed through all D row
chunks; per-device serial work is ``(T + D - 1) * m / D`` rows versus
``T * m`` single-chip — a D-fold speedup for T >> D.

Bit-exactness: each chunk advances the wavefront with the SAME
``make_row_step`` recurrence the single-chip scan uses, and the carried
state (M, Ix, Iy in band coordinates) is exactly what crosses a chunk
boundary, so scores equal ``banded_scores_batch`` bit for bit (tested on
a virtual 8-device mesh).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from pwasm_tpu.utils.jaxcompat import pcast, ppermute, psum, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from pwasm_tpu.ops.banded_dp import (NEG, ScoreParams, band_dlo,
                                     final_score, initial_wavefront,
                                     make_row_step)


def make_wavefront_sp(mesh: Mesh, m: int, n: int, T: int,
                      band: int = 64,
                      params: ScoreParams = ScoreParams(),
                      axis: str = "seq", m_true: int | None = None):
    """Build the jitted sequence-parallel scorer for fixed shapes.

    Returns ``fn(q (m,) int, ts (T, n) int, t_lens (T,) int) -> (T,)
    int32 scores``.  ``m`` must divide by the ``axis`` size of the mesh;
    for a query that doesn't, pad it to the next multiple and pass its
    real length as ``m_true`` — rows past ``m_true`` are carried
    through unchanged (the pad content never touches the wavefront), so
    scores stay bit-exact with the single-chip scan over the unpadded
    query.  ``wavefront_sp_scores`` does this padding automatically
    (the ``bucket_targets`` companion in ``parallel/bucketing.py``
    handles the target side)."""
    D = mesh.shape[axis]
    if m % D != 0:
        raise ValueError(f"query length {m} must divide by mesh "
                         f"axis '{axis}' size {D}")
    if m_true is None:
        m_true = m
    if not 0 < m_true <= m:
        raise ValueError(f"m_true {m_true} outside (0, {m}]")
    chunk = m // D
    dlo = band_dlo(m_true, n, band)
    step = make_row_step(n, dlo, band, params)
    perm = [(i, i + 1) for i in range(D - 1)]

    def run_chunk(q_loc, t, wf, row0):
        """Advance the wavefront through this device's rows for one
        target.  ``row0`` is the absolute 0-based index of the first
        local row."""

        def row(carry, args):
            prev_m, prev_ix, prev_iy = carry
            qi, k = args
            i = row0 + k + 1          # 1-based absolute query row
            out = step(prev_m, prev_ix, prev_iy, i, qi, t)
            if m_true < m:            # pad rows: carry passthrough
                out = jax.tree.map(
                    lambda new, old: jnp.where(i <= m_true, new, old),
                    out, carry)
            return out, None

        ks = jnp.arange(chunk, dtype=jnp.int32)
        out, _ = jax.lax.scan(row, wf, (q_loc.astype(jnp.int32), ks))
        return out

    def local(q_loc, ts, t_lens):
        d = jax.lax.axis_index(axis)
        row0 = d * chunk
        wf_init = initial_wavefront(n, dlo, band, params)

        def stage(carry, s):
            wf_in = carry
            b = s - d                      # target flowing through here
            active = (b >= 0) & (b < T)
            bc = jnp.clip(b, 0, T - 1)
            t = jax.lax.dynamic_slice(ts, (bc, 0), (1, n))[0]
            # first chunk starts every target from the row-0 state; later
            # chunks continue from the neighbor's handed-over wavefront
            wf = jax.tree.map(
                lambda a, b_: jnp.where(d == 0, a, b_), wf_init, wf_in)
            wf_out = run_chunk(q_loc, t, wf, row0)
            score = final_score(*wf_out, t_lens[bc], m_true, dlo, band)
            emit = active & (d == D - 1)   # last chunk completes row m
            # hand the wavefront edge to the right neighbor (ICI halo)
            wf_next = jax.tree.map(
                lambda x: ppermute(x, axis, perm), wf_out)
            return wf_next, (bc, jnp.where(emit, score, 0),
                             emit.astype(jnp.int32))

        zeros = jax.tree.map(
            lambda x: pcast(jnp.zeros_like(x), axis, to="varying"),
            wf_init)
        _, (bs, scs, emits) = jax.lax.scan(
            stage, zeros, jnp.arange(T + D - 1, dtype=jnp.int32))
        scores = jnp.zeros((T,), jnp.int32).at[bs].add(
            jnp.where(emits == 1, scs, 0))
        got = jnp.zeros((T,), jnp.int32).at[bs].add(emits)
        # only the last device emitted real scores; share them ringwide
        scores = psum(scores, axis)
        got = psum(got, axis)
        return jnp.where(got > 0, scores, NEG)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(axis), P(None, None), P(None)),
                   out_specs=P(None))
    return jax.jit(fn)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "band", "params", "axis"))
def wavefront_sp_scores(q: jax.Array, ts: jax.Array, t_lens: jax.Array,
                        mesh: Mesh, band: int = 64,
                        params: ScoreParams = ScoreParams(),
                        axis: str = "seq") -> jax.Array:
    """Convenience wrapper: sequence-parallel scores for one (q, ts)
    workload (shapes specialize the compilation).  A query length that
    does not divide the mesh axis is padded up automatically; the pad
    rows are masked out of the wavefront, so scores are identical to
    the divisible case."""
    T, n = ts.shape
    m = q.shape[0]
    D = mesh.shape[axis]
    m_pad = (m + D - 1) // D * D
    if m_pad != m:
        q = jnp.pad(q, (0, m_pad - m), constant_values=127)
    fn = make_wavefront_sp(mesh, m_pad, n, T, band, params, axis,
                           m_true=m)
    return fn(q, ts, t_lens)
