"""Device-mesh pipeline (SURVEY.md §5 'distributed communication backend').

The reference is single-threaded C++ (Makefile:64-66: threads commented
out); the new framework's scaling story is SPMD over a ``jax.sharding``
mesh with XLA collectives riding ICI:

- **batch (dp)**: the (query x target) alignment batch is embarrassingly
  parallel — targets shard across chips for the banded DP and the
  context scan.
- **depth (tp-analog)**: deep consensus pileups shard across chips on the
  read-depth axis; per-column class counts are ``psum``-reduced over ICI
  before the vote (the BASELINE north star).
- **columns (sp-analog)**: pileup columns shard across the batch axis of
  the mesh, so a single wide MSA also spreads over chips; votes are
  per-column local, so no collective is needed on that axis.

Multi-slice/DCN: ``make_multislice_mesh``/``make_multislice_step`` add a
third, OUTERMOST 'slice' axis for pods connected over DCN.  Only the
embarrassingly-parallel axes (targets, pileup columns) shard across it;
the one collective in the step (the depth-axis psum of consensus counts)
runs on the innermost mesh axis, so it rides ICI within a slice and DCN
never carries a collective — the layout rule the scaling-book recipe
prescribes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from pwasm_tpu.utils.jaxcompat import psum, shard_map

from pwasm_tpu.ops.banded_dp import ScoreParams, banded_scores_batch
from pwasm_tpu.ops.consensus import consensus_vote_counts, pileup_counts


def _inner_factor(n: int) -> int:
    """Largest factor of n that is <= sqrt(n) — the innermost-axis size
    when factoring a device count into a 2-D grid."""
    for cand in range(int(n ** 0.5), 0, -1):
        if n % cand == 0:
            return cand
    return 1


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, str] = ("batch", "depth"),
              platform: str | None = None,
              devices=None) -> Mesh:
    """A 2-D mesh over the first ``n_devices`` devices.  The depth axis
    gets the largest factor <= sqrt(n) so both axes are exercised.
    ``platform`` restricts the device pool (e.g. ``"cpu"`` builds the
    degradation twin of a TPU mesh, see ``cpu_like_mesh``).
    ``devices`` pins the pool to an EXPLICIT device list instead of the
    global order — the device-lease scheduler hands each served job its
    lane's slice of ``jax.devices()`` this way, so two concurrent jobs'
    meshes never overlap on a chip."""
    if devices is not None:
        devs = list(devices)
    else:
        devs = jax.devices(platform) if platform else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    d = _inner_factor(n)
    return Mesh(np.asarray(devs).reshape(n // d, d), axis_names)


def cpu_like_mesh(mesh: Mesh) -> Mesh | None:
    """The CPU-backend twin of ``mesh``: same axis names and shape over
    CPU devices, so a sharded program degrades to the host with its
    partitioning (and bit-exact psum order) intact.  Returns None when
    too few CPU devices exist — callers then degrade to the unsharded
    path instead (same integers either way by the repo's mesh/flat
    parity contracts)."""
    shape = tuple(mesh.shape[a] for a in mesh.axis_names)
    need = int(np.prod(shape))
    try:
        cpus = jax.devices("cpu")
    except RuntimeError:
        return None
    if len(cpus) < need:
        return None
    return Mesh(np.asarray(cpus[:need]).reshape(shape),
                tuple(mesh.axis_names))


def sharded_consensus(mesh: Mesh, dp_axes=("batch",)):
    """Consensus with the pileup sharded (depth, cols) over the mesh:
    local counts per shard, ``psum`` over the depth axis (ICI), local
    votes per column shard.  ``dp_axes`` names the mesh axes the column
    axis shards over (("slice", "batch") on a multi-slice mesh).
    Returns a jitted fn(bases (depth, cols)) -> votes (cols,)."""
    dp = dp_axes if len(dp_axes) > 1 else dp_axes[0]

    def block(b_local):
        local = pileup_counts(b_local)
        total = psum(local, "depth")
        return consensus_vote_counts(total)

    fn = shard_map(block, mesh=mesh,
                   in_specs=P("depth", dp),
                   out_specs=P(dp))
    return jax.jit(fn)


def sharded_counts_votes(mesh: Mesh, dp_axes=("batch",)):
    """Counts AND votes with the pileup sharded (depth, cols) over the
    mesh — the product consensus path behind ``pafreport --shard``:
    local pileup counts per shard, ``psum`` over the depth axis (the
    north-star ICI collective, SURVEY.md §0), local votes per column
    shard.  The summed counts are returned too, so the host column
    tensor (MsaColumns) is filled from the same reduction the vote used.
    Returns a jitted fn(bases (depth, cols)) -> (votes (cols,) int8,
    counts (cols, 6) int32); depth must divide the mesh depth axis and
    cols the ``dp_axes`` product (callers pad with code 6, which
    contributes nothing)."""
    dp = dp_axes if len(dp_axes) > 1 else dp_axes[0]

    def block(b_local):
        total = psum(pileup_counts(b_local), "depth")
        return consensus_vote_counts(total), total

    fn = shard_map(block, mesh=mesh,
                   in_specs=P("depth", dp),
                   out_specs=(P(dp), P(dp, None)))
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def sharded_refine_phases(mesh: Mesh, xdrop: int, match_sc: int,
                          mismatch_sc: int):
    """The X-drop clip-refinement phase program
    (ops/refine_clip._phases_fn) with the MEMBER axis sharded over every
    mesh axis — members are independent lanes, so this is pure data
    parallelism (the consensus and its length are replicated; no
    collective).  Bit-exact with the single-device program by
    construction.  The padded member count must divide the mesh size
    (refine_phases_device pads accordingly).  Cached per (mesh,
    constants): Mesh has value-based hash/eq, so equal meshes share one
    compiled program."""
    from pwasm_tpu.ops.refine_clip import _phases_fn

    fn = _phases_fn(xdrop, match_sc, mismatch_sc)
    ax = tuple(mesh.axis_names)
    spec_m = P(ax)
    sm = shard_map(
        fn, mesh=mesh,
        in_specs=(P(ax, None), P(ax, None), P(None)) + (spec_m,) * 8
        + (P(),),
        out_specs=(spec_m,) * 4)
    return jax.jit(sm)


def make_pipeline_step(mesh: Mesh, band: int = 32,
                       params: ScoreParams = ScoreParams()):
    """The full sharded pipeline step — the framework's 'training step'
    equivalent: batched banded DP re-alignment over target-sharded lanes
    plus depth-sharded consensus voting with the ICI psum.

    Returns a jitted fn(q (m,), ts (T, n), t_lens (T,),
    pileup (depth, cols)) -> (scores (T,), votes (cols,)).
    T must divide by mesh.shape['batch']; depth by mesh 'depth' and cols
    by mesh 'batch'.
    """
    return _make_step(mesh, band, params, ("batch",))


def _make_step(mesh: Mesh, band, params, dp_axes):
    """Shared builder behind make_pipeline_step/make_multislice_step:
    targets and pileup columns shard over ``dp_axes``; the consensus
    psum reduces over 'depth' only."""
    dp = dp_axes if len(dp_axes) > 1 else dp_axes[0]
    s_batch = NamedSharding(mesh, P(dp, None))
    s_lens = NamedSharding(mesh, P(dp))
    s_rep = NamedSharding(mesh, P())
    s_pileup = NamedSharding(mesh, P("depth", dp))
    cons = sharded_consensus(mesh, dp_axes)

    @functools.partial(
        jax.jit,
        in_shardings=(s_rep, s_batch, s_lens, s_pileup),
        out_shardings=(s_lens, NamedSharding(mesh, P(dp))))
    def step(q, ts, t_lens, pileup):
        scores = banded_scores_batch(q, ts, t_lens, band=band,
                                     params=params)
        votes = cons(pileup)
        return scores, votes

    return step


def make_multislice_mesh(n_slices: int, n_devices: int | None = None,
                         axis_names: tuple[str, str, str] =
                         ("slice", "batch", "depth")) -> Mesh:
    """A 3-D (slice, batch, depth) mesh.  'slice' is the OUTERMOST axis —
    on real multi-slice topologies consecutive device blocks belong to
    the same slice, so this reshape keeps intra-slice axes on ICI and
    puts only the slice axis across DCN."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    if n % n_slices:
        raise ValueError(f"{n} devices don't split into {n_slices} slices")
    per = n // n_slices
    d = _inner_factor(per)
    return Mesh(np.asarray(devs).reshape(n_slices, per // d, d),
                axis_names)


def make_multislice_step(mesh: Mesh, band: int = 32,
                         params: ScoreParams = ScoreParams()):
    """Data-parallel-over-DCN pipeline step on a (slice, batch, depth)
    mesh: targets and pileup columns shard over (slice x batch); the
    consensus psum reduces over 'depth' only, so no collective crosses
    the slice (DCN) axis.  Same signature and bit-exact results as
    ``make_pipeline_step``; T and cols must divide by
    slice*batch, depth by the mesh depth."""
    return _make_step(mesh, band, params, ("slice", "batch"))
