"""Device-mesh pipeline (SURVEY.md §5 'distributed communication backend').

The reference is single-threaded C++ (Makefile:64-66: threads commented
out); the new framework's scaling story is SPMD over a ``jax.sharding``
mesh with XLA collectives riding ICI:

- **batch (dp)**: the (query x target) alignment batch is embarrassingly
  parallel — targets shard across chips for the banded DP and the
  context scan.
- **depth (tp-analog)**: deep consensus pileups shard across chips on the
  read-depth axis; per-column class counts are ``psum``-reduced over ICI
  before the vote (the BASELINE north star).
- **columns (sp-analog)**: pileup columns shard across the batch axis of
  the mesh, so a single wide MSA also spreads over chips; votes are
  per-column local, so no collective is needed on that axis.

Multi-slice/DCN: the outer per-alignment loop is data-parallel at the
process level; nothing in the step crosses slices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from pwasm_tpu.ops.banded_dp import ScoreParams, banded_scores_batch
from pwasm_tpu.ops.consensus import consensus_vote_counts, pileup_counts


def make_mesh(n_devices: int | None = None,
              axis_names: tuple[str, str] = ("batch", "depth")) -> Mesh:
    """A 2-D mesh over the first ``n_devices`` devices.  The depth axis
    gets the largest factor <= sqrt(n) so both axes are exercised."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    n = len(devs)
    d = 1
    for cand in range(int(n ** 0.5), 0, -1):
        if n % cand == 0:
            d = cand
            break
    return Mesh(np.asarray(devs).reshape(n // d, d), axis_names)


def sharded_consensus(mesh: Mesh):
    """Consensus with the pileup sharded (depth, cols) over the mesh:
    local counts per shard, ``psum`` over the depth axis (ICI), local
    votes per column shard.  Returns a jitted fn(bases (depth, cols)) ->
    votes (cols,)."""

    def block(b_local):
        local = pileup_counts(b_local)
        total = jax.lax.psum(local, "depth")
        return consensus_vote_counts(total)

    fn = shard_map(block, mesh=mesh,
                   in_specs=P("depth", "batch"),
                   out_specs=P("batch"))
    return jax.jit(fn)


def make_pipeline_step(mesh: Mesh, band: int = 32,
                       params: ScoreParams = ScoreParams()):
    """The full sharded pipeline step — the framework's 'training step'
    equivalent: batched banded DP re-alignment over target-sharded lanes
    plus depth-sharded consensus voting with the ICI psum.

    Returns a jitted fn(q (m,), ts (T, n), t_lens (T,),
    pileup (depth, cols)) -> (scores (T,), votes (cols,)).
    T must divide by mesh.shape['batch']; depth by mesh 'depth' and cols
    by mesh 'batch'.
    """
    s_batch = NamedSharding(mesh, P("batch", None))
    s_lens = NamedSharding(mesh, P("batch"))
    s_rep = NamedSharding(mesh, P())
    s_pileup = NamedSharding(mesh, P("depth", "batch"))
    cons = sharded_consensus(mesh)

    @functools.partial(
        jax.jit,
        in_shardings=(s_rep, s_batch, s_lens, s_pileup),
        out_shardings=(s_lens, NamedSharding(mesh, P("batch"))))
    def step(q, ts, t_lens, pileup):
        scores = banded_scores_batch(q, ts, t_lens, band=band,
                                     params=params)
        votes = cons(pileup)
        return scores, votes

    return step
