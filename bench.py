#!/usr/bin/env python
"""pwasm-tpu benchmark — prints ONE JSON line for the driver.

Headline config (BASELINE.md #2): batched banded affine-gap DP
re-alignment of one bacterial-CDS-sized query (~1.5 kb) against a batch of
Nanopore-assembly-sized targets, band 64 (PWASM_BENCH_BAND to change), on
one chip — measured as aligned target bases per second.  ``vs_baseline`` is the speedup over the
single-core C++ banded Gotoh on the same workload (the reference is a
single-threaded C++ program, Makefile:64-66, and publishes no numbers of
its own — BASELINE.md).

A consensus-vote parity check (CPU engine vs device kernel, bit-exact)
runs as part of the benchmark; a mismatch fails the run.

Timing note: the TPU here sits behind a tunnel with a ~70 ms host
round-trip, so timing fetch-per-rep measures the tunnel, not the chip
(and ``block_until_ready`` alone can return before the remote execution
actually runs).  The benchmark therefore times a DEPENDENCY-CHAINED
pipeline of launches (each rep's t_lens is xor-folded with the previous
rep's scores, so no rep can be elided or reordered) ending in one host
fetch, at two pipeline depths k and 2k; the per-rep time is
``(t(2k) - t(k)) / k``, which cancels the fixed round-trip latency.

Env knobs: PWASM_BENCH_T (batch targets, default 10240),
PWASM_BENCH_KERNEL=pallas|stream|xla (default pallas),
PWASM_BENCH_BAND (default 64), PWASM_BENCH_CPU_T (CPU baseline subset,
default 32), PWASM_BENCH_REPS (pipeline depth k, default 8).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

M = 1500          # query length (CDS-sized)
BAND = int(os.environ.get("PWASM_BENCH_BAND", "64"))
N_PAD = M + BAND // 2  # padded target length (pad also anchors the band)


def _workload(T: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    q = rng.integers(0, 4, size=M).astype(np.int8)
    ts = np.full((T, N_PAD), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(q)
        for _ in range(int(rng.integers(5, 40))):   # subs
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        for _ in range(int(rng.integers(0, 8))):    # indels
            p = int(rng.integers(1, len(t) - 1))
            if rng.random() < 0.5:
                t.insert(p, int(rng.integers(0, 4)))
            else:
                del t[p]
        t = t[:N_PAD]
        ts[k, :len(t)] = t
        t_lens[k] = len(t)
    return q, ts, t_lens


def main() -> int:
    import jax
    import jax.numpy as jnp

    from pwasm_tpu.ops.banded_dp import (ScoreParams, band_dlo,
                                         banded_scores_batch,
                                         banded_scores_long,
                                         banded_scores_pallas)
    from pwasm_tpu.ops.consensus import consensus_votes

    T = int(os.environ.get("PWASM_BENCH_T", "10240"))
    cpu_T = int(os.environ.get("PWASM_BENCH_CPU_T", "32"))
    kernel = os.environ.get("PWASM_BENCH_KERNEL", "pallas")
    params = ScoreParams()
    q, ts, t_lens = _workload(T)
    qd = jnp.asarray(q)
    tsd = jnp.asarray(ts)
    tld = jnp.asarray(t_lens)

    if kernel == "pallas":
        def score_fn(tl_in):
            return banded_scores_pallas(qd, tsd, tl_in, band=BAND,
                                        params=params)
    elif kernel == "stream":
        def score_fn(tl_in):
            return banded_scores_long(qd, tsd, tl_in, band=BAND,
                                      params=params, chunk=512)
    else:
        def score_fn(tl_in):
            return banded_scores_batch(qd, tsd, tl_in, band=BAND,
                                       params=params)

    @jax.jit
    def chained(tl_in, prev):
        # optimization_barrier ties each launch to the previous rep's
        # scores — unlike an algebraic no-op (e.g. xor with prev&0), XLA
        # cannot fold it away, so the chain can't be elided or reordered
        tl_in, _ = jax.lax.optimization_barrier((tl_in, prev))
        return score_fn(tl_in)

    zero = jnp.zeros_like(tld)
    scores_h = np.asarray(chained(tld, zero))   # compile + settle

    def pipe(reps):
        prev = zero
        t0 = time.perf_counter()
        for _ in range(reps):
            prev = chained(tld, prev)
        np.asarray(prev)                        # one fetch drains the chain
        return time.perf_counter() - t0

    k = int(os.environ.get("PWASM_BENCH_REPS", "8"))
    pipe(2)                                     # warm the dispatch path
    dev_dt = 0.0
    for _ in range(3):  # timer noise can make t(2k) <= t(k); retry
        dev_dt = (pipe(2 * k) - pipe(k)) / k
        if dev_dt > 0:
            break
    if dev_dt <= 0:
        print(json.dumps({"metric": "bench_timing_unstable", "value": 0,
                          "unit": "bool", "vs_baseline": 0}))
        return 1
    total_bases = int(t_lens.sum())
    bases_per_sec = total_bases / dev_dt

    # ---- consensus parity gate (bit-exact device vs CPU engine)
    from pwasm_tpu.align.msa import best_char_from_counts
    rng = np.random.default_rng(1)
    pileup = rng.integers(0, 7, size=(64, 512)).astype(np.int8)
    votes = np.asarray(consensus_votes(jnp.asarray(pileup)))
    nuc = b"ACGTN-"
    for c in range(pileup.shape[1]):
        counts = [(pileup[:, c] == k).sum() for k in range(6)]
        expect = best_char_from_counts(np.array(counts), sum(counts))
        got = 0 if votes[c] < 0 else nuc[votes[c]]
        if got != expect:
            print(json.dumps({"metric": "consensus_parity", "value": 0,
                              "unit": "bool", "vs_baseline": 0}))
            return 1

    # ---- single-core C++ baseline on a subset, scaled per-base
    from pwasm_tpu.native import banded_gotoh_batch, native_available
    dlo = band_dlo(M, N_PAD, BAND)
    if native_available():
        sub = slice(0, cpu_T)
        t0 = time.perf_counter()
        cpu_scores = banded_gotoh_batch(q, ts[sub], t_lens[sub], BAND, dlo,
                                        params.match, params.mismatch,
                                        params.gap_open, params.gap_extend)
        cpu_dt = time.perf_counter() - t0
        cpu_bases = int(t_lens[sub].sum())
        cpu_bases_per_sec = cpu_bases / cpu_dt
        # score parity between the C++ baseline and the device kernel
        if not np.array_equal(scores_h[sub], cpu_scores):
            print(json.dumps({"metric": "dp_parity", "value": 0,
                              "unit": "bool", "vs_baseline": 0}))
            return 1
        vs_baseline = bases_per_sec / cpu_bases_per_sec
    else:
        vs_baseline = 0.0

    print(json.dumps({
        "metric": "aligned_bases_per_sec_per_chip",
        "value": round(bases_per_sec, 1),
        "unit": "bases/s",
        "vs_baseline": round(vs_baseline, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
