#!/usr/bin/env python
"""pwasm-tpu benchmark — one JSON line per config for the driver.

A bare ``python bench.py`` runs ALL configs sequentially (each in its own
bounded subprocess), prints each config's JSON line as it completes with
the headline config (2) LAST, and writes the full table to
``BENCH_ALL.json``.  ``PWASM_BENCH_CONFIG=k`` runs a single config:

1. end-to-end ``pafreport`` CPU reference: 1 CDS vs 1 Nanopore-style
   assembly through the real CLI (parse -> diff extraction -> context ->
   codon impact -> report), metric = wall seconds per run.
2. batched banded affine-gap DP re-align, 1 CDS (~1.5 kb) vs 10k targets,
   band 64, one chip — aligned target bases/sec (headline metric).
3. many-to-many: 500 CDS x 10k targets on the 2-D (query x target) tile
   map, one chip — aligned base-pairs/sec (per-pair target bases).
4. MSA consensus: 256-deep pileup, per-column ACGT/N/gap count + vote
   Pallas kernel — pileup bases/sec, bit-exact vs the CPU engine vote.
5. long-read 50 kb banded DP, HBM-streaming double-buffered wavefront —
   aligned target bases/sec.
6. re-aligner end-to-end: banded DP with device traceback (forward pass
   emitting packed pointers + lax.scan walk) on 1 CDS vs 10k targets,
   plus the host op->GapData conversion — re-aligned target bases/sec,
   parity-gated against the unbanded full-Gotoh host oracle.
7. device X-drop clip refinement: 256-member ~1.5 kb pileup, the jitted
   dense phase program vs the host 2-D numpy batch pass — layout
   cells/sec, vs_baseline = wall speedup over the host pass.

``vs_baseline`` is the speedup over the single-core CPU equivalent of the
same computation (C++ banded Gotoh for DP configs, the reference-style
per-column qsort vote for consensus; the reference itself is a
single-threaded C++ program, Makefile:64-66, and publishes no numbers —
BASELINE.md).  Config 1 reports vs_baseline=1.0 by definition: it IS the
CPU reference point.

Parity gates (device vs CPU bit-exact) run inside each config; a mismatch
fails the run with a zero-value JSON line.

Timing note: the TPU here sits behind a tunnel with a ~70 ms host
round-trip, so timing fetch-per-rep measures the tunnel, not the chip
(and ``block_until_ready`` alone can return before the remote execution
actually runs).  Device configs therefore time a DEPENDENCY-CHAINED
pipeline of launches (each rep consumes the previous rep's output through
``lax.optimization_barrier``, so no rep can be elided or reordered)
ending in one host fetch, at two pipeline depths k and 2k; per-rep time
is ``(t(2k) - t(k)) / k``, which cancels the fixed round-trip latency.

Env knobs: PWASM_BENCH_CONFIG (1-7, or unset/'all' for the full table),
PWASM_BENCH_T (targets,
default 10240), PWASM_BENCH_Q (config-3 queries, default 500),
PWASM_BENCH_KERNEL=pallas|stream|xla (config-2 kernel, default pallas),
PWASM_BENCH_BAND (default 64), PWASM_BENCH_CPU_T (CPU-baseline subset,
default 32), PWASM_BENCH_REPS (pipeline depth k, default 8),
PWASM_BENCH_CTILE (config-4 column-tile override for on-chip sweeps),
PWASM_DP_IYCHAIN=log|two_level (config-2 Iy-chain variant A/B),
PWASM_BENCH_PROFILE=DIR (write one jax.profiler trace of the pipelined
run before timing).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

BAND = int(os.environ.get("PWASM_BENCH_BAND", "64"))
CPU_T = int(os.environ.get("PWASM_BENCH_CPU_T", "32"))
REPS = int(os.environ.get("PWASM_BENCH_REPS", "8"))


def _workload(T: int, m: int, seed: int = 0, max_subs: int = 40,
              max_indels: int = 8):
    """One random query of length m + T mutated copies, padded to
    n = m + BAND//2 (the pad also anchors the band)."""
    n_pad = m + BAND // 2
    rng = np.random.default_rng(seed)
    q = rng.integers(0, 4, size=m).astype(np.int8)
    ts = np.full((T, n_pad), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(q)
        for _ in range(int(rng.integers(5, max_subs))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        for _ in range(int(rng.integers(0, max_indels))):
            p = int(rng.integers(1, len(t) - 1))
            if rng.random() < 0.5:
                t.insert(p, int(rng.integers(0, 4)))
            else:
                del t[p]
        t = t[:n_pad]
        ts[k, :len(t)] = t
        t_lens[k] = len(t)
    return q, ts, t_lens


_WATCHDOG = None


def _arm_watchdog() -> None:
    """Hard deadline: if the bench has not emitted its JSON line after
    PWASM_BENCH_WATCHDOG seconds (default 1800, 0 disables), print a
    structured failure line and exit — a mid-run tunnel hang must never
    leave the driver with no output at all."""
    global _WATCHDOG
    try:
        secs = float(os.environ.get("PWASM_BENCH_WATCHDOG", "1800"))
    except ValueError:
        secs = 1800.0
    if secs <= 0:
        return
    import threading

    def fire():
        print(json.dumps({"metric": "bench_watchdog_timeout", "value": 0,
                          "unit": "bool", "vs_baseline": 0}), flush=True)
        os._exit(1)

    _WATCHDOG = threading.Timer(secs, fire)
    _WATCHDOG.daemon = True
    _WATCHDOG.start()


def _disarm_watchdog() -> None:
    if _WATCHDOG is not None:
        _WATCHDOG.cancel()


def _json_rows(stdout: str) -> list[dict]:
    """Parse the one-JSON-object-per-line stdout protocol of bench/smoke
    children (stray non-JSON lines and JSON scalars are noise) — the one
    parser shared by run-all and qa/chip_burst.py."""
    rows = []
    for line in stdout.splitlines():
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


def _fail(metric: str) -> int:
    _disarm_watchdog()
    print(json.dumps({"metric": metric, "value": 0, "unit": "bool",
                      "vs_baseline": 0}))
    return 1


def _probe_backend(env: dict, timeout: float) -> tuple[str | None, str]:
    """Bounded which-platform-initializes probe — canonical
    implementation in pwasm_tpu.utils.backend (shared with the CLI's
    --device=tpu health gate); this alias keeps tpu_smoke.py's import
    working."""
    from pwasm_tpu.utils.backend import probe_backend

    return probe_backend(env, timeout)


def _resolve_backend() -> str:
    """Pick a healthy jax backend, degrading instead of dying.

    The TPU sits behind a tunnel (an 'axon' platform a site hook
    registers); when the tunnel is unhealthy the first device query
    either raises RuntimeError or hangs.  Strategy: probe the configured
    platform in a bounded subprocess (twice — tunnel errors can be
    transient); on failure probe relaxed pins (auto, then cpu) and
    re-exec this script under the first env that proves healthy.  The
    bench then still measures and emits its one JSON line on the
    platform it reports to stderr.  A later mid-run hang is bounded by
    the watchdog."""
    probe_t = float(os.environ.get("PWASM_BENCH_PROBE_TIMEOUT", "150"))
    for attempt in range(2):
        p, _why = _probe_backend(dict(os.environ), probe_t)
        if p is not None:
            import jax
            devs = jax.devices()   # proven healthy just now
            print(f"[bench] backend={devs[0].platform} "
                  f"devices={len(devs)}", file=sys.stderr)
            return devs[0].platform
        print(f"[bench] backend probe failed/hung "
              f"(attempt {attempt + 1}/2, timeout {probe_t:.0f}s)",
              file=sys.stderr)
    if "PWASM_BENCH_FALLBACK" not in os.environ:  # never re-exec twice
        # the '' (auto-select) pin is only worth a probe when it differs
        # from the env that just failed — i.e. when a non-empty pin was set
        pins = [""] if os.environ.get("JAX_PLATFORMS") else []
        for pin in pins + ["cpu"]:
            if pin == "cpu":
                env = _cpu_pin_env(dict(os.environ))
            else:
                env = dict(os.environ, JAX_PLATFORMS=pin,
                           PWASM_BENCH_FALLBACK=pin or "auto")
            if _probe_backend(env, probe_t)[0] is not None:
                print(f"[bench] re-exec with JAX_PLATFORMS={pin!r}",
                      file=sys.stderr)
                sys.stderr.flush()
                sys.stdout.flush()
                os.execve(sys.executable, [sys.executable] + sys.argv,
                          env)
    raise RuntimeError("no healthy jax backend (tunnel down; cpu probe "
                       "failed too)")


def _cpu_pin_env(env: dict) -> dict:
    """The one recipe for pinning a child process to the CPU backend
    (used by _resolve_backend's re-exec and run-all's pre-pin)."""
    env.update(JAX_PLATFORMS="cpu", PWASM_BENCH_FALLBACK="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return env


def _scale_for_fallback(cfg: str) -> None:
    """Shrink the workload when the chip is unreachable and the bench
    fell back to host CPU, so it completes in minutes rather than hours.
    Explicit PWASM_BENCH_* env settings always win; the measured rate is
    still honest for the platform reported to stderr."""
    global REPS
    small_t = {"2": "512", "3": "256", "4": str(1 << 16), "5": "4",
               "6": "256", "7": "64"}
    if cfg in small_t:
        os.environ.setdefault("PWASM_BENCH_T", small_t[cfg])
    if cfg == "3":
        os.environ.setdefault("PWASM_BENCH_Q", "8")
    # interpreter-mode Pallas on CPU is orders of magnitude too slow to
    # time; the XLA lowering of the same recurrence is the honest CPU
    # stand-in (bit-exactness between the two is gated by the test suite)
    os.environ.setdefault("PWASM_BENCH_KERNEL", "xla")
    if "PWASM_BENCH_REPS" not in os.environ:
        REPS = 2


def _pipe_rate(run_fn, arg, zero, work_per_rep: float, reps: int = 0):
    """Latency-cancelling pipelined rate: work units per second, or None
    if the timer never stabilizes.  ``run_fn(arg, prev)`` must consume
    ``prev`` (the previous rep's output) through an optimization_barrier,
    and must already be compiled (every caller fetches one result first
    for its parity gate, which compiles and settles the function).
    """
    reps = reps or REPS

    def pipe(reps):
        prev = zero
        t0 = time.perf_counter()
        for _ in range(reps):
            prev = run_fn(arg, prev)
        np.asarray(prev)                    # one fetch drains the chain
        return time.perf_counter() - t0

    pipe(2)                                 # warm the dispatch path
    prof_dir = os.environ.get("PWASM_BENCH_PROFILE", "")
    if prof_dir:
        # one profiled pipeline for where-does-the-time-go analysis
        # (device trace viewable offline); timing below stays unprofiled
        import jax

        with jax.profiler.trace(prof_dir):
            pipe(reps)
        print(f"[bench] profile written to {prof_dir}", file=sys.stderr)
    # the chip is shared: other tenants' work landing inside a window
    # skews a single differenced estimate either way (an inflated
    # pipe(k) makes the difference too small, an inflated pipe(2k) too
    # large) — the median of several estimates is robust to both
    ests = []
    for _ in range(5):
        dt = (pipe(2 * reps) - pipe(reps)) / reps
        if dt > 0:
            ests.append(dt)
    if not ests:
        return None
    ests.sort()
    return work_per_rep / ests[len(ests) // 2]


def _numpy_banded_gotoh(q, t, t_len, band, dlo, params) -> int:
    """Row-wavefront banded Gotoh in plain numpy (no jax) — the
    independent parity reference when the native library is absent."""
    NEG = -(2 ** 30)
    ge, go = params.gap_extend, params.gap_open + params.gap_extend
    m, n = len(q), t_len
    bidx = np.arange(band)
    j0 = dlo + bidx
    M = np.where(j0 == 0, 0, NEG).astype(np.int64)
    Iy = np.where((j0 >= 1) & (j0 <= n), -(go + (j0 - 1) * ge),
                  NEG).astype(np.int64)
    Ix = np.full(band, NEG, dtype=np.int64)
    for i in range(1, m + 1):
        j = i + dlo + bidx
        valid = (j >= 1) & (j <= n)
        tj = np.where(valid, t[np.clip(j - 1, 0, len(t) - 1)], 127)
        s = np.where((tj == q[i - 1]) & (q[i - 1] < 4), params.match,
                     -params.mismatch)
        diag = np.maximum(M, np.maximum(Ix, Iy))
        M2 = np.where(valid, diag + s, NEG)
        upM = np.append(M[1:], NEG)
        upIx = np.append(Ix[1:], NEG)
        Ix2 = np.maximum(upM - go, upIx - ge)
        Ix2 = np.where(j == 0, -(go + (i - 1) * ge), Ix2)
        Ix2 = np.where((j < 0) | (j > n), NEG, Ix2)
        run = np.maximum.accumulate(M2 + bidx * ge)
        run_prev = np.append(NEG, run[:-1])
        Iy2 = np.where(valid, run_prev - go - (bidx - 1) * ge, NEG)
        M, Ix, Iy = M2, Ix2, Iy2
    b_end = n - m - dlo
    if b_end < 0 or b_end >= band:
        return -(2 ** 30)
    return int(max(M[b_end], Ix[b_end], Iy[b_end]))


def _gotoh_cpu_rate(q, ts, t_lens, band, scores_expect) -> float | None:
    """Single-core C++ banded-Gotoh bases/sec on a subset; also the DP
    parity gate.  Returns None on parity mismatch, 0.0 when the native
    library is unavailable — in that case the parity gate still runs,
    against the XLA scan path (a fully independent lowering of the same
    recurrence), so no config ever skips its bit-exactness check."""
    from pwasm_tpu.native import banded_gotoh_batch, native_available
    from pwasm_tpu.ops.banded_dp import ScoreParams, band_dlo

    params = ScoreParams()
    sub = slice(0, min(CPU_T, ts.shape[0]))
    dlo = band_dlo(len(q), ts.shape[1], band)
    if not native_available():
        # still gate parity, against a plain-numpy banded Gotoh — an
        # implementation independent of every jax lowering (the XLA scan
        # path could BE the kernel under test when PWASM_BENCH_KERNEL=xla)
        few = slice(0, min(4, ts.shape[0]))
        ref = np.array([_numpy_banded_gotoh(q, ts[k], int(t_lens[k]),
                                            band, dlo, params)
                        for k in range(few.stop)], dtype=np.int32)
        return None if not np.array_equal(scores_expect[few], ref) else 0.0
    t0 = time.perf_counter()
    cpu_scores = banded_gotoh_batch(q, ts[sub], t_lens[sub], band, dlo,
                                    params.match, params.mismatch,
                                    params.gap_open, params.gap_extend)
    cpu_dt = time.perf_counter() - t0
    if not np.array_equal(scores_expect[sub], cpu_scores):
        return None
    return float(t_lens[sub].sum()) / cpu_dt


def _sig(x: float, digits: int = 4) -> float:
    """Round to significant digits (plain round-to-decimals destroys
    sub-second wall times and adds nothing to multi-gigabase rates)."""
    if x == 0:
        return 0.0
    import math
    return round(x, digits - 1 - int(math.floor(math.log10(abs(x)))))


_METRIC_PREFIX = ""   # "cpu_fallback_" when the chip was unreachable


def _emit(metric, value, unit, vs_baseline, cpu_metric=False) -> int:
    """``cpu_metric=True`` marks a metric that measures the host path
    by design (config-1/8 CPU references): the chip-unreachable rename
    would be misleading there, so the prefix is skipped."""
    _disarm_watchdog()
    prefix = "" if cpu_metric else _METRIC_PREFIX
    print(json.dumps({"metric": prefix + metric,
                      "value": _sig(value), "unit": unit,
                      "vs_baseline": _sig(vs_baseline)}))
    return 0


# ---------------------------------------------------------------------------
# config 1 — end-to-end CPU reference: CLI on 1 CDS vs 1 assembly.
# The timed reference is the standalone C++ binary (pwasm_tpu/native/
# pafreport) — the honest analog of the reference's single-core C++
# program — with the Python CLI's wall as a secondary metric and a
# byte-parity gate between the two reports.
# ---------------------------------------------------------------------------
def cfg1_cli_cpu_ref() -> int:
    import subprocess
    import tempfile

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from pwasm_tpu.native import native_cli_path
    from tests.helpers import make_paf_line

    rng = np.random.default_rng(0)
    cds = "ATG" + "".join("ACGT"[i] for i in rng.integers(0, 4, 1494)) + \
        "TAA"
    ops = []
    pos = 0
    for cut in (200, 500, 900, 1200):   # a few subs + one ins + one del
        ops.append(("=", cut - pos))
        qb = cds[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops.append(("*", tb, qb))
        pos = cut + 1
    ops.append(("=", 99))        # pos 1201 -> 1300
    ops.append(("ins", "TT"))
    ops.append(("del", 3))       # pos 1300 -> 1303
    ops.append(("=", len(cds) - 1303))
    line, _ = make_paf_line("cds1", cds, "asm1", "+", ops, nm=6, score=80)
    with tempfile.TemporaryDirectory() as d:
        fa = os.path.join(d, "cds.fa")
        paf = os.path.join(d, "in.paf")
        out = os.path.join(d, "report.dfa")
        out_native = os.path.join(d, "report_native.dfa")
        with open(fa, "w") as f:
            f.write(f">cds1\n{cds}\n")
        with open(paf, "w") as f:
            f.write(line + "\n")
        cmd = [sys.executable, "-m", "pwasm_tpu.cli", paf, "-r", fa,
               "-o", out]
        repo = os.path.dirname(os.path.abspath(__file__))
        old_pp = os.environ.get("PYTHONPATH", "")
        env = dict(os.environ,
                   PYTHONPATH=repo + (os.pathsep + old_pp if old_pp
                                      else ""))
        # pin the child to CPU: the CLI's plain report path never
        # touches jax, but this environment's site hook performs a
        # tunnel handshake at interpreter start (~1.6 s) unless pinned —
        # py_cli_wall_s should measure the CLI, not the hook
        env.update(JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        py_times = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = subprocess.run(cmd, env=env, capture_output=True)
            py_times.append(time.perf_counter() - t0)
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:2000])
                return _fail("cli_cpu_ref")
        with open(out) as f:
            body = f.read()
        if "S\t" not in body or "coverage:" not in body:
            return _fail("cli_cpu_ref_output")
        cli_bin = native_cli_path()
        if cli_bin is None:
            # no toolchain: record the Python CLI wall under a DISTINCT
            # name — the native reference is ~800x faster, so reusing
            # cpu_ref_wall_s would corrupt cross-round comparability
            return _emit("cpu_ref_pycli_wall_s", min(py_times), "s", 1.0)
        ncmd = [cli_bin, paf, "-r", fa, "-o", out_native]
        nat_times = []
        for _ in range(5):
            t0 = time.perf_counter()
            r = subprocess.run(ncmd, capture_output=True)
            nat_times.append(time.perf_counter() - t0)
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:2000])
                return _fail("native_cpu_ref")
        with open(out_native) as f:
            if f.read() != body:  # byte-parity gate (the bench contract)
                return _fail("native_cli_parity")
        _emit("py_cli_wall_s", min(py_times), "s",
              min(nat_times) / min(py_times))
    return _emit("cpu_ref_wall_s", min(nat_times), "s", 1.0)


# ---------------------------------------------------------------------------
# config 2 — headline: batched banded DP, 1 CDS vs 10k targets
# ---------------------------------------------------------------------------
def cfg2_batched_dp() -> int:
    import jax
    import jax.numpy as jnp

    from pwasm_tpu.ops.banded_dp import (ScoreParams, banded_scores_batch,
                                         banded_scores_long,
                                         banded_scores_pallas)
    from pwasm_tpu.ops.consensus import consensus_votes

    T = int(os.environ.get("PWASM_BENCH_T", "10240"))
    kernel = os.environ.get("PWASM_BENCH_KERNEL", "pallas")
    params = ScoreParams()
    q, ts, t_lens = _workload(T, m=1500)
    qd, tsd, tld = jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens)

    if kernel == "pallas":
        def score_fn(tl_in):
            return banded_scores_pallas(qd, tsd, tl_in, band=BAND,
                                        params=params)
    elif kernel == "packed":
        from pwasm_tpu.ops.pack import banded_scores_packed, pack_targets
        tspd = jnp.asarray(pack_targets(ts))  # 127 pad packs as 'A'
        n_cols = ts.shape[1]

        def score_fn(tl_in):
            return banded_scores_packed(qd, tspd, n_cols, tl_in,
                                        band=BAND, params=params)
    elif kernel == "stream":
        def score_fn(tl_in):
            return banded_scores_long(qd, tsd, tl_in, band=BAND,
                                      params=params, chunk=512)
    else:
        def score_fn(tl_in):
            return banded_scores_batch(qd, tsd, tl_in, band=BAND,
                                       params=params)

    @jax.jit
    def chained(tl_in, prev):
        tl_in, _ = jax.lax.optimization_barrier((tl_in, prev))
        return score_fn(tl_in)

    zero = jnp.zeros_like(tld)
    scores_h = np.asarray(chained(tld, zero))
    rate = _pipe_rate(chained, tld, zero, float(t_lens.sum()))
    if rate is None:
        return _fail("bench_timing_unstable")

    # consensus parity gate (bit-exact device vs CPU engine)
    from pwasm_tpu.align.msa import best_char_from_counts
    rng = np.random.default_rng(1)
    pileup = rng.integers(0, 7, size=(64, 512)).astype(np.int8)
    votes = np.asarray(consensus_votes(jnp.asarray(pileup)))
    nuc = b"ACGTN-"
    for c in range(pileup.shape[1]):
        counts = [(pileup[:, c] == k).sum() for k in range(6)]
        expect = best_char_from_counts(np.array(counts), sum(counts))
        got = 0 if votes[c] < 0 else nuc[votes[c]]
        if got != expect:
            return _fail("consensus_parity")

    cpu_rate = _gotoh_cpu_rate(q, ts, t_lens, BAND, scores_h)
    if cpu_rate is None:
        return _fail("dp_parity")
    return _emit("aligned_bases_per_sec_per_chip", rate, "bases/s",
                 rate / cpu_rate if cpu_rate else 0.0)


# ---------------------------------------------------------------------------
# config 3 — many-to-many: Q CDS x T targets, 2-D tile map
# ---------------------------------------------------------------------------
def cfg3_many2many() -> int:
    import jax
    import jax.numpy as jnp

    from pwasm_tpu.ops import on_tpu_backend
    from pwasm_tpu.parallel.many2many import (many2many_scores,
                                              many2many_scores_pallas)

    Q = int(os.environ.get("PWASM_BENCH_Q", "500"))
    T = int(os.environ.get("PWASM_BENCH_T", "10240"))
    m = 1500
    q0, ts, t_lens = _workload(T, m=m, seed=0)
    rng = np.random.default_rng(7)
    qs = np.empty((Q, m), dtype=np.int8)
    qs[0] = q0
    for i in range(1, Q):
        qi = q0.copy()
        idx = rng.integers(0, m, size=30)
        qi[idx] = rng.integers(0, 4, size=30).astype(np.int8)
        qs[i] = qi
    qsd, tsd, tld = jnp.asarray(qs), jnp.asarray(ts), jnp.asarray(t_lens)
    # off-TPU (chip unreachable -> cpu fallback) the Pallas kernel would
    # run in interpreter mode — time the XLA lowering instead
    m2m_fn = many2many_scores_pallas if on_tpu_backend() else \
        many2many_scores

    @jax.jit
    def chained(tl_in, prev):
        tl_in, _ = jax.lax.optimization_barrier((tl_in, prev))
        return m2m_fn(qsd, tsd, tl_in, band=BAND)

    zero = jnp.zeros_like(tld)
    scores_h = np.asarray(chained(tld, zero))
    # each rep is Q full DP batches (~4 s) — shallow pipeline suffices
    rate = _pipe_rate(chained, tld, zero, float(t_lens.sum()) * Q,
                      reps=max(1, REPS // 8))
    if rate is None:
        return _fail("bench_timing_unstable")

    # parity gate on one query row vs the C++ single-core baseline
    cpu_rate = _gotoh_cpu_rate(q0, ts, t_lens, BAND, scores_h[0])
    if cpu_rate is None:
        return _fail("dp_parity")
    return _emit("m2m_aligned_bases_per_sec_per_chip", rate, "bases/s",
                 rate / cpu_rate if cpu_rate else 0.0)


# ---------------------------------------------------------------------------
# config 4 — consensus vote kernel: 256-deep pileup
# ---------------------------------------------------------------------------
def cfg4_consensus() -> int:
    import jax
    import jax.numpy as jnp

    from pwasm_tpu.align.msa import best_char_from_counts
    from pwasm_tpu.ops import on_tpu_backend
    from pwasm_tpu.ops.consensus import (consensus_pallas, consensus_votes,
                                         votes_to_chars)

    on_tpu = on_tpu_backend()  # off-TPU: XLA path, not interpreted Pallas
    depth = 256
    # the vote kernel runs at HBM speed (~0.3 ms/GB), while each host
    # dispatch through the shared tunnel costs ~1-2 ms — at the old
    # 1 M-column shape every capture was dispatch-bound and the recorded
    # rate swung 160-730 G bases/s run-to-run.  Size one launch to ~4 GB
    # (several ms of device work) so the pipelined timing is device-bound;
    # the pileup is generated ON device (a 4 GB host transfer through the
    # tunnel would take minutes).
    cols = int(os.environ.get("PWASM_BENCH_T",
                              str(1 << 24 if on_tpu else 1 << 20)))

    @functools.partial(jax.jit, static_argnames=("d", "c"))
    def make_pileup(key, d, c):
        # realistic pileup: mostly agreeing bases + 10% noise/gaps
        k1, k2, k3 = jax.random.split(key, 3)
        true_base = jax.random.randint(k1, (c,), 0, 4, dtype=jnp.int8)
        noise = jax.random.uniform(k2, (d, c)) < 0.10
        rand = jax.random.randint(k3, (d, c), 0, 6, dtype=jnp.int8)
        return jnp.where(noise, rand, true_base[None, :])

    # PWASM_BENCH_CTILE overrides the kernel's depth-aware column tile
    # (for on-chip tile sweeps; 0/unset = the kernel's default)
    ctile = int(os.environ.get("PWASM_BENCH_CTILE", "0")) or None

    @jax.jit
    def chained(p_in, prev):
        p_in, _ = jax.lax.optimization_barrier((p_in, prev))
        if on_tpu:
            # the generated pileup holds codes 0..5 only: use the same
            # remap-free path the product consensus uses
            votes, _counts = consensus_pallas(p_in, col_tile=ctile,
                                              assume_valid=True)
        else:
            votes = consensus_votes(p_in)
        return votes

    # a 4 GB pileup is comfortable on an idle 16 GB v5e but can OOM on
    # a busy shared chip — on an OOM (and only an OOM: anything else is
    # a real bug and must fail the config) drop the buffers, shrink and
    # retry down to the 1 M-column floor; the timed loop runs inside
    # the same guard because another tenant can OOM us mid-measurement
    pd = zero = None
    while True:
        try:
            pd = make_pileup(jax.random.PRNGKey(3), depth, cols)
            zero = jnp.zeros((cols,), jnp.int8)
            votes_h = np.asarray(chained(pd, zero))
            rate = _pipe_rate(chained, pd, zero, float(depth * cols))
            break
        except Exception as e:
            msg = f"{type(e).__name__}: {e}"
            oomish = ("RESOURCE_EXHAUSTED" in msg
                      or "out of memory" in msg.lower()
                      or "ran out of memory" in msg.lower())
            pd = zero = None  # release before the smaller attempt
            if not oomish:
                raise
            nxt = max(cols // 4, 1 << 20)  # never shrink below the
            # 1 M-column floor (smaller is dispatch-bound/unstable)
            if nxt >= cols:
                raise
            cols = nxt
            print(f"[bench] device OOM ({msg[:200]}); retrying with "
                  f"cols={cols}", file=sys.stderr)
    if rate is None:
        return _fail("bench_timing_unstable")

    # bit-exact parity + single-core C++ vote baseline over a fetched
    # column subset (the full device pileup would be a huge transfer)
    from pwasm_tpu.native import consensus_vote_pileup, native_available
    sub = min(cols, 1 << 18)
    pileup_sub = np.asarray(pd[:, :sub])
    got_chars = votes_to_chars(votes_h[:sub], star_gap=False)
    if native_available():
        t0 = time.perf_counter()
        cpu_chars = consensus_vote_pileup(pileup_sub)
        cpu_dt = time.perf_counter() - t0
        if got_chars != cpu_chars.tobytes():
            return _fail("consensus_parity")
        cpu_rate = depth * sub / cpu_dt
    else:  # parity vs the Python engine vote on a subset; no baseline
        counts_np = np.stack([(pileup_sub == k).sum(0)
                              for k in range(6)], 0)
        psub = min(sub, 4096)
        expect = bytes(
            best_char_from_counts(counts_np[:, c],
                                  int(counts_np[:, c].sum()))
            for c in range(psub))
        if got_chars[:psub] != expect:
            return _fail("consensus_parity")
        cpu_rate = 0.0
    return _emit("pileup_bases_per_sec_per_chip", rate, "bases/s",
                 rate / cpu_rate if cpu_rate else 0.0)


# ---------------------------------------------------------------------------
# config 5 — long-read 50 kb banded DP, HBM-streaming wavefront
# ---------------------------------------------------------------------------
def cfg5_longread() -> int:
    import jax
    import jax.numpy as jnp

    from pwasm_tpu.ops import on_tpu_backend
    from pwasm_tpu.ops.banded_dp import (ScoreParams, banded_scores_batch,
                                         banded_scores_long)

    T = int(os.environ.get("PWASM_BENCH_T", "256"))
    m = 50_000
    params = ScoreParams()
    q, ts, t_lens = _workload(T, m=m, seed=5, max_subs=400, max_indels=12)
    qd, tsd, tld = jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens)
    on_tpu = on_tpu_backend()  # off-TPU: XLA path, not interpreted Pallas

    @jax.jit
    def chained(tl_in, prev):
        tl_in, _ = jax.lax.optimization_barrier((tl_in, prev))
        if on_tpu:
            return banded_scores_long(qd, tsd, tl_in, band=BAND,
                                      params=params, chunk=1024)
        return banded_scores_batch(qd, tsd, tl_in, band=BAND,
                                   params=params)

    zero = jnp.zeros_like(tld)
    scores_h = np.asarray(chained(tld, zero))
    rate = _pipe_rate(chained, tld, zero, float(t_lens.sum()))
    if rate is None:
        return _fail("bench_timing_unstable")

    cpu_rate = _gotoh_cpu_rate(q, ts, t_lens, BAND, scores_h)
    if cpu_rate is None:
        return _fail("dp_parity")
    return _emit("longread_bases_per_sec_per_chip", rate, "bases/s",
                 rate / cpu_rate if cpu_rate else 0.0)


# ---------------------------------------------------------------------------
# config 6 — re-aligner end-to-end: device traceback + host gap conversion
# ---------------------------------------------------------------------------
def cfg6_realign() -> int:
    import jax
    import jax.numpy as jnp

    from pwasm_tpu.ops.banded_dp import ScoreParams, band_dlo
    from pwasm_tpu.ops.realign import (banded_realign_rows, _gaps_jit,
                                       banded_traceback_batch,
                                       full_gotoh_traceback,
                                       gap_slots_to_gapdata, ops_consumed,
                                       ops_forward, ops_score,
                                       rows_to_ops_fwd)

    T = int(os.environ.get("PWASM_BENCH_T", "10240"))
    params = ScoreParams()
    q, ts, t_lens = _workload(T, m=1500)
    q_lens = np.full(T, len(q), dtype=np.int32)
    dlo = band_dlo(len(q), ts.shape[1], BAND)
    qsd = jnp.asarray(np.broadcast_to(q, (T, len(q))).copy())
    tsd = jnp.asarray(ts)
    qld, tld = jnp.asarray(q_lens), jnp.asarray(t_lens)

    # parity gate: 12 small random pairs, device path (same band) vs the
    # unbanded full-Gotoh host oracle — scores AND op strings identical
    rng = np.random.default_rng(11)
    small = []
    for _ in range(12):
        m_s = int(rng.integers(40, 150))
        qq = rng.integers(0, 4, size=m_s).astype(np.int8)
        tt = list(qq)
        for _ in range(int(rng.integers(0, 10))):
            p = int(rng.integers(1, len(tt) - 1))
            r = rng.random()
            if r < 0.4:
                tt[p] = int(rng.integers(0, 4))
            elif r < 0.7:
                tt.insert(p, int(rng.integers(0, 4)))
            else:
                del tt[p]
        small.append((qq, np.array(tt, dtype=np.int8)))
    sm = max(len(p[0]) for p in small)
    sn = max(len(p[1]) for p in small)
    sqs = np.full((12, sm), 127, dtype=np.int8)
    sts = np.full((12, sn), 127, dtype=np.int8)
    for k, (qq, tt) in enumerate(small):
        sqs[k, :len(qq)] = qq
        sts[k, :len(tt)] = tt
    sql = np.array([len(p[0]) for p in small], dtype=np.int32)
    stl = np.array([len(p[1]) for p in small], dtype=np.int32)
    sc_d, ops_d, ok_d = banded_traceback_batch(
        jnp.asarray(sqs), jnp.asarray(sts), jnp.asarray(sql),
        jnp.asarray(stl), band=BAND, params=params)
    sc_d, ops_d, ok_d = (np.asarray(sc_d), np.asarray(ops_d),
                         np.asarray(ok_d))
    for k, (qq, tt) in enumerate(small):
        sc_o, ops_o = full_gotoh_traceback(qq, tt, params)
        if (not ok_d[k] or int(sc_d[k]) != sc_o
                or not np.array_equal(ops_forward(ops_d[k]), ops_o)):
            return _fail("realign_parity")

    # full end-to-end pass once: device forward+walk+gap-extraction, gap
    # slots fetched, converted to GapData on host; every lane must close
    scores_d, leads_d, iy_d, ops_d, ok_d = banded_realign_rows(
        qsd, tsd, qld, tld, band=BAND, params=params, dlo=dlo)
    slots = _gaps_jit(leads_d, iy_d, ops_d, qld, 32)
    scores_h = np.asarray(scores_d)
    ok_h = np.asarray(ok_d)
    rg_pos, rg_len, r_cnt, tg_pos, tg_len, t_cnt, ovf = \
        (np.asarray(x) for x in slots)
    if not ok_h.all() or ovf.any():
        return _fail("realign_band_coverage")
    n_gaps = 0
    for k in range(T):
        rg, tg = gap_slots_to_gapdata(
            rg_pos[k], rg_len[k], int(r_cnt[k]), tg_pos[k], tg_len[k],
            int(t_cnt[k]), 0, len(q), int(t_lens[k]), 0)
        n_gaps += len(rg) + len(tg)
    if n_gaps == 0:
        return _fail("realign_no_gaps")
    # spot-check: the walked path achieves the DP score and consumes the
    # full sequences (independent re-walk over the expanded ops)
    iy_h, opr_h, leads_h = (np.asarray(iy_d), np.asarray(ops_d),
                            np.asarray(leads_d))
    for k in range(0, T, max(1, T // 16)):
        fwd = rows_to_ops_fwd(int(leads_h[k]), iy_h[k], opr_h[k],
                              int(q_lens[k]))
        if ops_consumed(fwd) != (int(q_lens[k]), int(t_lens[k])):
            return _fail("realign_ops_consumed")
        if ops_score(fwd, np.asarray(q), ts[k], params) != int(scores_h[k]):
            return _fail("realign_ops_score")

    # throughput: latency-cancelling pipelined rate of the full device
    # program (forward + row-walk + gap extraction)
    @jax.jit
    def chained(tl_in, prev):
        tl_in, _ = jax.lax.optimization_barrier((tl_in, prev))
        s, leads, iy, ops_r, _ok = banded_realign_rows(
            qsd, tsd, qld, tl_in, band=BAND, params=params, dlo=dlo)
        g = _gaps_jit(leads, iy, ops_r, qld, 32)
        return s + g[2] + g[5]

    zero = jnp.zeros_like(tld)
    np.asarray(chained(tld, zero))
    rate = _pipe_rate(chained, tld, zero, float(t_lens.sum()))
    if rate is None:
        return _fail("bench_timing_unstable")

    cpu_rate = _gotoh_cpu_rate(q, ts, t_lens, BAND, scores_h)
    if cpu_rate is None:
        return _fail("dp_parity")
    return _emit("realign_bases_per_sec_per_chip", rate, "bases/s",
                 rate / cpu_rate if cpu_rate else 0.0)


# ---------------------------------------------------------------------------
# config 7 — device X-drop clip refinement (VERDICT r3 item 3)
# ---------------------------------------------------------------------------
def cfg7_refine_clip() -> int:
    """256-member ~1.5 kb consensus pileup with clipped ends: the device
    phase program (ops/refine_clip.py) vs the host 2-D numpy batch pass,
    end-to-end wall per refinement (both include the shared layout
    build; the device side also pays its transfers — the honest
    comparison).  Parity-gated bit-exact each rep."""
    from pwasm_tpu.align.gapseq import GapSeq, refine_clipping_batch

    M = int(os.environ.get("PWASM_BENCH_T", "256"))
    m = 1500
    rng = np.random.default_rng(7)
    base = rng.choice(list(b"ACGT"), m).astype(np.uint8)

    def mk():
        seqs = []
        r = np.random.default_rng(11)
        for k in range(M):
            arr = base.copy()
            idx = r.integers(0, m, 40)
            arr[idx] = r.choice(list(b"ACGT"), 40)
            s = GapSeq(f"r{k}", "", bytes(arr))
            s.clp5 = int(r.integers(1, 30))
            s.clp3 = int(r.integers(1, 30))
            for _ in range(4):
                s.set_gap(int(r.integers(0, m)), 1)
            seqs.append(s)
        return seqs

    cons = bytes(base)
    cells = float(M) * (m + 8)  # layout cells walked per refinement

    host_ref = mk()
    refine_clipping_batch(host_ref, cons, [0] * M)

    def timed(device: bool, reps: int = 5):
        # fresh members each rep: refinement mutates the clip state.
        # Returns (median wall, error label or None) so a mid-run
        # demotion (infra) is distinguishable from a clip mismatch
        # (bit-exactness failure).
        walls = []
        for _ in range(reps):
            seqs = mk()
            t0 = time.perf_counter()
            demoted = refine_clipping_batch(seqs, cons, [0] * M,
                                            device=device)
            walls.append(time.perf_counter() - t0)
            if demoted:
                return None, "refine_clip_device_demoted"
            for s, hr in zip(seqs, host_ref):
                if (s.clp5, s.clp3) != (hr.clp5, hr.clp3):
                    return None, "refine_clip_parity"
        return float(np.median(walls)), None

    warm = mk()  # compile outside the timed reps
    if refine_clipping_batch(warm, cons, [0] * M, device=True):
        return _fail("refine_clip_device_demoted")
    dev_wall, dev_err = timed(True)
    host_wall, host_err = timed(False)
    if dev_wall is None or host_wall is None:
        return _fail(dev_err or host_err)
    return _emit("refine_clip_cells_per_sec", cells / dev_wall,
                 "cells/s", host_wall / dev_wall)


def cfg8_realistic_scale() -> int:
    """Realistic-scale end-to-end CLI (BASELINE.md 'realistic scale'):
    one 1.5 kb CDS vs 200 Nanopore-like assemblies (ragged 50-150 kb,
    35%% reverse, per-base 2-5%% subs + 1-3%% indels incl. a tail past
    the device MAX_EV scope limit), full output set (report + summary +
    MSA + consensus).  The native binary is the single-core reference;
    the Python CLI (host path, CPU-pinned child) is byte-parity-gated
    against it.  On a real TPU backend the --device=tpu wall is also
    captured (unpinned child, same parity gate).

    Additional legs (all CPU-pinned children, backend-agnostic):
    - dispatch budget: a --device=tpu --stats run (cpu-jax backend)
      emits ``realistic_device_flushes`` — the per-run device
      round-trip count the single-digit budget gates;
    - chaos: the same run under seeded --inject-faults must stay
      byte-identical to the clean outputs (resilience at realistic
      scale, ROADMAP PR-1 follow-up);
    - flap: a scripted outage window (down=2-4) must open the global
      breaker mid-run AND be healed by the health monitor
      (``realistic_flap_recovered_batches``, gated on
      breaker_recloses >= 1 / recovered_batches > 0 / byte parity —
      the ISSUE 3 acceptance contract);
    - preempt: a scripted preemption (preempt=3) must drain at a batch
      boundary, exit 75 with a CRC-valid ckpt, and --resume must
      complete byte-identically (``realistic_preempt_resume_parity``);
    - OOM: a simulated memory ceiling (oom=192) must finish on-device
      via batch bisection — splits > 0, demotions > 0, NO breaker
      trip, NO host fallback, byte parity (``realistic_oom_bisect``) —
      the ISSUE 4 acceptance contract;
    - serve: 3 jobs through ONE warm `serve` daemon vs 3 cold runs —
      byte parity for every job, jobs 2..3 pay zero backend probes
      (warm-hit counters > 0), daemon drains to exit 75
      (``realistic_serve_warm_jobs`` — the ISSUE 5 acceptance
      contract);
    - host engines: a 1k-alignment report+summary corpus A/Bs the
      vectorized columnar host engine against the scalar ground-truth
      engine (PWASM_HOST_COLUMNAR=0) — ``realistic_host_report_1k_s``
      with vs_baseline = scalar/columnar speedup;
    - result cache: repeat jobs through a `serve --result-cache`
      daemon answered at admission from stored bytes —
      ``realistic_cache_hit_ratio`` (hit p50 / cold wall, the
      ROADMAP item 2 >= 100x target) + the deterministic parity bool
      (ISSUE 15 acceptance);
    - delta cache: a 10%%-appended 5k-alignment input served as a
      DELTA hit (cached prefix + recomputed tail) at all three tiers
      — cold CLI, daemon admission, router edge —
      ``realistic_cache_delta_ratio`` (worst tier wall / dedicated
      cache-off cold wall, the ISSUE 17 <= 0.3x acceptance) + the
      parity bool (bytes AND truthful cache_delta stats across
      tiers);
    - gray drill: one of three members behind qa/fleet_chaos's delay
      proxy (alive and answering, just slow — the failure liveness
      polls cannot see) must be quarantined within ~3 poll rounds,
      take no new placements while completed jobs stay byte-identical
      and --deadline-s stays truthful mid-chaos, then probation-exit
      once relieved (``realistic_fleet_graydrill_p99_ms``, the ISSUE
      18 acceptance drill);
    - shed floor: sustained queue pressure must brown out the LOWEST
      --priority-lanes tier with a truthful overloaded +
      retry_after_s before any member sees the job, keep admitting
      the top tier throughout, and de-escalate back to level 0 when
      pressure clears (``realistic_fleet_shed_floor``, ISSUE 18);
    - TLS overhead: the same job through an all-TLS 3-member fleet
      (client->router TLS, router->member mTLS) vs an all-plaintext
      fleet on the same TCP topology, byte-identical, wall ratio
      gated <= 1.15 (``realistic_tls_overhead_ratio`` /
      ``realistic_tls_overhead_ok``, ISSUE 19)."""
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    inserted = [repo, os.path.join(repo, "tests")]
    for p in inserted:
        sys.path.insert(0, p)
    try:
        from test_realistic_scale import make_corpus
    finally:
        # pop OUR insertions (first occurrence each) so later --all
        # configs don't resolve imports through tests/ (ADVICE round 5)
        for p in inserted:
            try:
                sys.path.remove(p)
            except ValueError:
                pass

    from pwasm_tpu.native import native_cli_path
    from pwasm_tpu.ops import on_tpu_backend

    qseq, lines = make_corpus()
    with tempfile.TemporaryDirectory() as d:
        fa = os.path.join(d, "cds.fa")
        paf = os.path.join(d, "in.paf")
        with open(fa, "w") as f:
            f.write(f">cds1\n{qseq}\n")
        with open(paf, "w") as f:
            f.write("".join(l + "\n" for l in lines))

        def outset(tag):
            return [os.path.join(d, f"{tag}.{k}")
                    for k in ("dfa", "sum", "mfa", "cons")]

        def args(tag, extra):
            o = outset(tag)
            return [paf, "-r", fa, "-o", o[0], "-s", o[1],
                    "-w", o[2], f"--cons={o[3]}"] + extra

        def readset(tag):
            return b"".join(open(p, "rb").read() for p in outset(tag))

        cli_bin = native_cli_path()
        nat_times = []
        if cli_bin is not None:
            for _ in range(3):
                t0 = time.perf_counter()
                r = subprocess.run([cli_bin] + args("nat", []),
                                   capture_output=True)
                nat_times.append(time.perf_counter() - t0)
                if r.returncode != 0:
                    sys.stderr.write(r.stderr.decode()[:1000])
                    return _fail("realistic_native")

        old_pp = os.environ.get("PYTHONPATH", "")
        env = _cpu_pin_env(dict(
            os.environ,
            PYTHONPATH=repo + (os.pathsep + old_pp if old_pp else "")))
        cmd = [sys.executable, "-m", "pwasm_tpu.cli"]
        py_times = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = subprocess.run(cmd + args("py", []), env=env,
                               capture_output=True)
            py_times.append(time.perf_counter() - t0)
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:1000])
                return _fail("realistic_pycli")
        py_body = readset("py")
        if cli_bin is None:
            # no toolchain: a DISTINCT metric name — reusing
            # realistic_pycli_wall_s with vs_baseline=1.0 would let a
            # toolchain regression masquerade as a perfect-parity run
            # in cross-round comparisons (ADVICE round 5).  The
            # dispatch-budget / chaos / host-engine legs below don't
            # need the native reference (they parity-check against the
            # host run) and still run.
            _emit("realistic_pycli_wall_noref_s", min(py_times),
                  "s", 1.0, cpu_metric=True)
            parity_body = py_body
        else:
            nat_body = readset("nat")
            if py_body != nat_body:
                return _fail("realistic_pycli_parity")
            parity_body = nat_body
            _emit("realistic_native_wall_s", min(nat_times), "s", 1.0,
                  cpu_metric=True)
            _emit("realistic_pycli_wall_s", min(py_times), "s",
                  min(nat_times) / min(py_times), cpu_metric=True)
            # Python-CLI-vs-native multiplier.  vs_baseline records
            # whether the aspirational 1.5x target is met (1.0) or not
            # (0.0), like the other budget-style legs; the enforced
            # regression gate is qa/bench_gate.py comparing the ratio
            # against the committed trajectory (unit "x" =
            # lower-is-better, wall rule)
            ratio = min(py_times) / min(nat_times)
            _emit("realistic_pycli_vs_native_ratio", ratio, "x",
                  1.0 if ratio <= 1.5 else 0.0, cpu_metric=True)

        # --- dispatch budget + chaos (device pipeline on the pinned
        # cpu-jax backend: dispatch counting and fault injection are
        # backend-agnostic, and bytes must match the host run) -------
        stats_p = os.path.join(d, "dev.stats")
        r = subprocess.run(
            cmd + args("devcpu", ["--device=tpu",
                                  f"--stats={stats_p}"]),
            env=env, capture_output=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_devpath")
        if readset("devcpu") != parity_body:
            return _fail("realistic_devpath_parity")
        with open(stats_p) as f:
            dev_stats = json.load(f)["device"]
        # the single-digit dispatch budget (VERDICT r5 item 3)
        budget_ok = 0 < dev_stats["flushes"] <= 9
        _emit("realistic_device_flushes", dev_stats["flushes"],
              "flushes", 1.0 if budget_ok else 0.0, cpu_metric=True)
        r = subprocess.run(
            cmd + args("chaos", ["--device=tpu", "--batch=16",
                                 "--max-retries=4",
                                 "--inject-faults=seed=11,rate=0.4,"
                                 "kinds=raise+nan+corrupt"]),
            env=env, capture_output=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_chaos")
        if readset("chaos") != parity_body:
            return _fail("realistic_chaos_parity")

        # --- flap chaos (PR 3 tentpole): a scripted outage window
        # (down=2-4 over the supervised-call clock) must OPEN the
        # global breaker mid-run, and the health monitor must RECLOSE
        # it after the window and re-promote device work — gated on the
        # recovery counters AND byte parity with the clean run.
        # PWASM_DEVICE_PROBE=0 keeps the out-of-window probe verdict
        # healthy without paying a subprocess jax import per re-probe
        # (the scripted window dominates the in-window verdict either
        # way).
        stats_f = os.path.join(d, "flap.stats")
        r = subprocess.run(
            cmd + args("flap", ["--device=tpu", "--batch=16",
                                "--max-retries=4",
                                "--inject-faults=down=2-4",
                                "--reprobe-interval=0",
                                f"--stats={stats_f}"]),
            env=dict(env, PWASM_DEVICE_PROBE="0"),
            capture_output=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_flap")
        if readset("flap") != parity_body:
            return _fail("realistic_flap_parity")
        with open(stats_f) as f:
            flap_res = json.load(f)["resilience"]
        flap_ok = (flap_res["breaker_recloses"] >= 1
                   and flap_res["recovered_batches"] > 0
                   and flap_res["degraded_batches"] > 0)
        _emit("realistic_flap_recovered_batches",
              flap_res["recovered_batches"], "batches",
              1.0 if flap_ok else 0.0, cpu_metric=True)

        # --- preemption drain + resume (ISSUE 4 tentpole): a scripted
        # preempt=3 over the supervised-call clock drains at a batch
        # boundary — the run must exit 75 ("preempted, resumable")
        # leaving a CRC-valid <report>.ckpt, and --resume must complete
        # it BYTE-IDENTICALLY to the uninterrupted run.  The -s summary
        # is excluded from the parity set by contract (a resumed
        # summary covers only the resumed portion).
        def read_nosum(tag):
            o = outset(tag)
            return b"".join(open(p, "rb").read()
                            for p in (o[0], o[2], o[3]))

        expected_nosum = read_nosum("py")
        r = subprocess.run(
            cmd + args("pre", ["--device=tpu", "--batch=16",
                               "--inject-faults=preempt=3"]),
            env=env, capture_output=True)
        if r.returncode != 75:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_preempt")
        if not os.path.exists(os.path.join(d, "pre.dfa.ckpt")):
            return _fail("realistic_preempt_ckpt")
        r = subprocess.run(
            cmd + args("pre", ["--device=tpu", "--batch=16",
                               "--resume"]),
            env=env, capture_output=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_preempt_resume")
        if read_nosum("pre") != expected_nosum:
            return _fail("realistic_preempt_resume_parity")
        _emit("realistic_preempt_resume_parity", 1, "ok", 1.0,
              cpu_metric=True)

        # --- OOM bisection (ISSUE 4 tentpole): a simulated device
        # memory ceiling (oom=192 items) makes every realistic flush
        # too big — the supervisor must bisect down and demote the
        # pow2 batch ceiling instead of retrying the shape, tripping
        # the breaker, or degrading to the host: the run finishes
        # ON-DEVICE, byte-identical to the clean arm.
        stats_o = os.path.join(d, "oomb.stats")
        r = subprocess.run(
            cmd + args("oomb", ["--device=tpu", "--batch=16",
                                "--inject-faults=oom=192",
                                f"--stats={stats_o}"]),
            env=env, capture_output=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_oom")
        if readset("oomb") != parity_body:
            return _fail("realistic_oom_bisect_parity")
        with open(stats_o) as f:
            oom_js = json.load(f)
        oom_res = oom_js["resilience"]
        oom_ok = (oom_res["oom_events"] > 0
                  and oom_res["batch_splits"] > 0
                  and oom_res["bucket_demotions"] > 0
                  and oom_res["breaker_trips"] == 0
                  and oom_js["fallback_batches"] == 0)
        _emit("realistic_oom_bisect", oom_res["batch_splits"],
              "splits", 1.0 if oom_ok else 0.0, cpu_metric=True)

        # --- warm-pool serve (ISSUE 5 tentpole): the SAME corpus as 3
        # consecutive jobs through ONE `serve` daemon must stay
        # byte-identical to the cold runs, AND jobs 2..3 must pay ZERO
        # additional backend probes (the per-job --stats "backend"
        # block: probes == 0, warm_hits > 0 — the warm-pool promise,
        # gated).  The daemon then drains on the protocol command and
        # exits 75 like a SIGTERM would.
        from pwasm_tpu.service.client import (ServiceClient,
                                              wait_for_socket)
        svc_sock = os.path.join(d, "svc.sock")
        sp = subprocess.Popen(
            cmd + ["serve", f"--socket={svc_sock}", "--max-queue=8"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE)
        serve_rc = None
        warm_ok = True
        try:
            if not wait_for_socket(svc_sock, 120):
                return _fail("realistic_serve_up")
            for j in (1, 2, 3):
                stats_j = os.path.join(d, f"srv{j}.stats")
                with ServiceClient(svc_sock) as c:
                    sub = c.submit(args(
                        f"srv{j}", ["--device=tpu",
                                    f"--stats={stats_j}"]))
                    if not sub.get("ok"):
                        return _fail("realistic_serve_submit")
                    res = c.result(sub["job_id"], timeout=600)
                if not res.get("ok") or res.get("rc") != 0:
                    sys.stderr.write(str(res)[:1000])
                    return _fail("realistic_serve_job")
                if readset(f"srv{j}") != parity_body:
                    return _fail("realistic_serve_parity")
                with open(stats_j) as f:
                    bk = json.load(f).get("backend", {})
                if j > 1 and not (bk.get("probes", 1) == 0
                                  and bk.get("warm_hits", 0) > 0):
                    warm_ok = False
            # warm-serve ratio (ISSUE 8 satellite / ROADMAP item 2
            # lever c): the serving HOT path — a host-path job through
            # the already-warm daemon skips the ~0.44 s
            # interpreter+numpy startup floor every cold CLI run pays
            # — measured against the native binary.  Client-side
            # submit->result wall on an empty queue IS the per-job
            # serving latency.
            warm_walls = []
            for j in (1, 2):
                t0 = time.perf_counter()
                with ServiceClient(svc_sock) as c:
                    sub = c.submit(args(f"srvh{j}", []))
                    if not sub.get("ok"):
                        return _fail("realistic_serve_submit")
                    res = c.result(sub["job_id"], timeout=600)
                warm_walls.append(time.perf_counter() - t0)
                if not res.get("ok") or res.get("rc") != 0:
                    sys.stderr.write(str(res)[:1000])
                    return _fail("realistic_serve_warm_job")
                if readset(f"srvh{j}") != parity_body:
                    return _fail("realistic_serve_warm_parity")
            with ServiceClient(svc_sock) as c:
                c.drain()
            serve_rc = sp.wait(timeout=120)
        except Exception as e:
            sys.stderr.write(f"serve leg: {e}\n")
            return _fail("realistic_serve")
        finally:
            if sp.poll() is None:
                sp.kill()
                sp.wait()
        serve_ok = warm_ok and serve_rc == 75
        _emit("realistic_serve_warm_jobs", 3, "jobs",
              1.0 if serve_ok else 0.0, cpu_metric=True)
        if cli_bin is not None:
            # unit "x" = lower-is-better in qa/bench_gate.py (the wall
            # rule); vs_baseline records the aspirational 2x flag like
            # the pycli ratio's 1.5x
            wr = min(warm_walls) / min(nat_times)
            _emit("realistic_serve_warm_ratio", wr, "x",
                  1.0 if wr <= 2.0 else 0.0, cpu_metric=True)

        # --- content-addressed result cache (ISSUE 15 tentpole): the
        # repeat-job leg.  One `serve --result-cache` daemon: job 1
        # misses (runs + inserts), jobs 2..6 — submitted with a
        # REORDERED argv and their own output paths, so the leg also
        # exercises the flag-canonicalization table — must be
        # answered AT ADMISSION from the stored bytes: byte parity
        # with the cache-off outputs, cache_hit stats with zero
        # backend probes, hits counted in svc-stats.  The p50
        # submit->result wall over the cold-run wall is the gated
        # ratio (unit "x" lower-is-better; the ROADMAP item 2 target
        # is <= 0.01, i.e. >= 100x, recorded in vs_baseline); the
        # bool leg gates only the deterministic facts, per the lanes
        # leg's rule.
        svc7 = os.path.join(d, "svc7.sock")
        cdir = os.path.join(d, "rescache")
        # dedicated COLD ARM: the repeat-job shape is the serving
        # product (-o report + -s summary — what the service's
        # document model serves), and the >=100x denominator is the
        # EXACT job a hit replaces: the same argv as an identical
        # cold CLI run, cache off
        rc_out = [os.path.join(d, "rcold.dfa"),
                  os.path.join(d, "rcold.sum")]
        cold_walls: list[float] = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = subprocess.run(
                cmd + [paf, "-r", fa, "-o", rc_out[0],
                       "-s", rc_out[1]],
                env=env, capture_output=True)
            cold_walls.append(time.perf_counter() - t0)
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:1000])
                return _fail("realistic_cache_cold")
        cold_body = b"".join(open(p, "rb").read() for p in rc_out)

        def cache_out(tag):
            return [os.path.join(d, f"{tag}.dfa"),
                    os.path.join(d, f"{tag}.sum")]

        sp7 = subprocess.Popen(
            cmd + ["serve", f"--socket={svc7}", "--max-queue=16",
                   f"--result-cache={cdir}"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        hit_walls: list[float] = []
        cache_ok = True
        try:
            if not wait_for_socket(svc7, 120):
                return _fail("realistic_cache_up")
            o0 = cache_out("cc0")
            with ServiceClient(svc7) as c:
                sub = c.submit([paf, "-r", fa, "-o", o0[0],
                                "-s", o0[1]])
                if not sub.get("ok"):
                    return _fail("realistic_cache_submit")
                res = c.result(sub["job_id"], timeout=600)
            if not res.get("ok") or res.get("rc") != 0:
                sys.stderr.write(str(res)[:1000])
                return _fail("realistic_cache_job")
            if b"".join(open(p, "rb").read() for p in o0) \
                    != cold_body:
                return _fail("realistic_cache_miss_parity")
            for k in (1, 2, 3, 4, 5):
                stats_k = os.path.join(d, f"cch{k}.stats")
                o = cache_out(f"cch{k}")
                # argv REORDERED vs the populating job: the
                # canonicalization table must still hit
                argv = ["-r", fa, "-o", o[0], paf, "-s", o[1],
                        f"--stats={stats_k}"]
                t0 = time.perf_counter()
                with ServiceClient(svc7) as c:
                    sub = c.submit(argv)
                    if not sub.get("ok"):
                        return _fail("realistic_cache_submit")
                    res = c.result(sub["job_id"], timeout=600)
                hit_walls.append(time.perf_counter() - t0)
                if not res.get("ok") or res.get("rc") != 0:
                    sys.stderr.write(str(res)[:1000])
                    return _fail("realistic_cache_hit_job")
                if b"".join(open(p, "rb").read() for p in o) \
                        != cold_body:
                    cache_ok = False
                with open(stats_k) as f:
                    hst = json.load(f)
                if not (hst.get("cache_hit") is True
                        and hst.get("backend", {}).get(
                            "probes", 1) == 0):
                    cache_ok = False
            with ServiceClient(svc7) as c:
                svc_st7 = c.stats()["stats"]
                c.drain()
            cache_rc = sp7.wait(timeout=120)
            cache_ok = (cache_ok and cache_rc == 75
                        and svc_st7["cache"]["hits"] >= 5
                        and svc_st7["cache"]["insertions"] >= 1)
        except Exception as e:
            sys.stderr.write(f"cache leg: {e}\n")
            return _fail("realistic_cache")
        finally:
            if sp7.poll() is None:
                sp7.kill()
                sp7.wait()
        hit_p50 = sorted(hit_walls)[len(hit_walls) // 2]
        cache_ratio = hit_p50 / min(cold_walls)
        _emit("realistic_cache_hit_ratio", cache_ratio, "x",
              1.0 if cache_ratio <= 0.01 else 0.0, cpu_metric=True)
        _emit("realistic_cache_hit_parity", 1 if cache_ok else 0,
              "bool", 1.0 if cache_ok else 0.0, cpu_metric=True)

        # --- incremental delta-scoring (ISSUE 17 tentpole): the
        # dominant near-repeat — an input that GREW by ~10% — must
        # answer as a DELTA hit (the cached prefix served from
        # CRC-verified bytes, only the tail recomputed) at all three
        # serving tiers: cold CLI, daemon admission, router edge.
        # One dedicated cache-off cold arm on the SAME grown input is
        # every tier's denominator; the gated ratio is the WORST
        # tier's wall over that cold wall (unit "x" lower-is-better;
        # vs_baseline records the ISSUE 17 acceptance <= 0.3, i.e.
        # >= 3x).  The parity bool ANDs byte parity with the cold arm
        # AND truthful stats (cache_delta:true with computed-vs-
        # served record counts, never the hit-shaped cache_hit)
        # across tiers.  Jobs are report-only by the delta-
        # eligibility contract (the fast path is the parse-only
        # --resume replay); each tier gets a FRESH cache dir holding
        # only the prefix entry, because a completed delta run
        # re-populates its own exact entry — sharing one dir would
        # quietly turn the later tiers into plain exact hits.
        dl_q, dl_lines = make_corpus(n_aln=5000)
        dl_fa = os.path.join(d, "dl.fa")
        with open(dl_fa, "w") as f:
            f.write(f">cds1\n{dl_q}\n")
        dl_npre = (len(dl_lines) * 9) // 10
        dl_pre = os.path.join(d, "dl_pre.paf")
        dl_full = os.path.join(d, "dl_full.paf")
        with open(dl_pre, "w") as f:
            f.write("".join(l + "\n" for l in dl_lines[:dl_npre]))
        with open(dl_full, "w") as f:
            f.write("".join(l + "\n" for l in dl_lines))
        dl_cold_out = os.path.join(d, "dl_cold.dfa")
        dl_cold_walls: list[float] = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = subprocess.run(
                cmd + [dl_full, "-r", dl_fa, "-o", dl_cold_out],
                env=env, capture_output=True)
            dl_cold_walls.append(time.perf_counter() - t0)
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:1000])
                return _fail("realistic_cache_delta_cold")
        dl_body = open(dl_cold_out, "rb").read()
        dl_cold = min(dl_cold_walls)
        dl_walls: dict[str, float] = {}
        dl_ok = True

        def dl_check(tag, out_p, stats_p):
            """Byte parity + truthful delta stats for one tier."""
            nonlocal dl_ok
            if open(out_p, "rb").read() != dl_body:
                dl_ok = False
            try:
                with open(stats_p) as f:
                    st = json.load(f)
            except (OSError, ValueError):
                dl_ok = False
                return
            if not (st.get("cache_delta") is True
                    and st.get("cache_records_total")
                    == len(dl_lines)
                    and st.get("cache_records_served", 0)
                    >= dl_npre - 1
                    and "cache_hit" not in st):
                dl_ok = False

        # tier 1: cold CLI — populate with the prefix, then the grown
        # input exact-misses into a family delta hit
        dl_dir1 = os.path.join(d, "dlc1")
        r = subprocess.run(
            cmd + [dl_pre, "-r", dl_fa, "-o",
                   os.path.join(d, "dl_p1.dfa"),
                   f"--result-cache={dl_dir1}"],
            env=env, capture_output=True)
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_cache_delta_populate")
        dl_o1 = os.path.join(d, "dl_t1.dfa")
        dl_s1 = os.path.join(d, "dl_t1.stats")
        t0 = time.perf_counter()
        r = subprocess.run(
            cmd + [dl_full, "-r", dl_fa, "-o", dl_o1,
                   f"--result-cache={dl_dir1}", f"--stats={dl_s1}"],
            env=env, capture_output=True)
        dl_walls["cli"] = time.perf_counter() - t0
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_cache_delta_cli")
        dl_check("cli", dl_o1, dl_s1)

        # tier 2: daemon admission — the serve daemon owns the cache;
        # the grown job is re-armed at admission as an in-process
        # --resume over the served prefix
        dl_dir2 = os.path.join(d, "dlc2")
        svc_dl = os.path.join(d, "svcdl.sock")
        sp_dl = subprocess.Popen(
            cmd + ["serve", f"--socket={svc_dl}", "--max-queue=8",
                   f"--result-cache={dl_dir2}"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE)
        try:
            if not wait_for_socket(svc_dl, 120):
                return _fail("realistic_cache_delta_serve_up")
            with ServiceClient(svc_dl) as c:
                sub = c.submit([dl_pre, "-r", dl_fa, "-o",
                                os.path.join(d, "dl_p2.dfa")])
                if not sub.get("ok"):
                    return _fail("realistic_cache_delta_submit")
                res = c.result(sub["job_id"], timeout=600)
            if not res.get("ok") or res.get("rc") != 0:
                sys.stderr.write(str(res)[:1000])
                return _fail("realistic_cache_delta_pop_job")
            dl_o2 = os.path.join(d, "dl_t2.dfa")
            dl_s2 = os.path.join(d, "dl_t2.stats")
            t0 = time.perf_counter()
            with ServiceClient(svc_dl) as c:
                sub = c.submit([dl_full, "-r", dl_fa, "-o", dl_o2,
                                f"--stats={dl_s2}"])
                if not sub.get("ok"):
                    return _fail("realistic_cache_delta_submit")
                res = c.result(sub["job_id"], timeout=600)
            dl_walls["daemon"] = time.perf_counter() - t0
            if not res.get("ok") or res.get("rc") != 0:
                sys.stderr.write(str(res)[:1000])
                return _fail("realistic_cache_delta_daemon")
            dl_check("daemon", dl_o2, dl_s2)
            with ServiceClient(svc_dl) as c:
                c.drain()
            sp_dl.wait(timeout=120)
        except Exception as e:
            sys.stderr.write(f"delta daemon leg: {e}\n")
            return _fail("realistic_cache_delta_daemon")
        finally:
            if sp_dl.poll() is None:
                sp_dl.kill()
                sp_dl.wait()

        # tier 3: router edge — one cache-owning member behind a
        # `route` front door; the router's cache-affinity places the
        # grown job on the member holding the family
        dl_dir3 = os.path.join(d, "dlc3")
        msock_dl = os.path.join(d, "mdl.sock")
        rsock_dl = os.path.join(d, "rdl.sock")
        mp_dl = subprocess.Popen(
            cmd + ["serve", f"--socket={msock_dl}", "--max-queue=8",
                   f"--result-cache={dl_dir3}"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE)
        rp_dl = None
        try:
            if not wait_for_socket(msock_dl, 120):
                return _fail("realistic_cache_delta_member_up")
            rp_dl = subprocess.Popen(
                cmd + ["route", f"--backends={msock_dl}",
                       f"--socket={rsock_dl}",
                       "--poll-interval=0.2"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            if not wait_for_socket(rsock_dl, 120):
                return _fail("realistic_cache_delta_router_up")
            with ServiceClient(rsock_dl) as c:
                sub = c.submit([dl_pre, "-r", dl_fa, "-o",
                                os.path.join(d, "dl_p3.dfa")])
                if not sub.get("ok"):
                    return _fail("realistic_cache_delta_submit")
                res = c.result(sub["job_id"], timeout=600)
            if not res.get("ok") or res.get("rc") != 0:
                sys.stderr.write(str(res)[:1000])
                return _fail("realistic_cache_delta_pop_job")
            dl_o3 = os.path.join(d, "dl_t3.dfa")
            dl_s3 = os.path.join(d, "dl_t3.stats")
            t0 = time.perf_counter()
            with ServiceClient(rsock_dl) as c:
                sub = c.submit([dl_full, "-r", dl_fa, "-o", dl_o3,
                                f"--stats={dl_s3}"])
                if not sub.get("ok"):
                    return _fail("realistic_cache_delta_submit")
                res = c.result(sub["job_id"], timeout=600)
            dl_walls["router"] = time.perf_counter() - t0
            if not res.get("ok") or res.get("rc") != 0:
                sys.stderr.write(str(res)[:1000])
                return _fail("realistic_cache_delta_router")
            dl_check("router", dl_o3, dl_s3)
        except Exception as e:
            sys.stderr.write(f"delta router leg: {e}\n")
            return _fail("realistic_cache_delta_router")
        finally:
            if rp_dl is not None and rp_dl.poll() is None:
                rp_dl.terminate()
                rp_dl.wait()
            if mp_dl.poll() is None:
                mp_dl.terminate()
                mp_dl.wait()
        sys.stderr.write(
            "delta leg: cold=%s walls=%s\n"
            % ([round(w, 2) for w in dl_cold_walls],
               {k: round(v, 2) for k, v in dl_walls.items()}))
        dl_ratio = max(w / dl_cold for w in dl_walls.values())
        _emit("realistic_cache_delta_ratio", dl_ratio, "x",
              1.0 if dl_ratio <= 0.3 else 0.0, cpu_metric=True)
        _emit("realistic_cache_delta_parity", 1 if dl_ok else 0,
              "bool", 1.0 if dl_ok else 0.0, cpu_metric=True)

        # --- device-lease lanes (ISSUE 8 tentpole): a 2-lane daemon
        # (--max-concurrent=2) must run jobs CONCURRENTLY on disjoint
        # lanes with byte parity for every job, and concurrency must
        # not LOSE throughput vs the same jobs serialized through the
        # same warm daemon — the floor the cpu-like twin can certify
        # (the K*0.8x per-chip scale-UP on a real mesh is
        # qa/chip_burst.py --multichip's to measure).
        import threading

        svc2 = os.path.join(d, "svc2.sock")
        sp2 = subprocess.Popen(
            cmd + ["serve", f"--socket={svc2}", "--max-queue=8",
                   "--max-concurrent=2"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        lanes_rc = None
        lane_jobs: list[int] = []
        errs: list[str] = []

        def lane_job(tag):
            try:
                with ServiceClient(svc2) as c:
                    sub = c.submit(args(tag, []))
                    if not sub.get("ok"):
                        raise RuntimeError(f"submit: {sub}")
                    res = c.result(sub["job_id"], timeout=600)
                if not res.get("ok") or res.get("rc") != 0:
                    raise RuntimeError(str(res)[:300])
            except Exception as e:
                errs.append(f"{tag}: {e}")

        try:
            if not wait_for_socket(svc2, 120):
                return _fail("realistic_serve_lanes_up")
            lane_job("lwarm")     # shared warmup: probe + native lib
            if errs:
                sys.stderr.write("\n".join(errs)[:1000])
                return _fail("realistic_serve_lanes_warm")
            t0 = time.perf_counter()
            ts = [threading.Thread(target=lane_job, args=(f"lc{k}",))
                  for k in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            conc_wall = time.perf_counter() - t0
            with ServiceClient(svc2) as c:
                lane_jobs = [r["jobs_run"]
                             for r in c.stats()["stats"]["lanes"]]
            t0 = time.perf_counter()
            for k in range(4):
                lane_job(f"ls{k}")
            seq_wall = time.perf_counter() - t0
            if errs:
                sys.stderr.write("\n".join(errs)[:1000])
                return _fail("realistic_serve_lanes_job")
            for k in range(4):
                if (readset(f"lc{k}") != parity_body
                        or readset(f"ls{k}") != parity_body):
                    return _fail("realistic_serve_lanes_parity")
            with ServiceClient(svc2) as c:
                c.drain()
            lanes_rc = sp2.wait(timeout=120)
        except Exception as e:
            sys.stderr.write(f"lanes leg: {e}\n")
            return _fail("realistic_serve_lanes")
        finally:
            if sp2.poll() is None:
                sp2.kill()
                sp2.wait()
        jps1 = 4 / seq_wall
        jps2 = 4 / conc_wall
        # the bool leg gates only deterministic facts: byte parity
        # (checked above), both lanes actually scheduled jobs, clean
        # drain rc.  The jps2-vs-jps1 throughput floor is a TIMING
        # claim — a loaded box can miss it with every byte correct —
        # so it lives in the gated rate legs below (bench_gate fails
        # a >25% rate drop), not folded into a "parity" bool.
        lanes_ok = (lanes_rc == 75 and len(lane_jobs) == 2
                    and min(lane_jobs) >= 1)
        _emit("realistic_serve_jobs_per_s_1lane", jps1, "jobs/s",
              1.0, cpu_metric=True)
        _emit("realistic_serve_jobs_per_s_2lane", jps2, "jobs/s",
              jps2 / jps1, cpu_metric=True)
        _emit("realistic_serve_lanes_parity", 1 if lanes_ok else 0,
              "bool", 1.0 if lanes_ok else 0.0, cpu_metric=True)

        # --- crash recovery (ISSUE 9 tentpole): kill -9 a live serve
        # daemon mid-job (after its first durable ckpt) with a second
        # job still queued; a fresh daemon on the same socket replays
        # the journal — the interrupted job resumes from its ckpt, the
        # queued one re-runs whole — and both reports end
        # byte-identical to the never-crashed arm (the resumed job's
        # -s summary excluded by the documented --resume contract).
        svc3 = os.path.join(d, "svc3.sock")
        sp3 = subprocess.Popen(
            cmd + ["serve", f"--socket={svc3}", "--max-queue=8"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        sp3b = None
        crash_ok = False
        try:
            if not wait_for_socket(svc3, 120):
                return _fail("realistic_serve_crash_up")
            slow = ("--inject-faults=seed=1,rate=1,kinds=hang,"
                    "hang_s=0.25")
            with ServiceClient(svc3) as c:
                ja = c.submit(args("cra", ["--batch=16", slow]))
                jb = c.submit(args("crb", []))
                if not (ja.get("ok") and jb.get("ok")):
                    return _fail("realistic_serve_crash_submit")
                ck = os.path.join(d, "cra.dfa.ckpt")
                deadline = time.monotonic() + 120
                mid = False
                while time.monotonic() < deadline:
                    st = c.status(ja["job_id"])["job"]["state"]
                    if st == "running" and os.path.exists(ck):
                        mid = True
                        break
                    if st not in ("queued", "running"):
                        break
                    time.sleep(0.02)
            if not mid:
                return _fail("realistic_serve_crash_window")
            sp3.kill()              # SIGKILL: no drain, no cleanup
            sp3.wait(timeout=60)
            sp3b = subprocess.Popen(
                cmd + ["serve", f"--socket={svc3}", "--max-queue=8"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            if not wait_for_socket(svc3, 120):
                return _fail("realistic_serve_crash_restart")
            with ServiceClient(svc3) as c:
                ra = c.result(ja["job_id"], timeout=600)
                rb = c.result(jb["job_id"], timeout=600)
                svc_st = c.stats()["stats"]
                c.drain()
            crash_rc = sp3b.wait(timeout=120)
            crash_ok = (
                ra.get("rc") == 0 and rb.get("rc") == 0
                and svc_st["journal"]["replays"] == 1
                and read_nosum("cra") == read_nosum("py")
                and readset("crb") == parity_body
                and crash_rc == 75
                and not os.path.exists(svc3 + ".journal"))
        except Exception as e:
            sys.stderr.write(f"crash-recovery leg: {e}\n")
            return _fail("realistic_serve_crash_recovery")
        finally:
            for p in (sp3, sp3b):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_serve_crash_recovery_parity",
              1 if crash_ok else 0, "bool",
              1.0 if crash_ok else 0.0, cpu_metric=True)

        # --- fair-share admission (ISSUE 9 tentpole): a LIGHT client
        # submitting one job while a HEAVY co-submitter holds a deep
        # backlog must be round-robined in after at most ~one running
        # job, not serialized behind the whole backlog.  The leg
        # reports the light client's p50 daemon-side queue wait
        # (submit->start, ms, lower-is-better in qa/bench_gate.py);
        # under the old global FIFO this is the heavy backlog's whole
        # drain time.
        svc4 = os.path.join(d, "svc4.sock")
        sp4 = subprocess.Popen(
            cmd + ["serve", f"--socket={svc4}", "--max-queue=16"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        light_waits = []
        heavy_walls = []
        try:
            if not wait_for_socket(svc4, 120):
                return _fail("realistic_serve_fairshare_up")
            with ServiceClient(svc4) as c:
                heavy = []
                for k in range(8):
                    s = c.submit(args(f"fh{k}", []), client="heavy")
                    if not s.get("ok"):
                        return _fail("realistic_serve_fairshare_submit")
                    heavy.append(s["job_id"])
                for k in range(3):
                    s = c.submit(args(f"fl{k}", []), client="light")
                    if not s.get("ok"):
                        return _fail("realistic_serve_fairshare_light")
                    r = c.result(s["job_id"], timeout=600)
                    if not r.get("ok") or r.get("rc") != 0:
                        return _fail("realistic_serve_fairshare_job")
                    job = r["job"]
                    light_waits.append(
                        (job["started_s"] - job["submitted_s"]) * 1e3)
                for jid in heavy:
                    r = c.result(jid, timeout=600)
                    if not r.get("ok") or r.get("rc") != 0:
                        return _fail("realistic_serve_fairshare_heavy")
                    job = r["job"]
                    heavy_walls.append(job["finished_s"]
                                       - job["started_s"])
                c.drain()
            sp4.wait(timeout=120)
            if (readset("fl0") != parity_body
                    or readset("fh0") != parity_body):
                return _fail("realistic_serve_fairshare_parity")
        except Exception as e:
            sys.stderr.write(f"fair-share leg: {e}\n")
            return _fail("realistic_serve_fairshare")
        finally:
            if sp4.poll() is None:
                sp4.kill()
                sp4.wait()
        light_p50 = sorted(light_waits)[len(light_waits) // 2]
        # the acceptance flag: the light client waited at most ~2
        # heavy job walls (the running job + one DRR rotation), far
        # under the ~8-wall FIFO backlog drain
        fair_flag = light_p50 <= 2.5 * max(heavy_walls) * 1e3
        _emit("realistic_serve_fairshare_p50_light_ms", light_p50,
              "ms", 1.0 if fair_flag else 0.0, cpu_metric=True)

        # --- fleet federation (ISSUE 13 tentpole): THREE serve
        # daemons behind one `route` router.  One fleet serves three
        # legs in order: (1) an UNCRASHED arm (byte parity of routed
        # jobs vs the direct run), (2) fleet-wide fairness — a light
        # client's p50 queue wait under a heavy 8-job co-submitter
        # routed across all three members (ms, lower-is-better), and
        # (3) THE kill-one-of-three drill: SIGKILL the member running
        # a mid-job job (after its first durable ckpt) → the router
        # reads its journal, resumes the job on a sibling as a
        # --resume continuation, and every report lands byte-identical
        # to the uncrashed arm with the client's trace_id intact
        # (gated bool leg).
        fsocks = [os.path.join(d, f"flt{k}.sock") for k in range(3)]
        fprocs = [subprocess.Popen(
            cmd + ["serve", f"--socket={s}", "--max-queue=16"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE) for s in fsocks]
        frouter = None
        rsock = os.path.join(d, "fleet.sock")
        flt_fair: list[float] = []
        flt_heavy_walls: list[float] = []
        flt_ok = False
        try:
            for s in fsocks:
                if not wait_for_socket(s, 120):
                    return _fail("realistic_fleet_up")
            frouter = subprocess.Popen(
                cmd + ["route", "--backends=" + ",".join(fsocks),
                       f"--socket={rsock}", "--poll-interval=0.2"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            if not wait_for_socket(rsock, 120):
                return _fail("realistic_fleet_router_up")
            slow = ("--inject-faults=seed=1,rate=1,kinds=hang,"
                    "hang_s=0.25")
            with ServiceClient(rsock,
                               trace_id="bench-fleet") as c:
                # (1) uncrashed arm through the router
                for tag in ("fa0", "fb0"):
                    s0 = c.submit(args(tag, ["--batch=16"]))
                    if not s0.get("ok"):
                        return _fail("realistic_fleet_submit")
                    r0 = c.result(s0["job_id"], timeout=600)
                    if r0.get("rc") != 0:
                        return _fail("realistic_fleet_job")
                if readset("fa0") != parity_body \
                        or readset("fb0") != parity_body:
                    return _fail("realistic_fleet_parity")
                # (2) fleet-wide fair share across 3 members: a deep
                # heavy backlog saturating EVERY member's worker, then
                # the light client's jobs submitted while it stands —
                # DRR on each member must rotate light in after at
                # most ~one running job, so the light p50 is ~one
                # heavy wall, not the backlog's drain time (the
                # whole-fleet twin of the single-daemon leg above)
                heavy = []
                for k in range(12):
                    s0 = c.submit(args(f"ffh{k}", []),
                                  client="fleet-heavy")
                    if not s0.get("ok"):
                        return _fail("realistic_fleet_fair_submit")
                    heavy.append(s0["job_id"])
                light = []
                for k in range(3):
                    s0 = c.submit(args(f"ffl{k}", []),
                                  client="fleet-light")
                    if not s0.get("ok"):
                        return _fail("realistic_fleet_fair_light")
                    light.append(s0["job_id"])
                for jid in light:
                    r0 = c.result(jid, timeout=600)
                    if r0.get("rc") != 0:
                        return _fail("realistic_fleet_fair_job")
                    job = r0["job"]
                    flt_fair.append(
                        (job["started_s"] - job["submitted_s"]) * 1e3)
                for jid in heavy:
                    r0 = c.result(jid, timeout=600)
                    if r0.get("rc") != 0:
                        return _fail("realistic_fleet_fair_heavy")
                    job = r0["job"]
                    flt_heavy_walls.append(job["finished_s"]
                                           - job["started_s"])
                # (3) the kill drill: slow job mid-run + a queued one
                ja = c.submit(args("fa1", ["--batch=16", slow]))
                jb = c.submit(args("fb1", []))
                if not (ja.get("ok") and jb.get("ok")):
                    return _fail("realistic_fleet_crash_submit")
                ck = os.path.join(d, "fa1.dfa.ckpt")
                deadline = time.monotonic() + 120
                mid = False
                while time.monotonic() < deadline:
                    st = c.status(ja["job_id"])["job"]["state"]
                    if st == "running" and os.path.exists(ck):
                        mid = True
                        break
                    if st not in ("queued", "running"):
                        break
                    time.sleep(0.02)
                if not mid:
                    return _fail("realistic_fleet_crash_window")
                victim = ja["member"]
                vi = [i for i, s in enumerate(fsocks)
                      if os.path.basename(s) == victim][0]
                fprocs[vi].kill()       # SIGKILL: no drain
                fprocs[vi].wait(timeout=60)
                ra = c.result(ja["job_id"], timeout=600)
                rb = c.result(jb["job_id"], timeout=600)
                flt_st = c.stats()["stats"]
                c.drain()
            frc = frouter.wait(timeout=120)
            # the dead member's journal was consumed and set aside
            # (a restart of it must not double-run recovered work)
            flt_ok = (
                ra.get("rc") == 0 and rb.get("rc") == 0
                and ra["job"]["trace_id"] == "bench-fleet"
                and ra["job"].get("member") not in (None, victim)
                and ra["job"].get("failovers") == 1
                and flt_st["fleet"]["failovers"] == 1
                and flt_st["fleet"]["jobs_recovered"]["resumed"] == 1
                and read_nosum("fa1") == read_nosum("fa0")
                and readset("fb1") == readset("fb0")
                and os.path.exists(fsocks[vi]
                                   + ".journal.recovered")
                and frc == 0)
            for i, s in enumerate(fsocks):
                if i == vi:
                    continue
                with ServiceClient(s) as c:
                    c.drain()
                if fprocs[i].wait(timeout=120) != 75:
                    return _fail("realistic_fleet_member_drain")
        except Exception as e:
            sys.stderr.write(f"fleet leg: {e}\n")
            return _fail("realistic_fleet_failover")
        finally:
            for p in fprocs + ([frouter] if frouter else []):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_fleet_failover_parity",
              1 if flt_ok else 0, "bool",
              1.0 if flt_ok else 0.0, cpu_metric=True)
        flt_p50 = sorted(flt_fair)[len(flt_fair) // 2]
        flt_fair_flag = flt_p50 <= 2.5 * max(flt_heavy_walls) * 1e3
        _emit("realistic_fleet_fairshare_p50_light_ms", flt_p50,
              "ms", 1.0 if flt_fair_flag else 0.0, cpu_metric=True)

        # --- streaming ingestion (ISSUE 10 tentpole): the SAME
        # corpus record-at-a-time over the service socket.  Gates
        # byte parity against the one-shot outputs and measures the
        # record-appended -> report-bytes-emitted p50 under --batch=1
        # (every record is its own flush; the host pipeline holds two
        # batches in flight, so after a 3-record prime each appended
        # record emits exactly one older batch's bytes — the steady-
        # state per-record serving latency of the minimap2-pipe
        # shape, docs/STREAMING.md).
        svc5 = os.path.join(d, "svc5.sock")
        sp5 = subprocess.Popen(
            cmd + ["serve", f"--socket={svc5}", "--max-queue=8"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        lat_ms: list[float] = []
        try:
            if not wait_for_socket(svc5, 120):
                return _fail("realistic_stream_up")
            strm_out = outset("strm")
            recs = [l + "\n" for l in lines]

            def _rsize():
                try:
                    return os.path.getsize(strm_out[0])
                except OSError:
                    return 0

            with ServiceClient(svc5) as c:
                so = c.stream_open(
                    ["-r", fa, "-o", strm_out[0], "-s", strm_out[1],
                     "-w", strm_out[2], f"--cons={strm_out[3]}",
                     "--batch=1"])
                if not so.get("ok"):
                    sys.stderr.write(str(so)[:1000])
                    return _fail("realistic_stream_open")
                jid = so["job_id"]
                for r in recs[:3]:       # prime the 2-deep pipeline
                    c.stream_data(jid, r)
                deadline = time.monotonic() + 120
                while _rsize() == 0:
                    if time.monotonic() > deadline:
                        return _fail("realistic_stream_first_byte")
                    time.sleep(0.002)
                for r in recs[3:43]:
                    base = _rsize()
                    t0 = time.perf_counter()
                    rr = c.stream_data(jid, r)
                    if not rr.get("ok"):
                        sys.stderr.write(str(rr)[:1000])
                        return _fail("realistic_stream_feed")
                    deadline = time.monotonic() + 60
                    while _rsize() <= base:
                        if time.monotonic() > deadline:
                            return _fail("realistic_stream_latency")
                        time.sleep(0.001)
                    lat_ms.append((time.perf_counter() - t0) * 1e3)
                for r in recs[43:]:
                    rr = c.stream_data(jid, r)
                    if not rr.get("ok"):
                        sys.stderr.write(str(rr)[:1000])
                        return _fail("realistic_stream_feed")
                c.stream_end(jid)
                res = c.result(jid, timeout=600)
                c.drain()
            strm_rc = sp5.wait(timeout=120)
            if not res.get("ok") or res.get("rc") != 0:
                sys.stderr.write(str(res)[:1000])
                return _fail("realistic_stream_job")
            if readset("strm") != parity_body:
                return _fail("realistic_stream_parity")
            if strm_rc != 75:
                return _fail("realistic_stream_drain")
        except Exception as e:
            sys.stderr.write(f"stream leg: {e}\n")
            return _fail("realistic_stream")
        finally:
            if sp5.poll() is None:
                sp5.kill()
                sp5.wait()
        _emit("realistic_stream_batch_latency_ms",
              sorted(lat_ms)[len(lat_ms) // 2], "ms", 1.0,
              cpu_metric=True)

        # --- many-to-many (ISSUE 10 tentpole): BASELINE config 3's
        # shape in miniature — Q CDS queries scored against T
        # assembly targets through ONE --many2many session vs Q
        # sequential single-CDS jobs (each paying its own interpreter
        # + jax + session).  Per-CDS section/summary bytes are parity
        # gated (concatenated singles == multi); the emitted ratio is
        # the amortization multiplier (unit "x", lower is better,
        # gated by qa/bench_gate.py like the other ratios).
        import numpy as _np
        m2m = os.path.join(d, "m2m")
        os.makedirs(m2m, exist_ok=True)
        rng = _np.random.default_rng(19)

        def _seq(n):
            return "".join("ACGT"[i]
                           for i in rng.integers(0, 4, n))

        m2m_qs = [(f"cds{k}", _seq(300 + 40 * (k % 3)))
                  for k in range(4)]
        m2m_ts = [(f"asm{k}", _seq(500 + 31 * k)) for k in range(24)]
        qfa_all = os.path.join(m2m, "cds_multi.fa")
        tfa = os.path.join(m2m, "targets.fa")
        with open(qfa_all, "w") as f:
            f.write("".join(f">{n}\n{s}\n" for n, s in m2m_qs))
        with open(tfa, "w") as f:
            f.write("".join(f">{n}\n{s}\n" for n, s in m2m_ts))
        multi_out = os.path.join(m2m, "multi.tsv")
        multi_sum = os.path.join(m2m, "multi.sum")
        t0 = time.perf_counter()
        r = subprocess.run(
            cmd + ["--many2many", tfa, "-r", qfa_all,
                   "-o", multi_out, "-s", multi_sum],
            env=env, capture_output=True)
        multi_wall = time.perf_counter() - t0
        if r.returncode != 0:
            sys.stderr.write(r.stderr.decode()[:1000])
            return _fail("realistic_many2many")
        seq_wall = 0.0
        seq_body = b""
        seq_sum = b""
        for name, s in m2m_qs:
            q1 = os.path.join(m2m, f"{name}.fa")
            with open(q1, "w") as f:
                f.write(f">{name}\n{s}\n")
            o1 = os.path.join(m2m, f"{name}.tsv")
            s1 = os.path.join(m2m, f"{name}.sum")
            t0 = time.perf_counter()
            r = subprocess.run(
                cmd + ["--many2many", tfa, "-r", q1,
                       "-o", o1, "-s", s1],
                env=env, capture_output=True)
            seq_wall += time.perf_counter() - t0
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:1000])
                return _fail("realistic_many2many_seq")
            seq_body += open(o1, "rb").read()
            seq_sum += open(s1, "rb").read()
        if (seq_body != open(multi_out, "rb").read()
                or seq_sum != open(multi_sum, "rb").read()):
            return _fail("realistic_many2many_parity")
        m2m_ratio = multi_wall / seq_wall
        # vs_baseline flags the aspirational "one session costs at
        # most half of N sessions" target, like the pycli ratio's 1.5x
        _emit("realistic_many2many_vs_sequential_ratio", m2m_ratio,
              "x", 1.0 if m2m_ratio <= 0.5 else 0.0, cpu_metric=True)

        # --- host engine A/B: 1k-alignment report+summary corpus ----
        qseq1k, lines1k = make_corpus(n_aln=1000)
        fa1k = os.path.join(d, "cds1k.fa")
        paf1k = os.path.join(d, "in1k.paf")
        with open(fa1k, "w") as f:
            f.write(f">cds1\n{qseq1k}\n")
        with open(paf1k, "w") as f:
            f.write("".join(l + "\n" for l in lines1k))

        def host_once(tag, columnar):
            env_h = dict(env, PWASM_HOST_COLUMNAR="1" if columnar
                         else "0")
            o = [os.path.join(d, f"{tag}.dfa"),
                 os.path.join(d, f"{tag}.sum")]
            t0 = time.perf_counter()
            r = subprocess.run(
                cmd + [paf1k, "-r", fa1k, "-o", o[0], "-s", o[1]],
                env=env_h, capture_output=True)
            wall = time.perf_counter() - t0
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:1000])
                return None, None
            return wall, b"".join(open(p, "rb").read() for p in o)
        # interleave the engines so shared-box load drift biases both
        # arms equally
        col_walls, sca_walls = [], []
        col_body = sca_body = None
        for _ in range(4):
            w, col_body = host_once("h1kcol", True)
            if w is None:
                return _fail("realistic_host_1k")
            col_walls.append(w)
            w, sca_body = host_once("h1ksca", False)
            if w is None:
                return _fail("realistic_host_1k")
            sca_walls.append(w)
        if col_body != sca_body:
            return _fail("realistic_host_engine_parity")
        _emit("realistic_host_report_1k_s", min(col_walls), "s",
              min(sca_walls) / min(col_walls), cpu_metric=True)

        # --- observability overhead (ISSUE 11): the same 1k-alignment
        # report with the FULL observability surface on (trace + event
        # log + stats + metrics textfile) vs all off.  Bytes must stay
        # identical (the byte-neutrality contract at realistic scale)
        # and the wall ratio is gated <= 1.10 — observability that
        # costs more than 10% would get turned off exactly when it is
        # needed.  Unit "x" = lower-is-better in qa/bench_gate.py.
        def host_obs_once(tag, obs_on):
            o = [os.path.join(d, f"{tag}.dfa"),
                 os.path.join(d, f"{tag}.sum")]
            extra = []
            if obs_on:
                extra = [
                    f"--trace-json={os.path.join(d, tag + '.trace')}",
                    f"--log-json={os.path.join(d, tag + '.ndjson')}",
                    f"--stats={os.path.join(d, tag + '.json')}",
                    "--metrics-textfile="
                    + os.path.join(d, tag + ".prom")]
            t0 = time.perf_counter()
            r = subprocess.run(
                cmd + [paf1k, "-r", fa1k, "-o", o[0], "-s", o[1]]
                + extra, env=env, capture_output=True)
            wall = time.perf_counter() - t0
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:1000])
                return None, None
            return wall, b"".join(open(p, "rb").read() for p in o)
        # interleaved arms, same rationale as the engine A/B above
        obs_walls, plain_walls = [], []
        obs_body = plain_body = None
        for _ in range(4):
            w, obs_body = host_obs_once("h1kobs", True)
            if w is None:
                return _fail("realistic_obs_overhead")
            obs_walls.append(w)
            w, plain_body = host_obs_once("h1kplain", False)
            if w is None:
                return _fail("realistic_obs_overhead")
            plain_walls.append(w)
        if obs_body != plain_body:
            return _fail("realistic_obs_parity")
        obs_ratio = min(obs_walls) / min(plain_walls)
        obs_ok = obs_ratio <= 1.10
        _emit("realistic_obs_overhead_ratio", obs_ratio, "x",
              1.0 if obs_ok else 0.0, cpu_metric=True)
        # the <= 1.10 ceiling as a BOOL leg: unit "x" only gates
        # against the committed trajectory, so without this a first
        # stamp at 1.4x would become the accepted baseline — the bool
        # flips 1 -> 0 past the ceiling and bench_gate fails the flip
        _emit("realistic_obs_overhead_ok", 1 if obs_ok else 0,
              "bool", 1.0 if obs_ok else 0.0, cpu_metric=True)

        # --- self-monitoring overhead (ISSUE 14): the SAME 3-job
        # serve flow through a daemon with the canary + SLO engine ON
        # (--canary-interval + default rules) vs OFF (--slo-rules=off,
        # no canary).  Bytes must stay identical (self-monitoring is
        # observability, byte-invisible to real traffic) and the
        # submit->result wall ratio is gated <= 1.10 like the PR 11
        # obs-overhead leg — interleaved arms + min-of-mins for the
        # same noise-robustness reason.  --lanes=2 on BOTH arms so
        # the canary probes the idle lane instead of queueing behind
        # the jobs — the designed free-lane behavior; --warmup=cpu
        # keeps the probe corpus on the deterministic host path in
        # this backend-agnostic leg.
        def selfmon_arm(tag, selfmon_on):
            sockp = os.path.join(d, f"{tag}.sock")
            flags = ["serve", f"--socket={sockp}", "--max-queue=8",
                     "--lanes=2", "--warmup=cpu"]
            flags += (["--canary-interval=1.0"] if selfmon_on
                      else ["--slo-rules=off"])
            proc = subprocess.Popen(
                cmd + flags, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            walls, body = [], b""
            try:
                if not wait_for_socket(sockp, 120):
                    return None, None
                for j in (1, 2, 3):
                    t0 = time.perf_counter()
                    with ServiceClient(sockp) as c:
                        sub = c.submit(args(f"{tag}{j}", []))
                        if not sub.get("ok"):
                            return None, None
                        res = c.result(sub["job_id"], timeout=600)
                    walls.append(time.perf_counter() - t0)
                    if not res.get("ok") or res.get("rc") != 0:
                        sys.stderr.write(str(res)[:1000])
                        return None, None
                    body += readset(f"{tag}{j}")
                if selfmon_on:
                    # the engine + canary must actually be LIVE in
                    # the measured arm, or the ratio gates nothing
                    # (bounded wait: a fast box can finish the jobs
                    # before the first 0.5s canary tick)
                    h = {}
                    live_by = time.monotonic() + 30
                    while time.monotonic() < live_by:
                        with ServiceClient(sockp) as c:
                            h = c.health().get("health") or {}
                        if h.get("rules", 0) >= 1 \
                                and (h.get("canary") or {}).get(
                                    "runs", 0) >= 1:
                            break
                        time.sleep(0.1)
                    else:
                        sys.stderr.write(
                            f"selfmon arm not live: {h}\n")
                        return None, None
                with ServiceClient(sockp) as c:
                    c.drain()
                if proc.wait(timeout=120) != 75:
                    return None, None
            except Exception as e:
                sys.stderr.write(f"selfmon arm {tag}: {e}\n")
                return None, None
            finally:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
            return min(walls), body
        mon_walls, off_walls = [], []
        mon_body = off_body = None
        for _round in range(2):
            mon_wall, mon_body = selfmon_arm("svcmon", True)
            if mon_wall is None:
                return _fail("realistic_selfmon_overhead")
            mon_walls.append(mon_wall)
            off_wall, off_body = selfmon_arm("svcoff", False)
            if off_wall is None:
                return _fail("realistic_selfmon_overhead")
            off_walls.append(off_wall)
        if mon_body != off_body:
            return _fail("realistic_selfmon_parity")
        selfmon_ratio = min(mon_walls) / min(off_walls)
        selfmon_ok = selfmon_ratio <= 1.10
        _emit("realistic_selfmon_overhead_ratio", selfmon_ratio, "x",
              1.0 if selfmon_ok else 0.0, cpu_metric=True)
        _emit("realistic_selfmon_overhead_ok",
              1 if selfmon_ok else 0, "bool",
              1.0 if selfmon_ok else 0.0, cpu_metric=True)

        # --- canary detection latency (ISSUE 14): a scripted outage
        # on the canary's own serving path (PWASM_CANARY_FAULTS:
        # probe runs 2-3 carry --inject-faults=preempt=1, so they
        # exit 75 = a failed probe) must surface as a FIRING rule in
        # `health` within two canary intervals of the last healthy
        # probe, and resolve once the window passes.  This measures
        # the member-level detection wall; the 3-member routed drill
        # is gated as a test (tests/test_slo.py).
        det_interval = 1.0
        det_sock = os.path.join(d, "svcdet.sock")
        det_env = dict(env, PWASM_CANARY_FAULTS="2-3:preempt=1")
        # --warmup=tpu: the canary probes the SUPERVISED device path
        # (where the scripted preempt=1 clock ticks — a host-path
        # probe would never see the injected outage) with the pow2
        # compiles prepaid, so probe walls stay far under the interval
        det_proc = subprocess.Popen(
            cmd + ["serve", f"--socket={det_sock}", "--warmup=tpu",
                   f"--canary-interval={det_interval}"],
            env=det_env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE)
        detect_s = resolved = None
        try:
            if not wait_for_socket(det_sock, 120):
                return _fail("realistic_canary_up")

            def det_health():
                with ServiceClient(det_sock) as c:
                    return c.health().get("health") or {}

            deadline = time.monotonic() + 120
            t_ok = None
            while time.monotonic() < deadline:
                h = det_health()
                can = h.get("canary") or {}
                if t_ok is None and can.get("runs", 0) >= 1 \
                        and can.get("last_ok"):
                    t_ok = time.monotonic()   # outage window opens
                    #   with the NEXT probe — the detection clock
                if t_ok is not None and h.get("verdict") != "ok" \
                        and "canary_failing" in [
                            f.get("rule") for f in
                            (h.get("firing") or [])]:
                    detect_s = time.monotonic() - t_ok
                    break
                time.sleep(0.05)
            if detect_s is None:
                return _fail("realistic_canary_detect")
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if det_health().get("verdict") == "ok":
                    resolved = True
                    break
                time.sleep(0.05)
            with ServiceClient(det_sock) as c:
                c.drain()
            det_proc.wait(timeout=120)
        except Exception as e:
            sys.stderr.write(f"canary detect leg: {e}\n")
            return _fail("realistic_canary_detect")
        finally:
            if det_proc.poll() is None:
                det_proc.kill()
                det_proc.wait()
        det_ok = bool(resolved) and detect_s <= 2 * det_interval
        _emit("realistic_canary_detect_s", detect_s, "s",
              1.0 if det_ok else 0.0, cpu_metric=True)

        # --- router HA failover gap (ISSUE 16 tentpole): SIGKILL the
        # PRIMARY router while a job is mid-run on a member, with a
        # warm standby (`route --standby-of`) tailing its write-ahead
        # journal.  The standby must take over the SAME socket, replay
        # the routed-job table, and serve the pre-crash client's
        # `result` — rc 0, trace_id intact, byte-identical outputs.
        # The metric is the submit-surface outage: primary SIGKILL ->
        # first successful ping on the same socket (ms, lower-better).
        dslow = ("--inject-faults=seed=1,rate=1,kinds=hang,"
                 "hang_s=0.5")    # device-path hangs: ~12-16 s walls
        hsocks = [os.path.join(d, f"ha{k}.sock") for k in range(2)]
        hprocs = [subprocess.Popen(
            cmd + ["serve", f"--socket={s}", "--max-queue=16"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE) for s in hsocks]
        hrsock = os.path.join(d, "ha.sock")
        hprimary = hstandby = None
        gap_ms = None
        ha_ok = False
        try:
            for s in hsocks:
                if not wait_for_socket(s, 120):
                    return _fail("realistic_ha_member_up")
            hprimary = subprocess.Popen(
                cmd + ["route", "--backends=" + ",".join(hsocks),
                       f"--socket={hrsock}", "--poll-interval=0.2"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            if not wait_for_socket(hrsock, 120):
                return _fail("realistic_ha_router_up")
            hstandby = subprocess.Popen(
                cmd + ["route", f"--standby-of={hrsock}",
                       "--poll-interval=0.2"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            with ServiceClient(hrsock, trace_id="bench-ha") as c:
                ja = c.submit(args("haj", ["--device=tpu",
                                           "--batch=8", dslow]))
                if not ja.get("ok"):
                    return _fail("realistic_ha_submit")
                ck = os.path.join(d, "haj.dfa.ckpt")
                deadline = time.monotonic() + 120
                mid = False
                while time.monotonic() < deadline:
                    st = c.status(ja["job_id"])["job"]["state"]
                    if st == "running" and os.path.exists(ck):
                        mid = True
                        break
                    if st not in ("queued", "running"):
                        break
                    time.sleep(0.02)
            if not mid:
                return _fail("realistic_ha_crash_window")
            t_kill = time.monotonic()
            hprimary.kill()     # SIGKILL: the WAL is all that's left
            hprimary.wait(timeout=60)
            deadline = t_kill + 120
            up = False
            while time.monotonic() < deadline:
                try:
                    with ServiceClient(hrsock) as c:
                        if c.ping().get("ok"):
                            up = True
                            break
                except Exception:
                    pass
                time.sleep(0.02)
            if not up:
                return _fail("realistic_ha_takeover")
            gap_ms = (time.monotonic() - t_kill) * 1e3
            with ServiceClient(hrsock, trace_id="bench-ha") as c:
                ra = c.result(ja["job_id"], timeout=600)
                ha_st = c.stats()["stats"]
                c.drain()
            hrc = hstandby.wait(timeout=120)
            ha_ok = (ra.get("rc") == 0
                     and ra["job"]["trace_id"] == "bench-ha"
                     and readset("haj") == parity_body
                     and ha_st["ha"]["takeover"] is True
                     and ha_st["ha"]["epoch"] >= 2
                     and hrc == 0)
            for k, s in enumerate(hsocks):
                with ServiceClient(s) as c:
                    c.drain()
                if hprocs[k].wait(timeout=120) != 75:
                    return _fail("realistic_ha_member_drain")
        except Exception as e:
            sys.stderr.write(f"router HA leg: {e}\n")
            return _fail("realistic_router_failover")
        finally:
            for p in hprocs + [hprimary, hstandby]:
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_router_failover_gap_ms", gap_ms, "ms",
              1.0 if ha_ok else 0.0, cpu_metric=True)

        # --- SLO-driven member auto-scaling (ISSUE 16): a REAL
        # queue_pressure breach (two clients x 4 slow jobs against a
        # lone --max-queue=4 member: depth/quota up to 7/4, sustained
        # past the rule's for_s=5) must make the router's scaler spawn
        # a second `serve` member with --warmup=tpu +
        # --compile-cache-dir, and the FIRST job placed on that scaled
        # member must be served warm: probes == 0, warm_hits >= 1 in
        # its --stats backend block (bool, gated, + byte parity).
        scdir = os.path.join(d, "scale")
        os.makedirs(scdir, exist_ok=True)
        sccache = os.path.join(scdir, "ccache")
        scpolicy = os.path.join(scdir, "policy.json")
        with open(scpolicy, "w") as f:
            json.dump({"min_members": 1, "max_members": 2,
                       # cooldown/scale-down windows >> the leg: ONE
                       # deterministic spawn, retire only at drain
                       "cooldown_s": 600.0, "hysteresis": 2,
                       "scale_down_after_s": 600.0,
                       "rules": ["queue_pressure"],
                       "spawn": {
                           "socket_dir": scdir,
                           "args": ["--warmup=tpu",
                                    f"--compile-cache-dir={sccache}"],
                       }}, f)
        scm_sock = os.path.join(d, "scm0.sock")
        scm = subprocess.Popen(
            cmd + ["serve", f"--socket={scm_sock}", "--max-queue=4"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        scrouter = None
        scrsock = os.path.join(d, "scale.sock")
        warm_first = False
        try:
            if not wait_for_socket(scm_sock, 120):
                return _fail("realistic_scale_member_up")
            scrouter = subprocess.Popen(
                cmd + ["route", f"--backends={scm_sock}",
                       f"--socket={scrsock}",
                       f"--scale-policy={scpolicy}",
                       "--poll-interval=0.2"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            if not wait_for_socket(scrsock, 120):
                return _fail("realistic_scale_router_up")
            backlog = []
            with ServiceClient(scrsock, trace_id="bench-scale") as c:
                for k in range(8):
                    s0 = c.submit(args(f"scb{k}",
                                       ["--device=tpu", "--batch=16",
                                        dslow]),
                                  client=f"hv{k % 2}")
                    if not s0.get("ok"):
                        return _fail("realistic_scale_submit")
                    backlog.append(s0["job_id"])
                deadline = time.monotonic() + 180
                owned = 0
                while time.monotonic() < deadline:
                    sc = (c.stats()["stats"]["ha"].get("scaler")
                          or {})
                    owned = sc.get("owned", 0)
                    if owned >= 1:
                        break
                    time.sleep(0.1)
                if owned < 1:
                    return _fail("realistic_scale_spawn")
                # warm signal: the scaled member's --warmup=tpu pass
                # lands its pow2 compiles in the shared compile cache
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    if os.path.isdir(sccache) and os.listdir(sccache):
                        break
                    time.sleep(0.1)
                scstats = os.path.join(d, "scw.stats")
                sub = c.submit(args("scw", ["--device=tpu",
                                            f"--stats={scstats}"]))
                if not sub.get("ok"):
                    return _fail("realistic_scale_probe_submit")
                # the backlog still stands on member 0, so least-depth
                # placement must pick the fresh scaled member
                if not str(sub.get("member", "")
                           ).startswith("scaled-"):
                    return _fail("realistic_scale_placement")
                res = c.result(sub["job_id"], timeout=600)
                if res.get("rc") != 0:
                    return _fail("realistic_scale_probe_job")
                for jid in backlog:
                    if c.result(jid, timeout=600).get("rc") != 0:
                        return _fail("realistic_scale_backlog_job")
                c.drain()   # scaler.shutdown retires its member
            if scrouter.wait(timeout=120) != 0:
                return _fail("realistic_scale_router_drain")
            with open(scstats) as f:
                scbk = json.load(f).get("backend", {})
            warm_first = (scbk.get("probes", 1) == 0
                          and scbk.get("warm_hits", 0) >= 1
                          and readset("scw") == parity_body)
            with ServiceClient(scm_sock) as c:
                c.drain()
            if scm.wait(timeout=120) != 75:
                return _fail("realistic_scale_member_drain")
        except Exception as e:
            sys.stderr.write(f"scale-up leg: {e}\n")
            return _fail("realistic_fleet_scaleup")
        finally:
            for p in [scm, scrouter]:
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_fleet_scaleup_warm_first_job",
              1 if warm_first else 0, "bool",
              1.0 if warm_first else 0.0, cpu_metric=True)

        # --- gray-failure drill (ISSUE 18 tentpole): three members,
        # one behind qa/fleet_chaos's ChaosProxy — alive, polling
        # clean, but every byte 0.8 s slow.  The router must
        # quarantine it within ~3 poll rounds, place the chaos-window
        # jobs only on healthy members (byte parity intact),
        # honor --deadline-s truthfully mid-chaos, and probation-exit
        # the member once the fault lifts.  The emitted value is the
        # chaos-window job-wall p99; vs_baseline is the drill gate.
        qa_dir = os.path.join(repo, "qa")
        sys.path.insert(0, qa_dir)
        try:
            import fleet_chaos as chaos
        finally:
            try:
                sys.path.remove(qa_dir)
            except ValueError:
                pass
        from pwasm_tpu.fleet.transport import target_name
        gsocks = [os.path.join(d, f"gry{k}.sock") for k in range(3)]
        gprocs = [subprocess.Popen(
            cmd + ["serve", f"--socket={s}", "--max-queue=16"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE) for s in gsocks]
        grouter = None
        gproxy = None
        grsock = os.path.join(d, "gray.sock")
        gray_ok = False
        gray_p99 = 0.0
        gpoll, gdelay = 0.2, 0.8
        try:
            for s in gsocks:
                if not wait_for_socket(s, 120):
                    return _fail("realistic_fleet_gray_up")
            gproxy = chaos.ChaosProxy(gsocks[2])
            gaddr = gproxy.start()
            slow_name = target_name(gaddr)
            grouter = subprocess.Popen(
                cmd + ["route",
                       "--backends=" + ",".join(gsocks[:2] + [gaddr]),
                       f"--socket={grsock}",
                       f"--poll-interval={gpoll}", "--quarantine-x=3"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            if not wait_for_socket(grsock, 120):
                return _fail("realistic_fleet_gray_router_up")
            with ServiceClient(grsock, trace_id="bench-gray") as c:
                # a healthy-wall yardstick + EWMA convergence first
                t0 = time.perf_counter()
                s0 = c.submit(args("gw0", []))
                if not s0.get("ok") or c.result(
                        s0["job_id"], timeout=600).get("rc") != 0:
                    return _fail("realistic_fleet_gray_warm")
                healthy_wall = time.perf_counter() - t0
                time.sleep(6 * gpoll)
                gproxy.delay_s = gdelay       # the gray fault, armed
                d1 = chaos.gray_drill(grsock, slow_name,
                                      relieve=lambda: None,
                                      recover_timeout_s=0.0)
                walls: list[float] = []
                placed_ok = d1["quarantined"]
                dd_ok = False
                if d1["quarantined"]:
                    for k in range(6):
                        t0 = time.perf_counter()
                        s0 = c.submit(args(f"gc{k}", []))
                        if not s0.get("ok"):
                            return _fail("realistic_fleet_gray_submit")
                        placed_ok &= s0.get("member") != slow_name
                        if c.result(s0["job_id"],
                                    timeout=600).get("rc") != 0:
                            return _fail("realistic_fleet_gray_job")
                        walls.append(time.perf_counter() - t0)
                        if readset(f"gc{k}") != parity_body:
                            return _fail("realistic_fleet_gray_parity")
                    # deadlines stay truthful mid-chaos: a generous
                    # budget completes; an already-spent one is
                    # refused (or expires resumable), never silently
                    # run to completion
                    dd = chaos.deadline_drill(grsock, args("gdl", []),
                                              d, 120.0)
                    dt = chaos.deadline_drill(grsock, args("gdt", []),
                                              d, 0.001)
                    dd_ok = (dd["done"] and not dt["done"]
                             and (dt["refused"] or dt["expired"]))
                # fault lifted -> probation-exit (d1 already saw the
                # member quarantined, so d2's detect phase is instant)
                d2 = chaos.gray_drill(
                    grsock, slow_name,
                    relieve=lambda: setattr(gproxy, "delay_s", 0.0))
                c.drain()
            if grouter.wait(timeout=120) != 0:
                return _fail("realistic_fleet_gray_router_drain")
            for s in gsocks:
                with ServiceClient(s) as c:
                    c.drain()
            for p in gprocs:
                if p.wait(timeout=120) != 75:
                    return _fail("realistic_fleet_gray_member_drain")
            gray_p99 = (sorted(walls)[-1] * 1e3) if walls else 0.0
            gray_ok = (
                d1["quarantined"]
                and d1["t_detect_s"] <= 3 * (gpoll + gdelay) + 1.0
                and placed_ok and dd_ok
                and d2["recovered"]
                and d1["eligible_floor_held"]
                and d2["eligible_floor_held"]
                # p99 recovery: quarantine keeps the chaos-window
                # walls near the healthy yardstick — a placement on
                # the slow member would pay >= 2 x the proxy delay
                and walls
                and max(walls) <= 2.0 * healthy_wall + gdelay)
        except Exception as e:
            sys.stderr.write(f"gray drill leg: {e}\n")
            return _fail("realistic_fleet_graydrill")
        finally:
            if gproxy is not None:
                gproxy.stop()
            for p in gprocs + ([grouter] if grouter else []):
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_fleet_graydrill_p99_ms", gray_p99, "ms",
              1.0 if gray_ok else 0.0, cpu_metric=True)

        # --- brownout shed floor (ISSUE 18): one member behind the
        # router, both sides configured --priority-lanes=rt,bulk.  A
        # deep slow backlog sustains fleet_queue_pressure past its
        # for_s, the shed controller browns out the lowest tier, and
        # the gate checks the whole contract: bulk refused with a
        # truthful overloaded + retry_after_s (no member asked), rt
        # still admitted and byte-identical, level back to 0 once the
        # backlog drains (hysteresis), nothing wedged.
        shsock0 = os.path.join(d, "shd0.sock")
        shm = subprocess.Popen(
            cmd + ["serve", f"--socket={shsock0}", "--max-queue=32",
                   "--priority-lanes=rt,bulk"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE)
        shrouter = None
        shrsock = os.path.join(d, "shed.sock")
        shed_ok = False
        try:
            if not wait_for_socket(shsock0, 120):
                return _fail("realistic_fleet_shed_up")
            shrouter = subprocess.Popen(
                cmd + ["route", f"--backends={shsock0}",
                       f"--socket={shrsock}", "--poll-interval=0.2",
                       "--priority-lanes=rt,bulk"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE)
            if not wait_for_socket(shrsock, 120):
                return _fail("realistic_fleet_shed_router_up")
            with ServiceClient(shrsock, trace_id="bench-shed") as c:
                backlog = []
                for k in range(16):
                    s0 = c.submit(args(f"shb{k}", [slow]),
                                  priority="bulk")
                    if not s0.get("ok"):
                        return _fail("realistic_fleet_shed_submit")
                    backlog.append(s0["job_id"])
                deadline = time.monotonic() + 60
                level = 0
                while time.monotonic() < deadline:
                    sh = (c.stats()["stats"].get("ha")
                          or {}).get("shed") or {}
                    level = sh.get("level", 0)
                    if level >= 1:
                        break
                    time.sleep(0.1)
                if level < 1:
                    return _fail("realistic_fleet_shed_fire")
                bulk = c.submit(args("shx", []), priority="bulk")
                rt = c.submit(args("shr", []), priority="rt")
                shed_truthful = (
                    not bulk.get("ok")
                    and bulk.get("error") == "overloaded"
                    and float(bulk.get("retry_after_s") or 0) > 0
                    and bulk.get("lane") == "bulk")
                if not rt.get("ok"):
                    return _fail("realistic_fleet_shed_rt")
                for jid in backlog + [rt["job_id"]]:
                    if c.result(jid, timeout=600).get("rc") != 0:
                        return _fail("realistic_fleet_shed_backlog")
                deadline = time.monotonic() + 60
                sh = {}
                while time.monotonic() < deadline:
                    sh = (c.stats()["stats"].get("ha")
                          or {}).get("shed") or {}
                    if not sh.get("level"):
                        break
                    time.sleep(0.1)
                shed_ok = (shed_truthful and not sh.get("level")
                           and readset("shr") == parity_body)
                c.drain()
            if shrouter.wait(timeout=120) != 0:
                return _fail("realistic_fleet_shed_router_drain")
            with ServiceClient(shsock0) as c:
                c.drain()
            if shm.wait(timeout=120) != 75:
                return _fail("realistic_fleet_shed_member_drain")
        except Exception as e:
            sys.stderr.write(f"shed leg: {e}\n")
            return _fail("realistic_fleet_shed_floor")
        finally:
            for p in [shm, shrouter]:
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_fleet_shed_floor", 1 if shed_ok else 0,
              "bool", 1.0 if shed_ok else 0.0, cpu_metric=True)

        # --- TLS overhead (ISSUE 19 tentpole): the SAME job through
        # an ALL-TLS 3-member fleet (client->router over TLS,
        # router->member over mTLS with client certs) vs an all-
        # plaintext fleet on the SAME TCP topology, so the ratio
        # isolates encryption, not unix-vs-TCP.  Bytes must stay
        # identical and the submit->result wall ratio is gated
        # <= 1.15 as a bool leg (interleaved arms + min-of-mins,
        # same noise stance as the obs-overhead leg): a security
        # layer costing more than 15% would get turned off exactly
        # on the fleets that need it.
        import socket as _socket
        from pwasm_tpu.fleet.transport import ClientTLS

        def _port():
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        certs = os.path.join(repo, "tests", "certs")
        tca = os.path.join(certs, "ca.pem")
        tcrt = os.path.join(certs, "server.pem")
        tkey = os.path.join(certs, "server.key")
        acrt = os.path.join(certs, "fleet-admin.pem")
        akey = os.path.join(certs, "fleet-admin.key")
        tls_procs: list = []
        tls_ok = False
        tls_ratio = 0.0
        try:
            fleets = {}
            for arm in ("tls", "plain"):
                mports = [_port() for _ in range(3)]
                rport = _port()
                mflags = ([f"--tls-cert={tcrt}", f"--tls-key={tkey}",
                           f"--tls-client-ca={tca}"]
                          if arm == "tls" else [])
                for k, mp in enumerate(mports):
                    tls_procs.append(subprocess.Popen(
                        cmd + ["serve",
                               f"--socket={os.path.join(d, f'{arm}{k}.sock')}",
                               f"--listen=127.0.0.1:{mp}",
                               "--max-queue=16"] + mflags,
                        env=env, stdout=subprocess.DEVNULL,
                        stderr=subprocess.PIPE))
                rflags = ([f"--tls-cert={tcrt}", f"--tls-key={tkey}",
                           f"--member-tls-ca={tca}",
                           f"--member-tls-cert={acrt}",
                           f"--member-tls-key={akey}"]
                          if arm == "tls" else [])
                rs = os.path.join(d, f"{arm}r.sock")
                tls_procs.append(subprocess.Popen(
                    cmd + ["route",
                           "--backends=" + ",".join(
                               f"127.0.0.1:{mp}" for mp in mports),
                           f"--socket={rs}",
                           f"--listen=127.0.0.1:{rport}",
                           "--poll-interval=0.2"] + rflags,
                    env=env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.PIPE))
                fleets[arm] = (rs, rport)
            for arm in ("tls", "plain"):
                if not wait_for_socket(fleets[arm][0], 120):
                    return _fail("realistic_tls_fleet_up")

            def tls_once(arm, tag, settle_s=0.0):
                rs, rport = fleets[arm]
                ctls = ClientTLS(tca) if arm == "tls" else None
                t0 = time.perf_counter()
                with ServiceClient(f"127.0.0.1:{rport}",
                                   tls=ctls) as c:
                    deadline = time.monotonic() + settle_s
                    while True:
                        s0 = c.submit(args(tag, []))
                        if s0.get("ok"):
                            break
                        # TCP members need a first health-poll round
                        # before the router will place — honor the
                        # truthful retry hint during the prime only
                        if (s0.get("error") != "queue_full"
                                or time.monotonic() > deadline):
                            return None
                        time.sleep(min(0.5,
                                       s0.get("retry_after_s", 0.5)))
                        t0 = time.perf_counter()
                    if c.result(s0["job_id"],
                                timeout=600).get("rc") != 0:
                        return None
                return time.perf_counter() - t0
            # prime both arms (first placement pays member discovery)
            for arm in ("tls", "plain"):
                if tls_once(arm, f"{arm}p", settle_s=30.0) is None:
                    return _fail("realistic_tls_overhead")
            tls_walls, plain_walls = [], []
            for i in range(8):       # interleaved arms; sub-second
                # fleet walls are noisy at the +-30% level, so the
                # min-of-mins needs a deeper sample pool than the
                # longer-walled legs above
                w = tls_once("tls", f"tlsw{i}")
                if w is None:
                    return _fail("realistic_tls_overhead")
                tls_walls.append(w)
                w = tls_once("plain", f"plnw{i}")
                if w is None:
                    return _fail("realistic_tls_overhead")
                plain_walls.append(w)
            if (readset("tlsw0") != parity_body
                    or readset("plnw0") != parity_body):
                return _fail("realistic_tls_parity")
            for arm in ("tls", "plain"):
                ctls = ClientTLS(tca) if arm == "tls" else None
                with ServiceClient(f"127.0.0.1:{fleets[arm][1]}",
                                   tls=ctls) as c:
                    c.drain()
            tls_ratio = min(tls_walls) / min(plain_walls)
            tls_ok = tls_ratio <= 1.15
        except Exception as e:
            sys.stderr.write(f"tls leg: {e}\n")
            return _fail("realistic_tls_overhead")
        finally:
            for p in tls_procs:
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_tls_overhead_ratio", tls_ratio, "x",
              1.0 if tls_ok else 0.0, cpu_metric=True)
        # the <= 1.15 ceiling as a BOOL leg, same rationale as
        # realistic_obs_overhead_ok: "x" only gates the committed
        # trajectory, the bool fails the flip past the ceiling
        _emit("realistic_tls_overhead_ok", 1 if tls_ok else 0,
              "bool", 1.0 if tls_ok else 0.0, cpu_metric=True)

        # --- continuous surveillance m2m (ISSUE 20): two legs.
        # (1) incremental: a --result-cache primed with 12 targets,
        # then re-run with 4 MORE arrivals, must re-score ONLY the
        # arrivals (targets_reused/targets_scored counters gated)
        # and undercut the cache-off full re-run wall — the
        # arriving-target economics the subsystem exists for;
        # (2) fleet: the same target stream scattered across a
        # 3-member fleet with one member SIGKILLed mid-stream must
        # merge to bytes identical to one un-scattered run
        # (failovers == 1 — the invisible re-partition drill).
        import json as _json
        import random as _random
        import shutil as _shutil

        srng = _random.Random(20)
        sres = [("srv_cds0", qseq[:600]),
                ("srv_cds1", qseq[500:1100])]

        def _starget(i):
            core = list(sres[i % 2][1] * 6)
            for k in range(0, len(core), 17):    # ~6% noise
                core[k] = srng.choice("ACGT")
            pad = "".join(srng.choice("ACGT") for _ in range(200))
            return f"srv_t{i}", pad + "".join(core) + pad

        # 360 resident targets + 40 arrivals: deep enough that the
        # 800-pair full re-score dominates interpreter startup, so
        # the ratio measures splice-vs-rescore, not process spawn
        stargets = [_starget(i) for i in range(400)]
        sq_fa = os.path.join(d, "srv_q.fa")
        with open(sq_fa, "w") as f:
            for n, s in sres:
                f.write(f">{n}\n{s}\n")
        st360 = os.path.join(d, "srv_t360.fa")
        st400 = os.path.join(d, "srv_t400.fa")
        with open(st360, "w") as f:
            for n, s in stargets[:360]:
                f.write(f">{n}\n{s}\n")
        with open(st400, "w") as f:
            for n, s in stargets:
                f.write(f">{n}\n{s}\n")
        src0 = os.path.join(d, "srv_rc")

        def m2m_run(tag, tfa_p, cache_dir):
            o = os.path.join(d, f"{tag}.tsv")
            s = os.path.join(d, f"{tag}.sum")
            stt = os.path.join(d, f"{tag}.stats")
            argv = cmd + ["--m2m-stream", tfa_p, "-r", sq_fa,
                          "-o", o, "-s", s, f"--stats={stt}"]
            if cache_dir:
                argv.append(f"--result-cache={cache_dir}")
            t0 = time.perf_counter()
            r = subprocess.run(argv, env=env, capture_output=True)
            w = time.perf_counter() - t0
            if r.returncode != 0:
                sys.stderr.write(r.stderr.decode()[:800])
                return None
            with open(stt) as f:
                m2m = _json.load(f).get("m2m", {})
            return w, open(o, "rb").read(), open(s, "rb").read(), m2m

        prime = m2m_run("srv_prime", st360, src0)
        if prime is None or prime[3].get("targets_in") != 360 \
                or prime[3].get("pairs_reused"):
            return _fail("realistic_surveil_prime")
        inc_w = full_w = None
        full = None
        inc_ok = True
        for i in range(3):      # interleaved arms, min-of-mins; each
            # round replays arrival against a COPY of the primed
            # store (the first incremental run would otherwise cache
            # the arrivals and turn later rounds into all-reuse)
            srci = os.path.join(d, f"srv_rc{i}")
            _shutil.copytree(src0, srci)
            inc = m2m_run(f"srv_inc{i}", st400, srci)
            full = m2m_run(f"srv_full{i}", st400, None)
            if inc is None or full is None:
                return _fail("realistic_surveil_incremental")
            # the counter gate: the incremental arm dispatches ONLY
            # the 40 arrivals' pairs (40 x 2 residents) and splices
            # the primed 720; the full arm re-dispatches all 800
            inc_ok = (inc[3].get("targets_reused") == 360
                      and inc[3].get("pairs_dispatched") == 80
                      and inc[3].get("pairs_reused") == 720
                      and full[3].get("pairs_dispatched") == 800
                      and inc[1:3] == full[1:3])
            if not inc_ok:
                break
            inc_w = inc[0] if inc_w is None else min(inc_w, inc[0])
            full_w = full[0] if full_w is None \
                else min(full_w, full[0])
        if not inc_ok:
            return _fail("realistic_surveil_incremental")
        _emit("realistic_surveil_incremental_ratio",
              inc_w / full_w, "x", 1.0, cpu_metric=True)

        sfl_ok = False
        sprocs: list = []
        try:
            ssocks = [os.path.join(d, f"srv{k}.sock")
                      for k in range(3)]
            for s in ssocks:
                sprocs.append(subprocess.Popen(
                    cmd + ["serve", f"--socket={s}",
                           "--max-queue=16"],
                    env=env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.PIPE))
            for s in ssocks:
                if not wait_for_socket(s, 120):
                    return _fail("realistic_surveil_fleet_up")
            srsock = os.path.join(d, "srvr.sock")
            sprocs.append(subprocess.Popen(
                cmd + ["route", "--backends=" + ",".join(ssocks),
                       f"--socket={srsock}", "--poll-interval=0.2"],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE))
            if not wait_for_socket(srsock, 120):
                return _fail("realistic_surveil_router_up")
            sfo = os.path.join(d, "srv_fleet.tsv")
            sfs = os.path.join(d, "srv_fleet.sum")
            recs = [f">{n}\n{s}\n" for n, s in stargets]
            frames = ["".join(recs[k:k + 25])
                      for k in range(0, len(recs), 25)]
            with ServiceClient(srsock) as c:
                deadline = time.monotonic() + 30.0
                while True:
                    r0 = c.stream_open(
                        ["--m2m-stream", "-r", sq_fa, "-o", sfo,
                         "-s", sfs], cwd=d)
                    if r0.get("ok"):
                        break
                    # members need one health-poll round before the
                    # router will scatter — honor the retry hint
                    if (r0.get("error") != "queue_full"
                            or time.monotonic() > deadline):
                        return _fail("realistic_surveil_fleet_open")
                    time.sleep(min(0.5,
                                   r0.get("retry_after_s", 0.5)))
                if not r0.get("scatter"):
                    return _fail("realistic_surveil_fleet_scatter")
                jid = r0["job_id"]
                for t in frames[:8]:
                    if not c.stream_data(jid, t).get("ok"):
                        return _fail("realistic_surveil_fleet_feed")
                # SIGKILL the member hosting sub-stream 0 (also the
                # ledger anchor) mid-stream: the router must
                # re-partition its buffered records invisibly
                victim = r0["scatter"][0]
                vi = [i for i, s in enumerate(ssocks)
                      if os.path.basename(s) == victim][0]
                sprocs[vi].kill()
                sprocs[vi].wait(timeout=60)
                for t in frames[8:]:
                    if not c.stream_data(jid, t).get("ok"):
                        return _fail("realistic_surveil_fleet_feed")
                if not c.stream_end(jid).get("ok"):
                    return _fail("realistic_surveil_fleet_end")
                rr = c.result(jid, timeout=600)
                sstats = (rr.get("stats") or {}).get("scatter", {})
                c.drain()
            sfl_ok = (rr.get("rc") == 0
                      and sstats.get("failovers") == 1
                      and open(sfo, "rb").read() == full[1]
                      and open(sfs, "rb").read() == full[2])
        except Exception as e:
            sys.stderr.write(f"surveil fleet leg: {e}\n")
            return _fail("realistic_surveil_fleet_parity")
        finally:
            for p in sprocs:
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait()
        _emit("realistic_surveil_fleet_parity", 1 if sfl_ok else 0,
              "bool", 1.0 if sfl_ok else 0.0, cpu_metric=True)

        if on_tpu_backend():
            dev_env = dict(os.environ, PYTHONPATH=env["PYTHONPATH"])
            dev_times = []
            for _ in range(2):     # cold + warm(compile-cache) sample
                t0 = time.perf_counter()
                r = subprocess.run(cmd + args("dev", ["--device=tpu"]),
                                   env=dev_env, capture_output=True)
                dev_times.append(time.perf_counter() - t0)
                if r.returncode != 0:
                    sys.stderr.write(r.stderr.decode()[:1000])
                    return _fail("realistic_device")
            if readset("dev") != parity_body:
                return _fail("realistic_device_parity")
            # no toolchain -> no native reference wall: vs_baseline 0
            # marks "unreferenced", like the other no-baseline configs
            return _emit("realistic_device_wall_s", min(dev_times),
                         "s", min(nat_times) / min(dev_times)
                         if nat_times else 0.0)
    return 0


CONFIGS = {"1": cfg1_cli_cpu_ref, "2": cfg2_batched_dp,
           "3": cfg3_many2many, "4": cfg4_consensus,
           "5": cfg5_longread, "6": cfg6_realign,
           "7": cfg7_refine_clip, "8": cfg8_realistic_scale}

# all-mode run order: headline config 2 LAST, so a driver that records
# only the final stdout line still gets the metric comparable with
# earlier rounds' single-config captures
_ALL_ORDER = ["1", "3", "4", "5", "6", "7", "8", "2"]


def _run_all() -> int:
    """Run the Pallas lowering smoke, then every config in its own
    bounded subprocess; stream each JSON line through and write the
    aggregate table to BENCH_ALL.json (smoke row first) plus the smoke's
    own line to TPU_SMOKE.json — the per-round lowering-gate artifact."""
    import subprocess

    repo = os.path.dirname(os.path.abspath(__file__))
    try:
        child_t = float(os.environ.get("PWASM_BENCH_WATCHDOG", "1800"))
    except ValueError:
        child_t = 1800.0
    table = []
    rc = 0
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "tpu_smoke.py")],
            env=dict(os.environ), capture_output=True, text=True,
            timeout=child_t + 120 if child_t > 0 else None)
        smoke_lines = [l for l in r.stdout.splitlines() if l.strip()]
        smoke_line = smoke_lines[-1] if smoke_lines else ""
        if r.returncode < 0 or not smoke_line:
            # killed by a signal (OOM-kill etc.) or produced nothing:
            # the smoke never got far enough to vouch for the backend —
            # treat it as down so children don't each re-discover an
            # unreachable tunnel the slow way
            raise RuntimeError(
                f"smoke produced no verdict (rc={r.returncode})")
        smoke = json.loads(smoke_line)
        with open(os.path.join(repo, "TPU_SMOKE.json"), "w") as f:
            f.write(smoke_line + "\n")
        if r.returncode != 0:
            rc = 1
    except Exception as e:
        import subprocess as _sp
        smoke = {"smoke": "pallas_lowering", "ok": False,
                 # a smoke TIMEOUT means the tunnel hung mid-kernels —
                 # the children would hang the same way, so pin them;
                 # a signal-killed or mute smoke (RuntimeError above, or
                 # an unparseable verdict line) likewise never proved the
                 # backend healthy; other parent-side failures say
                 # nothing about the backend and must not downgrade a
                 # healthy capture
                 "backend_down": isinstance(
                     e, (_sp.TimeoutExpired, RuntimeError,
                         json.JSONDecodeError)),
                 "error": f"{type(e).__name__}: {e}"}
        rc = 1
        try:  # never leave a stale passing artifact from a prior round
            with open(os.path.join(repo, "TPU_SMOKE.json"), "w") as f:
                json.dump(smoke, f)
                f.write("\n")
        except OSError:
            pass
    row = {"metric": "pallas_lowering_ok",
           "value": 1 if smoke.get("ok") else 0, "unit": "bool",
           "vs_baseline": 0, "config": 0}
    print(json.dumps(row), flush=True)
    table.append(row)
    # the smoke already probed the backend (bounded, two attempts); if
    # it proved the tunnel unreachable — the structured backend_down
    # flag, set by tpu_smoke's probe or by a smoke timeout above —
    # pre-pin every config child to CPU so they don't each spend ~5
    # minutes (or a 30-minute hang) re-discovering that
    backend_down = bool(smoke.get("backend_down"))
    if backend_down:
        print("[bench] backend unreachable; pre-pinning configs to cpu",
              file=sys.stderr)
    for cfg in _ALL_ORDER:
        env = dict(os.environ, PWASM_BENCH_CONFIG=cfg)
        # profiling is a single-config affair (PWASM_BENCH_CONFIG=k);
        # a run-all must not dump one overlapping trace per child
        env.pop("PWASM_BENCH_PROFILE", None)
        if backend_down:
            _cpu_pin_env(env)
        rows = []
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True,
                timeout=child_t + 120 if child_t > 0 else None)
            sys.stderr.write(r.stderr[-4000:])
            # a config may emit several metric lines (e.g. config 1's
            # native reference + Python-CLI secondary); keep them all,
            # last line remains the config's primary metric
            rows = _json_rows(r.stdout)
            if r.returncode != 0:  # a failed gate still exits nonzero
                rc = 1
        except subprocess.TimeoutExpired:
            rows = []
        if not rows:
            rows = [{"metric": f"bench_config_{cfg}_no_output", "value": 0,
                     "unit": "bool", "vs_baseline": 0}]
            rc = 1
        for row in rows:
            row["config"] = int(cfg)
            print(json.dumps(row), flush=True)
            table.append(row)
    with open(os.path.join(repo, "BENCH_ALL.json"), "w") as f:
        json.dump(table, f, indent=1)
        f.write("\n")
    return rc


def main() -> int:
    cfg = os.environ.get("PWASM_BENCH_CONFIG", "all")
    if cfg in ("", "all"):
        return _run_all()
    if cfg not in CONFIGS:
        return _fail(f"unknown_bench_config_{cfg}")
    _arm_watchdog()
    try:
        if cfg != "1":  # config 1 is the subprocess CPU reference
            _resolve_backend()
            from pwasm_tpu.ops import (enable_compilation_cache,
                                       on_tpu_backend)
            # persist compiles across configs/rounds: a scarce healthy-
            # tunnel window must measure kernels, not rebuild them
            # (timing is unaffected — rates are post-warmup)
            enable_compilation_cache()
            if not on_tpu_backend():
                # a host-CPU rate must never be recorded as a chip rate:
                # rename the metric so benchmark history stays clean
                global _METRIC_PREFIX
                _METRIC_PREFIX = "cpu_fallback_"
                _scale_for_fallback(cfg)
        return CONFIGS[cfg]()
    except SystemExit:
        raise
    except BaseException as e:  # the one JSON line must ALWAYS print
        import traceback
        traceback.print_exc()
        return _fail(f"bench_error_{type(e).__name__}")


if __name__ == "__main__":
    sys.exit(main())
